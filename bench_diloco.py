"""Streaming semi-sync (DiLoCo) benchmarks: sync/compute overlap + wire
quantization, written as one JSON artifact (``DILOCO_BENCH.json``).

Two sections:

  overlap  — 2 full replica groups (real lighthouse + Managers, threads)
             on a shaped high-RTT link (``TPUFT_SHAPED_LINK``, default
             60 ms RTT — the cross-region scenario torchft targets with
             LocalSGD).  The inner step is a fixed-duration stand-in for
             device compute (the host sleeps — exactly the TPU shape,
             where inner steps leave the host idle), so the measurement
             isolates what the SYNC path costs the train thread.  Three
             cells over identical inner work:

               nosync     inner steps only — the throughput ceiling
               blocking   the legacy port shape (DiLoCo wrapper:
                          stream=False — whole-round stall at the sync
                          boundary)
               streaming  StreamingDiLoCo (background fragment rounds,
                          int8+EF wire)

             Headline: streaming inner-step throughput within 5% of
             nosync while an outer sync is in flight, with the blocking
             port's per-round stall measured alongside.

  quant    — codec drift cell, no network: G simulated groups run R outer
             rounds through each wire codec (f32 reference / bf16 /
             int8+EF / int8 without EF) with the SAME pseudogradient
             stream and outer optimizer; reports each codec's final
             outer-param drift vs the f32 reference, that error feedback
             bounds the drift plain int8 accumulates, and the int8 wire's
             byte ratio (<= 0.27x f32, from the collective's own
             wire_nbytes probe).

Run as
  python bench_diloco.py [--rounds 6] [--sync-every 8] [--inner-ms 40]
                         [--model-mb 2.0] [--mbps 200] [--rtt-ms 60]
                         [--out DILOCO_BENCH.json]
  python bench_diloco.py --quick     # tier-1 smoke (small, fast)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from datetime import timedelta
from typing import Any, Dict, List, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
try:
    # One implementation of the TPUFT_SHAPED_LINK set/restore contract —
    # the two benches must shape links identically.
    from bench_allreduce import _shaped
finally:
    sys.path.pop(0)


def _param_tree(total_bytes: int, n_leaves: int = 8) -> Dict[str, Any]:
    import jax.numpy as jnp

    per = max(1, total_bytes // n_leaves // 4)
    return {
        f"layer_{i}": jnp.full((per,), 0.1 * (i + 1), dtype=jnp.float32)
        for i in range(n_leaves)
    }


# ---------------------------------------------------------------------------
# Section 1: sync/compute overlap
# ---------------------------------------------------------------------------


def _inner_update(params: Dict[str, Any], scale: float) -> Dict[str, Any]:
    import jax

    return jax.tree.map(lambda p: p - np.float32(1e-4 * scale) * p, params)


def _nosync_cell(
    rounds: int, sync_every: int, inner_s: float, nbytes: int
) -> Dict[str, Any]:
    """The throughput ceiling: identical inner work, no sync at all."""
    params = _param_tree(nbytes)
    import jax

    jax.block_until_ready(_inner_update(params, 1.0))  # warm the jit
    steps = rounds * sync_every
    t0 = time.perf_counter()
    walls: List[float] = []
    for s in range(steps):
        ts = time.perf_counter()
        time.sleep(inner_s)
        params = _inner_update(params, float(s))
        walls.append(time.perf_counter() - ts)
    wall = time.perf_counter() - t0
    return {
        "mode": "nosync",
        "steps": steps,
        "committed_rounds": rounds,
        "wall_s": round(wall, 4),
        "inner_steps_per_s": round(steps / wall, 4),
        "inner_step_p50_ms": round(float(np.median(walls)) * 1e3, 3),
        "boundary_stall_ms": 0.0,
        "wire_bytes": 0,
    }


def _sync_group_body(
    lighthouse_addr: str,
    gid: int,
    mode: str,
    rounds: int,
    sync_every: int,
    inner_s: float,
    nbytes: int,
    fragment_bytes: int,
    codec: str,
    timeout_s: float,
) -> Dict[str, Any]:
    """One replica group's synthetic DiLoCo loop — shared by the blocking
    and streaming cells (the only difference is the engine mode)."""
    import optax

    from torchft_tpu.collectives import TCPCollective
    from torchft_tpu.manager import Manager
    from torchft_tpu.semisync import StreamingDiLoCo

    state = {"p": _param_tree(nbytes)}
    collective = TCPCollective(timeout=timeout_s)
    manager = Manager(
        collective=collective,
        load_state_dict=None,
        state_dict=None,
        min_replica_size=2,
        use_async_quorum=False,
        timeout=timedelta(seconds=timeout_s),
        quorum_timeout=timedelta(seconds=timeout_s),
        rank=0,
        world_size=1,
        replica_id=f"d{gid}",
        lighthouse_addr=lighthouse_addr,
        init_sync=False,  # groups start identical
    )
    algo = StreamingDiLoCo(
        manager,
        lambda: state["p"],
        lambda p: state.update(p=p),
        outer_tx=optax.sgd(0.7, momentum=0.9, nesterov=True),
        sync_every=sync_every,
        fragment_bytes=fragment_bytes,
        codec=codec,
        stream=(mode == "streaming"),
    )
    try:
        with algo:
            import jax

            jax.block_until_ready(_inner_update(state["p"], 1.0))
            # Warmup round outside the timed window: lighthouse join,
            # collective rendezvous, and codec jit compilation are startup,
            # not steady-state overlap.
            for _ in range(sync_every):
                state["p"] = _inner_update(state["p"], 1.0)
                algo.step()
            committed0 = manager.current_step()
            walls: List[float] = []
            boundary: List[bool] = []
            t0 = time.perf_counter()
            for r in range(rounds):
                for inner in range(sync_every):
                    ts = time.perf_counter()
                    time.sleep(inner_s)
                    state["p"] = _inner_update(state["p"], float(r + inner))
                    algo.step()
                    walls.append(time.perf_counter() - ts)
                    boundary.append(inner == sync_every - 1)
            wall = time.perf_counter() - t0
            steps = rounds * sync_every
            inner_walls = [w for w, b in zip(walls, boundary) if not b]
            boundary_walls = [w for w, b in zip(walls, boundary) if b]
            stall_ms = max(
                0.0,
                (float(np.mean(boundary_walls)) - float(np.mean(inner_walls)))
                * 1e3,
            )
            return {
                "mode": mode,
                "steps": steps,
                "committed_rounds": manager.current_step() - committed0,
                "wall_s": round(wall, 4),
                "inner_steps_per_s": round(steps / wall, 4),
                "inner_step_p50_ms": round(float(np.median(walls)) * 1e3, 3),
                # The boundary stall: what the final step of a round pays
                # over a mid-round step — the whole sync for the blocking
                # port, just the residual drain for streaming.
                "boundary_stall_ms": round(stall_ms, 3),
                "fragments": algo.num_fragments,
                "fragment_rounds": algo.metrics.fragments_total,
                "wire_bytes": algo.metrics.wire_bytes_total,
                "codec": algo.codec_name,
            }
    finally:
        manager.shutdown()


def _sync_cell(
    mode: str,
    rounds: int,
    sync_every: int,
    inner_s: float,
    nbytes: int,
    fragment_bytes: int,
    codec: str,
    timeout_s: float = 60.0,
) -> Dict[str, Any]:
    from torchft_tpu._native import LighthouseServer

    lighthouse = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=2, join_timeout_ms=5000,
        quorum_tick_ms=20,
    )
    results: Dict[int, dict] = {}
    errors: List[BaseException] = []
    try:
        def group(gid: int) -> None:
            try:
                results[gid] = _sync_group_body(
                    lighthouse.address(), gid, mode, rounds, sync_every,
                    inner_s, nbytes, fragment_bytes, codec, timeout_s,
                )
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)

        threads = [threading.Thread(target=group, args=(g,)) for g in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        lighthouse.shutdown()
    if errors:
        raise errors[0]
    # Slowest group's view (the cluster paces on it); byte counters from
    # group 0 (groups are symmetric).
    slow = max(results.values(), key=lambda r: r["wall_s"])
    out = dict(results[0])
    out["wall_s"] = slow["wall_s"]
    out["inner_steps_per_s"] = round(out["steps"] / slow["wall_s"], 4)
    return out


def bench_overlap(
    rounds: int,
    sync_every: int,
    inner_ms: float,
    model_mb: float,
    fragment_kb: int,
    mbps: float,
    rtt_ms: float,
    codec: str = "int8",
    timeout_s: float = 60.0,
    trials: int = 1,
) -> Dict[str, Any]:
    """``trials`` > 1 keeps each cell's BEST (fastest-wall) trial — the
    same scheduler-noise rationale as bench_allreduce: the modeled link is
    deterministic, but a 1-core CI host context-switching a dozen bench
    threads can lose 30%+ to an unlucky schedule, far more than the
    overlap effect being measured."""
    nbytes = int(model_mb * (1 << 20))
    inner_s = inner_ms / 1e3

    def best(fn):
        out = None
        for _ in range(max(1, trials)):
            attempt = fn()
            if out is None or attempt["wall_s"] < out["wall_s"]:
                out = attempt
        return out

    with _shaped(mbps, rtt_ms):
        nosync = best(lambda: _nosync_cell(rounds, sync_every, inner_s, nbytes))
        blocking = best(lambda: _sync_cell(
            "blocking", rounds, sync_every, inner_s, nbytes,
            fragment_kb << 10, codec, timeout_s,
        ))
        streaming = best(lambda: _sync_cell(
            "streaming", rounds, sync_every, inner_s, nbytes,
            fragment_kb << 10, codec, timeout_s,
        ))
    ratio_stream = streaming["inner_steps_per_s"] / nosync["inner_steps_per_s"]
    ratio_block = blocking["inner_steps_per_s"] / nosync["inner_steps_per_s"]
    return {
        "section": "overlap",
        "link": {"mbps": mbps, "rtt_ms": rtt_ms},
        "model_mb": model_mb,
        "sync_every": sync_every,
        "rounds": rounds,
        "inner_ms": inner_ms,
        "fragment_kb": fragment_kb,
        "codec": codec,
        "cells": {"nosync": nosync, "blocking": blocking,
                  "streaming": streaming},
        "inner_throughput_ratio_streaming_vs_nosync": round(ratio_stream, 4),
        "inner_throughput_ratio_blocking_vs_nosync": round(ratio_block, 4),
        "streaming_within_5pct": ratio_stream >= 0.95,
        "streaming_beats_blocking": (
            streaming["inner_steps_per_s"] >= blocking["inner_steps_per_s"]
        ),
        "blocking_stall_ms_per_round": blocking["boundary_stall_ms"],
        "streaming_stall_ms_per_round": streaming["boundary_stall_ms"],
    }


# ---------------------------------------------------------------------------
# Section 2: quantization error vs convergence (codec drift cell)
# ---------------------------------------------------------------------------


def bench_quant(
    rounds: int = 40, groups: int = 4, n: int = 65536, seed: int = 0
) -> Dict[str, Any]:
    """G simulated groups push the same pseudogradient stream through each
    codec for R outer rounds (identical outer SGD+Nesterov); reports final
    outer-param drift vs the f32 reference and the int8 wire ratio."""
    import ml_dtypes
    import optax

    from torchft_tpu.collectives import (
        TCPCollective,
        quantize_int4,
        quantize_int8,
    )
    from torchft_tpu.ddp import plan_buckets
    from torchft_tpu.semisync.codec import make_codec
    from torchft_tpu.semisync.fragments import Fragment

    outer_tx = optax.sgd(0.7, momentum=0.9, nesterov=True)

    def simulate(codec_name: str) -> np.ndarray:
        rng = np.random.default_rng(seed)
        backup = np.full(n, 0.1, dtype=np.float32)
        outer_state = outer_tx.init(backup)
        frag = Fragment(0, plan_buckets([((n,), np.float32)], 1 << 30)[0])
        ef_name = codec_name[:4] if codec_name.startswith("int") else None
        codecs = [
            make_codec(ef_name, frag)
            if codec_name in ("int8", "int8_noef", "int4", "int4_noef")
            else None
            for _ in range(groups)
        ]
        for c in codecs:
            if c is not None:
                c.set_backup(backup)
        for _r in range(rounds):
            decs = []
            for g in range(groups):
                # Biased low-magnitude walks — the adversarial stream for
                # plain int8 (small values round to zero every round).
                pg = (
                    0.01 * rng.standard_normal(n) + 0.002 * (g + 1)
                ).astype(np.float32)
                if codec_name == "f32":
                    decs.append(pg)
                elif codec_name == "bf16":
                    decs.append(
                        pg.astype(ml_dtypes.bfloat16).astype(np.float32)
                    )
                elif codec_name in ("int8", "int4"):
                    local = backup - pg
                    deq, _ = codecs[g].encode([local])
                    codecs[g].on_commit()
                    decs.append(deq)
                else:  # *_noef: the SAME quantizer, residual discarded
                    qfn = (
                        quantize_int8 if codec_name == "int8_noef"
                        else quantize_int4
                    )
                    scale, q = qfn(pg)
                    decs.append(q.astype(np.float32) * np.float32(scale))
            averaged = np.mean(decs, axis=0, dtype=np.float64).astype(
                np.float32
            )
            updates, outer_state = outer_tx.update(
                averaged, outer_state, backup
            )
            backup = np.asarray(optax.apply_updates(backup, updates))
            for c in codecs:
                if c is not None:
                    c.set_backup(backup)
        return backup

    ref = simulate("f32")
    drift: Dict[str, float] = {}
    for name in ("bf16", "int8", "int8_noef"):
        out = simulate(name)
        drift[name] = float(
            np.linalg.norm(out - ref) / max(1e-12, np.linalg.norm(ref))
        )
    # int4 lands in its OWN keys: drift_vs_f32's key set is a pinned
    # contract (tests/test_bench_contract.py) that downstream dashboards
    # key on, so the 4-bit cell extends the record without mutating it.
    drift4: Dict[str, float] = {}
    for name in ("int4", "int4_noef"):
        out = simulate(name)
        drift4[name] = float(
            np.linalg.norm(out - ref) / max(1e-12, np.linalg.norm(ref))
        )
    probe = TCPCollective(timeout=1.0, wire_dtype="f32")
    x = np.zeros(n, dtype=np.float32)
    wire_ratio = probe.wire_nbytes(x, True, "int8") / x.nbytes
    wire_ratio4 = probe.wire_nbytes(x, True, "int4") / x.nbytes
    probe.shutdown()
    return {
        "section": "quant",
        "rounds": rounds,
        "groups": groups,
        "numel": n,
        "drift_vs_f32": {k: round(v, 6) for k, v in drift.items()},
        # Error feedback is what licenses the lossy wire: it must bound the
        # drift plain int8 accumulates.
        "ef_bounds_drift": drift["int8"] < drift["int8_noef"],
        "wire_ratio_int8": round(wire_ratio, 4),
        "wire_ratio_ok": wire_ratio <= 0.27,
        "int4_drift_vs_f32": {k: round(v, 6) for k, v in drift4.items()},
        "int4_ef_bounds_drift": drift4["int4"] < drift4["int4_noef"],
        # EF's steady-state drift is set by the FINAL round's quantization
        # step (the one residual never delivered), so the best any
        # step-faithful 4-bit codec can do vs int8 is the step ratio
        # itself, 127/7 ~ 18.1x — measured ~18.7x here, i.e. EF holds
        # int4 exactly at its floor with no accumulation blowup.  The
        # gate pins that floor (ratio <= 21, the step ratio + margin);
        # a tighter band (e.g. 10x) is structurally unreachable for the
        # per-chunk-amax scheme both engines' wire parity is pinned to.
        "int4_drift_vs_int8_ratio": round(
            drift4["int4"] / max(1e-12, drift["int8"]), 2
        ),
        "int4_drift_at_step_ratio_floor": (
            drift4["int4"] <= 21.0 * drift["int8"]
        ),
        "wire_ratio_int4": round(wire_ratio4, 4),
        "wire_ratio_int4_ok": wire_ratio4 <= 0.14,
    }


# ---------------------------------------------------------------------------
# Drivers
# ---------------------------------------------------------------------------


def _assemble(overlap: Dict[str, Any], quant: Dict[str, Any],
              quick: bool) -> Dict[str, Any]:
    return {
        "metric": "diloco_overlap",
        "quick": quick,
        "overlap": overlap,
        "quant": quant,
        # The artifact's acceptance gate; quick mode relaxes the 5%
        # headline to "streaming >= blocking" (its cells are deliberately
        # tiny and a 1-core CI host's scheduler noise exceeds 5%).
        "ok": bool(
            overlap["streaming_beats_blocking"]
            and (quick or overlap["streaming_within_5pct"])
            and quant["ef_bounds_drift"]
            and quant["wire_ratio_ok"]
            and quant["int4_ef_bounds_drift"]
            and quant["int4_drift_at_step_ratio_floor"]
            and quant["wire_ratio_int4_ok"]
            and overlap["cells"]["streaming"]["committed_rounds"] > 0
            and overlap["cells"]["blocking"]["committed_rounds"] > 0
        ),
    }


def run_quick() -> Dict[str, Any]:
    """Tier-1 smoke: 2 groups, small model, shaped 60 ms-RTT link, 3 timed
    rounds per cell.  Gates: streaming inner throughput >= the blocking
    baseline with both cells committing every round, EF bounds int8 drift,
    int8 wire <= 0.27x f32.  Wired into
    tests/test_bench_contract.py::test_diloco_quick_smoke."""
    # Round overlap budget (sync_every * inner_ms = 320 ms) must exceed the
    # serialized fragment-sync time (4 fragments x ~2 shaped hops ~ 260 ms)
    # or even perfect streaming cannot hide the wire — the same sizing rule
    # docs/architecture.md states for real deployments.
    overlap = bench_overlap(
        rounds=3, sync_every=8, inner_ms=40.0, model_mb=0.25, fragment_kb=64,
        mbps=200.0, rtt_ms=60.0, timeout_s=60.0,
    )
    quant = bench_quant(rounds=20, groups=2, n=16384)
    return _assemble(overlap, quant, quick=True)


def run_full(
    rounds: int = 6,
    sync_every: int = 24,
    inner_ms: float = 50.0,
    model_mb: float = 2.0,
    fragment_kb: int = 256,
    mbps: float = 200.0,
    rtt_ms: float = 60.0,
) -> Dict[str, Any]:
    """The DILOCO_BENCH.json configuration.  Sizing: the round's overlap
    budget (sync_every * inner_ms = 1.2 s) covers the serialized fragment
    time (8 fragments x ~2 shaped 60 ms-RTT hops ~ 0.53 s) with the last
    fragment issued ~2 inner steps before the boundary, and the fixed
    per-round control cost (sync quorum + commit vote, ~25 ms) amortizes
    under the 5% headline."""
    overlap = bench_overlap(
        rounds=rounds, sync_every=sync_every, inner_ms=inner_ms,
        model_mb=model_mb, fragment_kb=fragment_kb, mbps=mbps, rtt_ms=rtt_ms,
        timeout_s=120.0, trials=3,
    )
    quant = bench_quant()
    return _assemble(overlap, quant, quick=False)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--rounds", type=int, default=6)
    parser.add_argument("--sync-every", type=int, default=24)
    parser.add_argument("--inner-ms", type=float, default=50.0)
    parser.add_argument("--model-mb", type=float, default=2.0)
    parser.add_argument("--fragment-kb", type=int, default=256)
    parser.add_argument("--mbps", type=float, default=200.0)
    parser.add_argument("--rtt-ms", type=float, default=60.0)
    parser.add_argument("--out", default="DILOCO_BENCH.json")
    args = parser.parse_args()
    if args.quick:
        payload = run_quick()
    else:
        payload = run_full(
            rounds=args.rounds, sync_every=args.sync_every,
            inner_ms=args.inner_ms, model_mb=args.model_mb,
            fragment_kb=args.fragment_kb, mbps=args.mbps, rtt_ms=args.rtt_ms,
        )
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(json.dumps({k: payload[k] for k in ("metric", "quick", "ok")}))


if __name__ == "__main__":
    main()
