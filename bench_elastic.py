"""Elastic quorum spot-market bench: constant global batch across churn.

ISSUE 20's tentpole (c) — the production story for preemptible fleets.  A
SEEDED arrival/departure trace drives a live cluster of real Manager
subprocess groups through membership churn while the elastic batch engine
(`TPUFT_ELASTIC_GLOBAL_BATCH`, ddp.ElasticBatchScaler) holds the global
batch constant: survivors take larger per-group shares when the quorum
shrinks, spares hot-admit and the share relaxes back.  Scored by the
goodput ledger's commit stream against a FIXED-SIZE ORACLE cell (same
worker, same step cost, no churn), normalized per group-second of live
capacity — so the ratio isolates exactly the cost of riding the churn.

Departures take the COOPERATIVE drain path (`lighthouse.drain`): spot
reclaim gives notice, the lighthouse excludes the leaver from the next
quorum immediately, the leaver finishes its in-flight step and exits via
`Manager.complete_drain()` — which is what makes the "zero failed survivor
commits across every transition" gate honest rather than aspirational
(SIGKILL mid-allreduce necessarily fails one survivor round; that path is
bench.py's kill scenario and the churn soak's job, not this trace's).
Arrivals are freshly spawned groups that pre-warm their runtime BEFORE
dialing the lighthouse (the launch.py spare-pool shape), then hot-admit at
the next step boundary.

What one full trace exercises, per ELASTIC_BENCH.json evidence fields:

  ring2d <-> ring crossover — `TPUFT_RING_TOPOLOGY=auto` with
      `TPUFT_RING2D_MIN_GROUPS=4`: the 4<->3 transitions cross the
      hierarchical/flat boundary in both directions (full reconfigure),
      the 3<->2 transitions stay flat (incremental lane reuse), and the
      reconfigure-mode counters in the metrics stream prove both paths ran.
  bucket-plan invalidation — workers run a real GradientAverager over a
      multi-bucket numpy tree; plans are keyed by participant count
      (ddp._plan_for), so the summary's bucket_plan_participants shows one
      plan per membership size with recurring sizes re-hitting their plan.
  EC re-shard — `TPUFT_EC_K=2` + the Manager's proactive
      `ECPlane.reshard()` on membership change: `ec_push` events with
      `reshard=true` land at transitions, not just on the encode path.
  constant global batch — every committed step_summary record carries
      `elastic_global_batch` (the Manager stamps the live plan), and the
      cell asserts it never moves while `elastic_participants` does.

Quick mode (``run_quick()``, tier-1's
tests/test_bench_contract.py::test_elastic_quick_smoke): a 3-group cell
with 3 cooperative transitions (leave/join/leave, flat-ring incremental
path), JAX-free workers (plain Manager.allreduce, no averager) for
subprocess startup speed, plus a short fixed oracle — full ELASTIC_BENCH
schema, minutes-not-hours.
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import random
import statistics
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.abspath(__file__))

# The drop-and-respawn baseline this trace's transitions are scored
# against: BENCH_r05's measured dead time per SIGKILL+respawn cycle.
DEAD_TIME_BASELINE_S = 12.4
GOODPUT_GATE = 0.85


def _fd_count() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:  # non-procfs platform: fd accounting unavailable
        return -1


# ---------------------------------------------------------------------------
# Worker: one replica group riding the elastic plan (re-entered subprocess)
# ---------------------------------------------------------------------------


def _worker_main(cfg: Dict) -> None:
    """One replica group: real Manager + lighthouse quorum + elastic batch
    plan + commit votes.  The "train step" sleeps proportional to THIS
    group's share of the constant global batch (the accumulation loop a
    real trainer would run), so wall-clock throughput honestly reflects
    the rescale: fewer groups -> bigger shares -> longer steps -> the same
    committed samples per step.  ``use_averager`` routes gradient traffic
    through a real multi-bucket GradientAverager (bucket plans keyed by
    participant count); otherwise a flat numpy payload rides
    Manager.allreduce directly (the JAX-free quick path)."""
    from datetime import timedelta

    import numpy as np

    use_averager = bool(cfg.get("use_averager"))
    if use_averager:
        # Pre-warm the runtime BEFORE dialing the lighthouse: a spare that
        # pays its JAX import inside its first lockstep step stalls every
        # survivor for the import time.  launch.py's spare pool pre-warms
        # for exactly this reason.
        import jax

        jax.numpy.zeros(1).block_until_ready()

    from torchft_tpu.checkpointing.http_transport import HTTPTransport
    from torchft_tpu.collectives import TCPCollective
    from torchft_tpu.manager import Manager

    state = {"w": np.zeros(16, dtype=np.float32)}
    manager = Manager(
        collective=TCPCollective(timeout=30.0),
        load_state_dict=lambda sd: state.update(sd),
        state_dict=lambda: dict(state),
        min_replica_size=1,
        rank=0,
        world_size=1,
        replica_id=str(cfg["group"]),
        lighthouse_addr=cfg["lighthouse"],
        quorum_timeout=timedelta(seconds=30.0),
        timeout=timedelta(seconds=30.0),
        connect_timeout=timedelta(seconds=15.0),
        checkpoint_transport=HTTPTransport(timeout=30.0),
        init_sync=False,
    )
    averager = None
    grads = None
    if use_averager:
        from torchft_tpu.ddp import GradientAverager

        # Small bucket size over a few-leaf tree -> multiple buckets, so
        # the participant-keyed plan cache is exercised for real.
        averager = GradientAverager(manager, bucket_bytes=8 << 10)
        grads = [
            np.ones(4096, dtype=np.float32),
            np.ones(2048, dtype=np.float32),
            np.ones(1024, dtype=np.float32),
        ]
    payload = np.ones(2048, dtype=np.float32)

    workdir = cfg["workdir"]
    stop_path = os.path.join(workdir, "stop")
    done_all_path = os.path.join(workdir, "done_all")
    end_cap = float(cfg["end_cap_ts"])  # hard ceiling, stop file is the norm
    per_sample_s = float(cfg.get("per_sample_s", 0.02))
    global_batch = int(os.environ.get("TPUFT_ELASTIC_GLOBAL_BATCH", "32"))
    commits = 0
    failed = 0
    samples = 0
    drained = False
    participants_seen: set = set()
    try:
        with open(os.path.join(workdir, f"ready_{cfg['group']}"), "w"):
            pass
        # Initial workers barrier on the driver's go file so the FIRST
        # quorum contains the whole starting set; arrivals see it already
        # present and proceed straight to their hot-admit join.
        go_deadline = time.time() + 180.0
        go_path = os.path.join(workdir, "go")
        while time.time() < go_deadline and not os.path.exists(go_path):
            time.sleep(0.05)
        while time.time() < end_cap and not os.path.exists(stop_path):
            try:
                manager.start_quorum()
                manager.wait_quorum()
                if manager.drain_requested():
                    # Cooperative departure: the lighthouse already
                    # excluded us from the next quorum — finish cleanly,
                    # never vote a failed commit into the stream.
                    drained = True
                    break
                plan = manager.elastic_plan() or {
                    "group_batch": max(1, global_batch // 2),
                    "global_batch": global_batch,
                }
                participants_seen.add(int(plan.get("participants", 0)))
                # The accumulation loop: this group's share of the fixed
                # global batch at a fixed per-sample cost.
                time.sleep(per_sample_s * int(plan["group_batch"]))
                if averager is not None:
                    grads = averager.allreduce(grads)
                else:
                    manager.allreduce(payload.copy())
                if manager.should_commit():
                    commits += 1
                    samples += int(plan["global_batch"])
                else:
                    failed += 1
            except Exception:  # noqa: BLE001 — count and retry, never die
                if manager.drain_requested():
                    drained = True
                    break
                failed += 1
                time.sleep(0.2)
        if not drained:
            # Uncounted linger: siblings' final counted quorums — started a
            # tick before ours ended — need our join to form.  Bounded;
            # the driver writes done_all once every live group checked in.
            with open(os.path.join(workdir, f"done_{cfg['group']}"), "w"):
                pass
            linger_deadline = time.time() + 12.0
            while (
                time.time() < linger_deadline
                and not os.path.exists(done_all_path)
            ):
                try:
                    manager.start_quorum()
                    time.sleep(0.1)
                    manager.should_commit()
                except Exception:  # noqa: BLE001 — teardown races are benign
                    break
    finally:
        if drained:
            manager.complete_drain()
        summary = {
            "group": cfg["group"],
            "commits": commits,
            "failed": failed,
            "samples": samples,
            "drained": drained,
            "participants_seen": sorted(participants_seen),
        }
        if averager is not None:
            # Evidence the bucket-plan cache is participant-keyed: one
            # plan per membership size this group trained through.
            summary["bucket_plan_participants"] = sorted(
                {key[3] for key in averager._plans}
            )
        print("ELASTIC_WORKER " + json.dumps(summary), flush=True)
        manager.shutdown()


# ---------------------------------------------------------------------------
# Trace construction
# ---------------------------------------------------------------------------


def make_trace(
    seed: int, kinds: List[str], start_groups: int, gap_range=(4.0, 7.0)
) -> List[Dict[str, Any]]:
    """The seeded spot-market trace: for each event kind in ``kinds``
    (``"leave"``/``"join"``), the rng picks WHICH live non-anchor group
    departs and the inter-event gap.  Group 0 is the anchor (never leaves)
    so the cell always has one continuous commit timeline to measure
    steady-state cadence from.  Join ids are fresh (monotonic) — drained
    incarnations are tombstoned by the lighthouse and never reused."""
    rng = random.Random(seed)
    live = list(range(start_groups))
    next_id = start_groups
    trace: List[Dict[str, Any]] = []
    for kind in kinds:
        gap = round(rng.uniform(*gap_range), 2)
        if kind == "leave":
            candidates = [g for g in live if g != 0]
            if not candidates:
                raise ValueError("trace would drain the anchor group")
            victim = rng.choice(candidates)
            live.remove(victim)
            trace.append(
                {"kind": "leave", "group": victim, "gap_s": gap,
                 "n_after": len(live)}
            )
        elif kind == "join":
            trace.append(
                {"kind": "join", "group": next_id, "gap_s": gap,
                 "n_after": len(live) + 1}
            )
            live.append(next_id)
            next_id += 1
        else:
            raise ValueError(f"unknown trace event kind {kind!r}")
    return trace


# ---------------------------------------------------------------------------
# Cell driver
# ---------------------------------------------------------------------------


def _spawn_worker(
    workdir: str,
    group: int,
    lighthouse_addr: str,
    end_cap: float,
    per_sample_s: float,
    use_averager: bool,
    env: Dict[str, str],
    log_paths: List[str],
    workers: Dict[int, subprocess.Popen],
) -> None:
    cfg = {
        "group": group,
        "lighthouse": lighthouse_addr,
        "workdir": workdir,
        "end_cap_ts": end_cap,
        "per_sample_s": per_sample_s,
        "use_averager": use_averager,
    }
    log_path = os.path.join(workdir, f"g{group}.log")
    log_paths.append(log_path)
    with open(log_path, "ab") as log:
        workers[group] = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             json.dumps(cfg)],
            env=env,
            stdout=log,
            stderr=subprocess.STDOUT,
            cwd=REPO,
        )


def run_trace_cell(
    workdir: str,
    start_groups: int,
    trace: List[Dict[str, Any]],
    *,
    global_batch: int = 32,
    per_sample_s: float = 0.02,
    use_averager: bool = True,
    tail_s: float = 6.0,
    min_groups: int = 2,
    ring2d_min: Optional[int] = None,
    section: str = "elastic_trace",
    worker_env: Optional[Dict[str, str]] = None,
) -> Dict[str, Any]:
    """One churn cell: spawn ``start_groups`` workers, run the trace's
    cooperative leaves (lighthouse drain) and hot-admit joins (fresh
    spawns), then score the commit stream.  An empty ``trace`` is the
    fixed-size oracle."""
    from torchft_tpu._native import LighthouseServer
    from torchft_tpu.obs import report as obs_report

    os.makedirs(workdir, exist_ok=True)
    metrics_path = os.path.join(workdir, "metrics.jsonl")
    gc.collect()
    fd_before = _fd_count()
    result: Dict[str, Any] = {
        "section": section,
        "groups_start": start_groups,
        "global_batch": global_batch,
        "per_sample_s": per_sample_s,
        "use_averager": use_averager,
        "trace": [dict(e) for e in trace],
        "ok": False,
    }
    workers: Dict[int, subprocess.Popen] = {}
    log_paths: List[str] = []
    lighthouse = None
    drained_groups: List[int] = []
    try:
        lighthouse = LighthouseServer(
            bind="127.0.0.1:0",
            http_bind="127.0.0.1:0",
            # The floor must stay satisfiable at the trace's smallest
            # membership; the ready/go barrier (not the floor) is what
            # makes the FIRST quorum contain the whole starting set.
            min_replicas=max(1, min_groups),
            join_timeout_ms=10000 + 500 * start_groups,
            quorum_tick_ms=50,
            heartbeat_timeout_ms=3000,
        )
        env = dict(os.environ)
        env["TPUFT_METRICS_PATH"] = metrics_path
        env["TPUFT_ELASTIC_GLOBAL_BATCH"] = str(global_batch)
        # EC plane on: shards of each committed step's state spread across
        # the groups, so every membership change has coverage to re-form.
        env.setdefault("TPUFT_EC_K", "2")
        env.setdefault("TPUFT_EC_M", "1")
        env.setdefault("TPUFT_EC_INTERVAL", "1")
        env.setdefault("TPUFT_RING_TOPOLOGY", "auto")
        if ring2d_min is not None:
            env["TPUFT_RING2D_MIN_GROUPS"] = str(ring2d_min)
        if use_averager:
            env.setdefault("JAX_PLATFORMS", "cpu")
        if worker_env:
            env.update(worker_env)
        # Hard ceiling: warmup + every trace gap + per-event stabilization
        # budget + the tail.
        end_cap = (
            time.time() + 120.0
            + sum(float(e["gap_s"]) for e in trace)
            + 45.0 * max(1, len(trace)) + tail_s
        )
        for g in range(start_groups):
            _spawn_worker(
                workdir, g, lighthouse.address(), end_cap, per_sample_s,
                use_averager, env, log_paths, workers,
            )

        def commits_per_group() -> Dict[str, List[float]]:
            return obs_report.commit_timelines(
                obs_report.read_events([metrics_path])
            )

        # Ready/go barrier (bench_scale's lesson): release together so the
        # first quorum holds the full starting set.
        ready_deadline = time.time() + 90.0 + 2.0 * start_groups
        while time.time() < ready_deadline:
            if all(
                os.path.exists(os.path.join(workdir, f"ready_{g}"))
                for g in range(start_groups)
            ):
                break
            time.sleep(0.1)
        with open(os.path.join(workdir, "go"), "w"):
            pass

        # Warmup: every starting group commits before the trace begins.
        warm_deadline = time.time() + 90.0
        while time.time() < warm_deadline:
            cs = commits_per_group()
            if all(len(cs.get(str(g), [])) >= 2 for g in range(start_groups)):
                break
            time.sleep(0.25)
        cs = commits_per_group()
        result["warmed_groups"] = sum(
            1 for g in range(start_groups) if len(cs.get(str(g), [])) >= 2
        )
        t0 = time.time()  # counted window opens here

        live = list(range(start_groups))
        transitions: List[Dict[str, Any]] = []
        for event in trace:
            time.sleep(float(event["gap_s"]))
            t_e = time.time()
            g = int(event["group"])
            survivors = list(live)
            if event["kind"] == "leave":
                # Cooperative drain: excluded from the next quorum
                # immediately, in-flight step finishes undisturbed.
                lighthouse.drain(str(g), deadline_ms=20000)
                survivors.remove(g)
                drained_groups.append(g)
                live.remove(g)
                try:
                    workers[g].wait(timeout=45.0)
                except subprocess.TimeoutExpired:
                    workers[g].kill()
                    workers[g].wait()
            else:
                _spawn_worker(
                    workdir, g, lighthouse.address(), end_cap, per_sample_s,
                    use_averager, env, log_paths, workers,
                )
                live.append(g)
            # Stabilization: every survivor commits >= 2 steps past the
            # event (and a joiner lands its first commit) before the next
            # event fires — each transition is measured in isolation.
            stab_deadline = time.time() + 60.0
            stable = False
            while time.time() < stab_deadline and not stable:
                cs = commits_per_group()
                stable = all(
                    len([t for t in cs.get(str(s), []) if t > t_e]) >= 2
                    for s in survivors
                ) and (
                    event["kind"] == "leave"
                    or len(cs.get(str(g), [])) >= 1
                )
                time.sleep(0.2)
            transitions.append(
                {
                    "kind": event["kind"],
                    "group": g,
                    "ts": t_e,
                    "n_after": len(live),
                    "survivors": survivors,
                    "stabilized": stable,
                }
            )
        time.sleep(tail_s)
        t1 = time.time()  # counted window closes at the stop signal
        with open(os.path.join(workdir, "stop"), "w"):
            pass
        # Linger protocol: every live group checks in, then done_all
        # releases them together.
        done_deadline = time.time() + 30.0
        while time.time() < done_deadline:
            if all(
                os.path.exists(os.path.join(workdir, f"done_{g}"))
                for g in live
            ):
                break
            time.sleep(0.1)
        with open(os.path.join(workdir, "done_all"), "w"):
            pass
        for g in live:
            try:
                workers[g].wait(timeout=60.0)
            except subprocess.TimeoutExpired:
                workers[g].kill()
                workers[g].wait()

        # ----- scoring -----------------------------------------------------
        events = obs_report.read_events([metrics_path])
        cs = commits_per_group()
        result["per_group_commits"] = {g: len(ts) for g, ts in sorted(cs.items())}
        result["transitions_stabilized"] = sum(
            1 for t in transitions if t["stabilized"]
        )

        # Committed work in the counted window: committed steps are
        # cluster-lockstep, so distinct step numbers x the constant global
        # batch IS the sample count — immune to double-counting per group.
        committed_steps = {
            int(ev["step"])
            for ev in events
            if ev.get("event") == "commit"
            and ev.get("committed")
            and t0 <= float(ev["ts"]) <= t1
        }
        result["committed_steps"] = len(committed_steps)
        result["committed_samples"] = len(committed_steps) * global_batch

        # Live capacity integral over the counted window: leaves stop
        # counting at the drain notice; joiners start counting at their
        # first commit (before that they are healing, not capacity).
        marks: List[tuple] = []  # (ts, delta)
        for t in transitions:
            if t["kind"] == "leave":
                marks.append((t["ts"], -1))
            else:
                first = next(
                    (x for x in cs.get(str(t["group"]), []) if x > t["ts"]),
                    None,
                )
                marks.append((first if first is not None else t["ts"], +1))
        marks.sort()
        capacity = 0.0
        n = start_groups
        prev = t0
        for ts, delta in marks:
            ts = min(max(ts, t0), t1)
            capacity += n * (ts - prev)
            n += delta
            prev = ts
        capacity += n * (t1 - prev)
        result["window_s"] = round(t1 - t0, 2)
        result["capacity_group_s"] = round(capacity, 2)
        result["goodput_samples_per_group_s"] = round(
            result["committed_samples"] / max(1e-9, capacity), 3
        )

        # Per-transition dead time: the widest survivor commit gap
        # straddling the event, minus the anchor's steady step interval.
        anchor_ts = cs.get("0", [])
        deltas = [b - a for a, b in zip(anchor_ts, anchor_ts[1:])]
        steady_s = statistics.median(deltas) if deltas else 0.0
        result["steady_step_s"] = round(steady_s, 3)
        for t in transitions:
            worst = 0.0
            for s in t["survivors"]:
                ts_list = cs.get(str(s), [])
                before = [x for x in ts_list if x <= t["ts"]]
                after = [x for x in ts_list if x > t["ts"]]
                if before and after:
                    worst = max(worst, min(after) - max(before))
                elif not after:
                    worst = DEAD_TIME_BASELINE_S  # never recovered: fail loud
            t["dead_s"] = round(worst, 3)
            t["dead_adj_s"] = round(max(0.0, worst - steady_s), 3)
        result["transitions"] = [
            {k: t[k] for k in ("kind", "group", "n_after", "stabilized",
                               "dead_s", "dead_adj_s")}
            for t in transitions
        ]
        result["max_transition_dead_s"] = max(
            (t["dead_adj_s"] for t in transitions), default=0.0
        )

        # Failed commits, from the stream (authoritative even if a worker
        # summary line is lost): every group in this cell is either a
        # survivor or a cooperative leaver/joiner, so the gate is zero
        # across ALL of them.
        failed_by_group: Dict[str, int] = {}
        for ev in events:
            if ev.get("event") == "commit" and not ev.get("committed"):
                grp = str(ev.get("replica_id", "")).split(":", 1)[0]
                failed_by_group[grp] = failed_by_group.get(grp, 0) + 1
        result["failed_commits_by_group"] = failed_by_group
        result["survivor_failed_commits"] = sum(failed_by_group.values())

        # Elastic invariant: every committed step record carries the
        # constant global batch; participants move with the trace.
        elastic_committed = 0
        bad_global = 0
        participants_seen: set = set()
        for ev in events:
            if ev.get("event") != "step_summary" or not ev.get("committed"):
                continue
            if "elastic_global_batch" not in ev:
                continue
            elastic_committed += 1
            if int(ev["elastic_global_batch"]) != global_batch:
                bad_global += 1
            participants_seen.add(int(ev.get("elastic_participants", 0)))
        total_committed_summaries = sum(
            1 for ev in events
            if ev.get("event") == "step_summary" and ev.get("committed")
        )
        result["elastic_records"] = {
            "committed_with_plan": elastic_committed,
            "committed_total": total_committed_summaries,
            "constant_global_batch": (
                elastic_committed == total_committed_summaries
                and elastic_committed > 0
                and bad_global == 0
            ),
            "participants_seen": sorted(participants_seen),
        }

        # Reconfiguration + membership + EC evidence.
        modes: Dict[str, int] = {}
        reused = opened = 0
        for ev in events:
            if ev.get("event") == "reconfigure":
                mode = str(ev.get("mode", "unknown"))
                modes[mode] = modes.get(mode, 0) + 1
                reused += int(ev.get("reused_lanes") or 0)
                opened += int(ev.get("opened_lanes") or 0)
        result["reconfigure_modes"] = modes
        result["reused_lanes_total"] = reused
        result["opened_lanes_total"] = opened
        result["membership_changes"] = sum(
            1 for ev in events if ev.get("event") == "membership_change"
        )
        result["membership_transition_s"] = [
            round(float(ev.get("transition_s") or 0.0), 3)
            for ev in events
            if ev.get("event") == "membership_change"
        ]
        result["ec_reshard_pushes"] = sum(
            1 for ev in events
            if ev.get("event") == "ec_push" and ev.get("reshard")
        )

        # Ledger attribution: lost seconds by cause across the cell — the
        # `resize` row is the transitions' named cost.
        lost: Dict[str, float] = {}
        for ev in events:
            causes = (ev.get("ledger") or {}).get("causes") or {}
            for cause, seconds in causes.items():
                lost[cause] = lost.get(cause, 0.0) + float(seconds)
        result["lost_seconds_by_cause"] = {
            k: round(v, 3) for k, v in sorted(lost.items())
        }

        summaries = []
        for path in log_paths:
            try:
                with open(path, "rb") as f:
                    for line in f:
                        if line.startswith(b"ELASTIC_WORKER "):
                            summaries.append(
                                json.loads(line[len(b"ELASTIC_WORKER "):])
                            )
            except OSError:
                pass
        result["worker_summaries"] = sorted(summaries, key=lambda s: s["group"])
        result["drained_groups"] = drained_groups
    finally:
        for w in workers.values():
            if w.poll() is None:
                w.kill()
                w.wait()
        if lighthouse is not None:
            lighthouse.shutdown()

    # fd hygiene: everything the cell opened must be closed.
    fd_after = _fd_count()
    settle = time.time() + 5.0
    while fd_after > fd_before and time.time() < settle:
        gc.collect()
        time.sleep(0.2)
        fd_after = _fd_count()
    result["fd_leaked"] = max(0, fd_after - fd_before) if fd_before >= 0 else None

    result["ok"] = bool(
        result.get("warmed_groups") == start_groups
        and result.get("transitions_stabilized") == len(trace)
        and result.get("committed_steps", 0) > 0
        and result.get("survivor_failed_commits") == 0
        and result.get("elastic_records", {}).get("constant_global_batch")
        and result.get("max_transition_dead_s", 1e9) < DEAD_TIME_BASELINE_S
        and (result.get("fd_leaked") in (0, None))
    )
    return result


# ---------------------------------------------------------------------------
# Full + quick entry points
# ---------------------------------------------------------------------------


def _score(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Folds the elastic + oracle cells into the headline gates."""
    elastic = payload["elastic"]
    oracle = payload["oracle"]
    e_good = elastic.get("goodput_samples_per_group_s") or 0.0
    o_good = oracle.get("goodput_samples_per_group_s") or 0.0
    ratio = (e_good / o_good) if o_good else 0.0
    payload["goodput_ratio_vs_oracle"] = round(ratio, 4)
    payload["goodput_gate"] = GOODPUT_GATE
    payload["dead_time_baseline_s"] = DEAD_TIME_BASELINE_S
    payload["max_transition_dead_s"] = elastic.get("max_transition_dead_s")
    payload["survivor_failed_commits"] = (
        elastic.get("survivor_failed_commits", 0)
        + oracle.get("survivor_failed_commits", 0)
    )
    payload["constant_global_batch"] = bool(
        elastic.get("elastic_records", {}).get("constant_global_batch")
        and oracle.get("elastic_records", {}).get("constant_global_batch")
    )
    payload["fd_leaked_total"] = (
        (elastic.get("fd_leaked") or 0) + (oracle.get("fd_leaked") or 0)
    )
    payload["ok"] = bool(
        elastic.get("ok")
        and oracle.get("ok")
        and ratio >= GOODPUT_GATE
        and payload["survivor_failed_commits"] == 0
        and payload["constant_global_batch"]
        and payload["fd_leaked_total"] == 0
    )
    return payload


def run_full(
    workdir: Optional[str] = None,
    seed: int = 20,
    global_batch: int = 32,
    per_sample_s: float = 0.02,
) -> Dict[str, Any]:
    """The committed ELASTIC_BENCH.json: a 4-group spot trace with 8
    seeded transitions crossing the ring2d/ring boundary in both
    directions (TPUFT_RING2D_MIN_GROUPS=4) and dipping to half capacity,
    vs a fixed 4-group no-churn oracle at identical worker parameters."""
    workdir = workdir or tempfile.mkdtemp(prefix="tpuft_bench_elastic_")
    kinds = ["leave", "join", "leave", "leave", "join", "join", "leave", "join"]
    trace = make_trace(seed, kinds, start_groups=4, gap_range=(4.0, 7.0))
    payload: Dict[str, Any] = {
        "metric": "elastic_goodput_vs_oracle",
        "quick": False,
        "seed": seed,
        "global_batch": global_batch,
        "workdir": workdir,
    }
    payload["elastic"] = run_trace_cell(
        os.path.join(workdir, "elastic"),
        start_groups=4,
        trace=trace,
        global_batch=global_batch,
        per_sample_s=per_sample_s,
        use_averager=True,
        min_groups=2,
        ring2d_min=4,
    )
    payload["oracle"] = run_trace_cell(
        os.path.join(workdir, "oracle"),
        start_groups=4,
        trace=[],
        global_batch=global_batch,
        per_sample_s=per_sample_s,
        use_averager=True,
        tail_s=40.0,
        min_groups=2,
        ring2d_min=4,
        section="fixed_oracle",
    )
    _score(payload)
    # Crossover evidence gate (full mode only): both reconfigure paths ran.
    modes = payload["elastic"].get("reconfigure_modes", {})
    payload["crossover_exercised"] = bool(
        modes.get("incremental", 0) > 0 and modes.get("full", 0) > 0
    )
    payload["ok"] = bool(payload["ok"] and payload["crossover_exercised"])
    return payload


def run_quick(workdir: Optional[str] = None, seed: int = 7) -> Dict[str, Any]:
    """Tier-1's 3-transition cell: 3 JAX-free groups, cooperative
    leave/join/leave on the flat-ring incremental path, plus a short fixed
    oracle — same schema as the full artifact."""
    workdir = workdir or tempfile.mkdtemp(prefix="tpuft_bench_elastic_q_")
    trace = make_trace(
        seed, ["leave", "join", "leave"], start_groups=3, gap_range=(1.5, 3.0)
    )
    payload: Dict[str, Any] = {
        "metric": "elastic_goodput_vs_oracle",
        "quick": True,
        "seed": seed,
        "global_batch": 24,
        "workdir": workdir,
    }
    payload["elastic"] = run_trace_cell(
        os.path.join(workdir, "elastic"),
        start_groups=3,
        trace=trace,
        global_batch=24,
        per_sample_s=0.01,
        use_averager=False,
        tail_s=3.0,
        min_groups=2,
    )
    payload["oracle"] = run_trace_cell(
        os.path.join(workdir, "oracle"),
        start_groups=3,
        trace=[],
        global_batch=24,
        per_sample_s=0.01,
        use_averager=False,
        tail_s=10.0,
        min_groups=2,
        section="fixed_oracle",
    )
    _score(payload)
    # Quick mode stays on the flat ring; the crossover is the full trace's
    # (and the churn soak's) job.
    payload["crossover_exercised"] = None
    return payload


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--worker", type=str, default=None)
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--workdir", type=str, default=None)
    parser.add_argument("--seed", type=int, default=None)
    args = parser.parse_args()
    if args.worker:
        _worker_main(json.loads(args.worker))
        return
    if args.quick:
        payload = run_quick(args.workdir, **(
            {"seed": args.seed} if args.seed is not None else {}
        ))
    else:
        payload = run_full(args.workdir, **(
            {"seed": args.seed} if args.seed is not None else {}
        ))
        out = os.path.join(REPO, "ELASTIC_BENCH.json")
        with open(out, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
    print(json.dumps({
        "metric": payload["metric"],
        "ok": payload["ok"],
        "goodput_ratio_vs_oracle": payload["goodput_ratio_vs_oracle"],
        "max_transition_dead_s": payload["max_transition_dead_s"],
        "survivor_failed_commits": payload["survivor_failed_commits"],
        "constant_global_batch": payload["constant_global_batch"],
    }))


if __name__ == "__main__":
    main()
