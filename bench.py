"""Headline benchmark: fault-tolerant training goodput, measured honestly.

Three configurations:

  raw   — the compiled train step alone on the local chip (no FT machinery).
  ft    — the full per-step fault-tolerance loop (native Lighthouse + Manager,
          async quorum, cross-group allreduce path, two-phase commit vote,
          checkpoint-transport gating) on the same chip, one replica group.
  kill  — the north-star scenario (BASELINE.md): two replica-group processes
          with restart supervisors on the CPU platform, one killed with
          SIGKILL mid-run and healed live from its peer; goodput is committed
          work over a fixed wall-clock window relative to an identical run
          without the kill.

Timing discipline: on the axon TPU tunnel ``jax.block_until_ready`` does NOT
wait for device completion (measured: a chained-matmul loop "finishes" at 13x
the chip's peak FLOP/s) — every measurement here therefore ends with a host
materialization of a value data-dependent on the whole step chain, and the
raw/ft numbers carry an MFU plausibility gate: if measured MFU exceeds 100%
of the chip's peak the benchmark fails loudly instead of reporting garbage.

Prints ONE JSON line:
  value        = FT training goodput on the chip (tokens/sec)
  vs_baseline  = goodput-under-kill fraction (committed work with one
                 SIGKILL + heal vs the same window undisturbed).  The
                 reference publishes no absolute numbers (BASELINE.md); its
                 design target is <5% goodput loss => vs_baseline >= 0.95.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

# (device_kind substring, bf16 peak FLOP/s) — checked in order.
# NOTE: v5e's widely-quoted 394 TFLOP/s is the INT8 figure; bf16 peak is
# 197 TFLOP/s.  Rounds 1-3 used 394 here, which understated MFU by 2x and
# manufactured the "4x off roofline" mystery — per-op profiling (round 4)
# shows the big bf16 matmul fusions sustaining ~187 TFLOP/s, i.e. ~95% of
# the real peak, which is what pinned the error to this table.
_PEAKS = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e reports "TPU v5 lite"
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
]


def _peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for sub, peak in _PEAKS:
        if sub in kind:
            return peak
    return None


# ---------------------------------------------------------------------------
# On-chip: raw vs FT per-step goodput.
# ---------------------------------------------------------------------------


def flagship_config():
    """The headline benchmark model: (TransformerConfig, batch_size, seq).

    Shared with tools/profile_step.py so the per-op profile always
    corresponds to the shape the recorded numbers describe."""
    from torchft_tpu.models import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=32000,
        d_model=768,
        n_layers=12,
        # head_dim 128 = TPU lane width: the pallas flash-attention kernel
        # engages (d_head 64 falls back to XLA S^2 attention) and MXU tiles
        # are full.  Measured on v5e: 12 heads x 64 -> 273 ms/step, 6 x 128
        # -> 213 ms at identical param count (rounds 1-3; MFU percentages
        # from those rounds were computed against the wrong 394 TF/s peak —
        # see _PEAKS — the wall times stand).
        n_heads=6,
        n_kv_heads=6,
        d_ff=2048,
        max_seq=1024,
        # 134M params at batch 16 fits HBM without rematerialization; remat
        # would recompute every layer in backward (~4/3 the FLOPs) to save
        # memory this config doesn't need.
        remat=False,
        # Full unroll of the layer stack: XLA fuses/pipelines across layer
        # boundaries, and >= n_layers takes the static-Python-loop path
        # (constant-folded layer indexing — kills ~17 ms/step of
        # dynamic-update-slice grad writes the scan form leaves behind).
        # Measured on v5e at this config: scan 158 ms/step -> scan-unroll
        # 141 ms -> static loop 131 ms (round 3; now 108 ms with the
        # round-4 pallas backward + fused CE).  Partial unroll (4) was
        # slower than any of these.
        scan_unroll=12,
    )
    return cfg, 16, 1024


def chip_benchmark() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchft_tpu.models import init_params, loss_fn
    from torchft_tpu.parallel import TrainStep, ft_init_mesh

    cfg, batch_size, seq = flagship_config()
    tokens_per_step = batch_size * seq

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch_size, seq)), dtype=jnp.int32
    )
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}

    params = init_params(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(x.size) for x in jax.tree.leaves(params))
    # 6N per token for the dense path + causal attention term (6*L*s*d).
    flops_per_step = (6 * n_params + 6 * cfg.n_layers * seq * cfg.d_model) * tokens_per_step

    device = jax.devices()[0]
    peak = _peak_flops(device)

    ftmesh = ft_init_mesh({"data": 1}, devices=[device])
    tx = optax.adamw(3e-4)
    step = TrainStep(ftmesh, tx, lambda p, b: loss_fn(p, b, cfg))

    def fetch(x) -> float:
        # Host materialization is the only trustworthy completion barrier on
        # this platform (see module docstring).
        return float(np.asarray(x))

    # -- raw --------------------------------------------------------------
    state = {"params": params, "opt": step.init_opt_state(params)}

    def raw_step():
        state["params"], state["opt"], loss = step.full_step(
            state["params"], state["opt"], batch
        )
        return loss

    for _ in range(3):  # compile + warmup
        loss = raw_step()
    fetch(loss)

    # Estimate step time to size the measured run (>= ~6 s of device time,
    # and never fewer than 20 steps: at ~240 ms/step an 8-step window showed
    # ±1% run-to-run noise — larger than the FT overhead being measured).
    t0 = time.perf_counter()
    fetch(raw_step())
    est = max(1e-3, time.perf_counter() - t0)
    steps = max(20, min(200, int(6.0 / est)))

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = raw_step()
    fetch(loss)  # loss depends on params_{k-1}: forces the whole chain
    raw_dt = time.perf_counter() - t0
    raw_tps = tokens_per_step * steps / raw_dt
    raw_mfu = (flops_per_step * steps / raw_dt / peak) if peak else None

    if raw_mfu is not None and raw_mfu > 1.0:
        print(
            json.dumps(
                {
                    "metric": "ft_train_goodput",
                    "value": 0,
                    "unit": "tokens/sec",
                    "vs_baseline": 0,
                    "error": f"implausible measurement: raw MFU {raw_mfu:.2f} "
                    f"exceeds 100% of {device.device_kind} peak — timing is "
                    "not capturing real device execution",
                }
            )
        )
        sys.exit(1)

    # -- ft (one replica group, full stack) -------------------------------
    from torchft_tpu._native import LighthouseServer
    from torchft_tpu.checkpointing.http_transport import HTTPTransport
    from torchft_tpu.collectives import TCPCollective
    from torchft_tpu.manager import Manager

    lighthouse = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=100
    )
    params2 = init_params(jax.random.PRNGKey(0), cfg)
    state2 = {"params": params2, "opt": step.init_opt_state(params2)}
    manager = Manager(
        collective=TCPCollective(timeout=30.0),
        load_state_dict=lambda sd: state2.update(sd),
        state_dict=lambda: dict(state2),
        min_replica_size=1,
        rank=0,
        world_size=1,
        replica_id="bench",
        lighthouse_addr=lighthouse.address(),
        checkpoint_transport=HTTPTransport(timeout=30.0),
    )
    ftmesh.manager = manager

    def ft_one_step():
        manager.start_quorum()
        state2["params"], state2["opt"], loss, committed = step.ft_step(
            state2["params"], state2["opt"], batch
        )
        assert committed, "bench step failed to commit"
        return loss

    try:
        for _ in range(3):
            loss = ft_one_step()
        fetch(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = ft_one_step()
        fetch(loss)
        ft_dt = time.perf_counter() - t0
    finally:
        manager.shutdown()
        lighthouse.shutdown()

    ft_tps = tokens_per_step * steps / ft_dt
    ft_mfu = (flops_per_step * steps / ft_dt / peak) if peak else None

    return {
        "device": str(device.device_kind),
        "model": f"transformer-lm 12L d768 bf16 seq{seq} batch{batch_size} "
        f"({n_params/1e6:.0f}M params)",
        "steps_timed": steps,
        "raw_tokens_per_sec": round(raw_tps, 1),
        "ft_tokens_per_sec": round(ft_tps, 1),
        "ft_step_ms": round(ft_dt / steps * 1000, 2),
        "raw_step_ms": round(raw_dt / steps * 1000, 2),
        "ft_overhead_fraction": round(1 - ft_tps / raw_tps, 4),
        "raw_mfu": round(raw_mfu, 4) if raw_mfu is not None else None,
        "ft_mfu": round(ft_mfu, 4) if ft_mfu is not None else None,
    }


# ---------------------------------------------------------------------------
# Goodput under kill -9 (the BASELINE.md north-star scenario).
# ---------------------------------------------------------------------------


def _run_scenario(
    workdir: str, window_s: float, kill_at_s: float | None, cache_dir: str
) -> dict:
    """Two supervised replica-group processes; optionally SIGKILL group 1 at
    kill_at_s into the measurement window (supervisor restarts it, it heals
    live from group 0).  Returns committed-batch counts parsed from the logs.

    The measurement window only starts once BOTH groups have committed a
    step: startup JIT compilation is excluded from both scenarios, and a
    shared persistent compilation cache keeps the post-kill restart from
    paying it again (on this single-core host a restart recompile starves
    every process, which would swamp the FT cost being measured).

    Process management is the framework's own Launcher (torchft_tpu/launch.py)
    — the same supervisor a user gets from ``python -m torchft_tpu.launch``;
    the bench only adds the scripted SIGKILL.

    Counting is primarily from the Manager's structured metrics stream
    (metrics.jsonl "commit"/"heal_fetched" events — O_APPEND lines are
    atomic on Linux so both groups share one file); the log-grep remains as
    a cross-checked fallback."""
    repo = os.path.dirname(os.path.abspath(__file__))
    from torchft_tpu.launch import Launcher

    metrics_path = os.path.join(workdir, "metrics.jsonl")
    launcher = Launcher(
        [sys.executable, os.path.join(repo, "examples", "train_ddp.py"),
         "--steps", "1000000"],
        num_groups=2,
        lighthouse="embed",
        min_replicas=1,
        join_timeout_ms=2000,
        log_dir=workdir,
        cache_dir=cache_dir,
        env={
            "JAX_PLATFORMS": None,  # parent may have pinned the TPU platform
            "TPUFT_JAX_PLATFORM": "cpu",  # env alone is overridden by site hooks
            "TPUFT_METRICS_PATH": metrics_path,
        },
        cwd=repo,
    )
    kill_ts = None
    with launcher:
        start = time.monotonic()
        killed = kill_at_s is None
        while time.monotonic() - start < window_s:
            time.sleep(0.25)
            if not killed and time.monotonic() - start >= kill_at_s:
                kill_ts = time.time()  # metrics events use time.time()
                launcher.kill(1)  # SIGKILL, the real thing
                killed = True
                time.sleep(3.0)  # restart delay: the dead window is real
                launcher.spawn(1)
            # Supervisor: restart any group that died for other reasons.
            launcher.supervise_once()

    return _scenario_stats(workdir, metrics_path, kill_ts)


def _scenario_stats(workdir: str, metrics_path: str, kill_ts: float | None) -> dict:
    """Parses the metrics stream into per-group committed counts and (for
    kill runs) the victim's measured downtime.

    Counting starts at t0 = the first moment BOTH groups have committed a
    step, so startup JIT compilation is excluded from the counts (not just
    from the wall window).  Group identity is the prefix of replica_id
    ("<group>:<uuid>")."""
    events = []
    try:
        with open(metrics_path, "rb") as f:
            for line in f:
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass

    commits: dict[str, list[float]] = {}
    heals = 0
    heal_ms: list[float] = []
    for ev in events:
        if ev.get("event") == "commit" and ev.get("committed"):
            group = str(ev.get("replica_id", "")).split(":", 1)[0]
            commits.setdefault(group, []).append(float(ev["ts"]))
        elif ev.get("event") == "heal_fetched":
            heals += 1
            if ev.get("heal_ms") is not None:
                heal_ms.append(float(ev["heal_ms"]))

    if not commits:
        # Metrics stream missing or empty: fall back to the log contract
        # (pinned by tests/test_bench_contract.py) — totals only, no
        # per-group timing.
        committed = 0
        heals = 0
        for g in (0, 1):
            path = os.path.join(workdir, f"g{g}.log")
            try:
                with open(path, "rb") as f:
                    for line in f:
                        if b"committed=True" in line:
                            committed += 1
                        if b"healing from replica" in line:
                            heals += 1
            except OSError:
                pass
        return {
            "committed_batches": committed,
            "per_group": {},
            "heals": heals,
            "heal_ms": [],
            "victim_downtime_s": None,
            "victim_partial_step_s": None,
            "victim_restart_s": None,
            "victim_ft_resume_s": None,
            "goodput_self_fraction": None,
            "metrics_stream": False,
        }

    t0 = max(min(ts_list) for ts_list in commits.values())
    per_group = {
        g: sum(1 for ts in ts_list if ts >= t0)
        for g, ts_list in sorted(commits.items())
    }

    victim_downtime = None
    victim_partial_step = None
    victim_restart = None
    victim_ft_resume = None
    self_fraction = None
    if kill_ts is not None and "1" in commits:
        before = [ts for ts in commits["1"] if ts <= kill_ts]
        after = [ts for ts in commits["1"] if ts > kill_ts]
        if before and after:
            victim_downtime = min(after) - max(before)
            victim_partial_step = kill_ts - max(before)
        # Decompose the dead window so the parts SUM to victim_downtime_s:
        #   downtime = partial_step (last pre-kill commit -> kill)
        #            + restart     (kill -> restarted process's first event)
        #            + ft_resume   (first event -> first post-kill commit).
        # Replica ids are "<group>:<uuid>" with a fresh uuid per
        # incarnation, so the restarted process's first event of any kind
        # marks "process up + JAX initialized"; restart is environment cost
        # (scripted respawn delay + spawn + init), ft_resume is the FT
        # system's own path (rejoin + heal + vote).  Only single-restart
        # trials decompose — if the respawned process died again before its
        # first commit (>1 new incarnation by then), attributing the extra
        # dead window to "FT resume" would be false, so the trial reports
        # None and is counted in multi_restart.
        pre_ids = {
            str(ev.get("replica_id"))
            for ev in events
            if str(ev.get("replica_id", "")).split(":", 1)[0] == "1"
            and float(ev["ts"]) <= kill_ts
        }
        new_events = [
            (float(ev["ts"]), str(ev.get("replica_id")))
            for ev in events
            if str(ev.get("replica_id", "")).split(":", 1)[0] == "1"
            and str(ev.get("replica_id")) not in pre_ids
            and float(ev["ts"]) > kill_ts
        ]
        if new_events and after:
            t_commit = min(after)
            incarnations_by_commit = {
                rid for ts, rid in new_events if ts <= t_commit
            }
            if len(incarnations_by_commit) == 1:
                t_up = min(ts for ts, _ in new_events)
                victim_restart = t_up - kill_ts
                victim_ft_resume = t_commit - t_up
        # Self-normalized goodput: the victim's total committed count vs
        # its own pre-kill rate extrapolated over the whole measurement
        # span.  Normalizing within one run makes the fraction immune to
        # run-to-run host-load variance (which dwarfed the effect when
        # comparing across runs) and <= 1 by construction up to rate
        # noise: the victim runs at the merged-quorum rate whenever it is
        # alive and simply loses its dead window.
        pre = [ts for ts in before if ts >= t0]
        span_pre = kill_ts - t0
        t_end = max(max(ts_list) for ts_list in commits.values())
        if len(pre) >= 10 and span_pre > 5.0 and t_end > kill_ts:
            rate_pre = len(pre) / span_pre
            expected = rate_pre * (t_end - t0)
            if expected > 0:
                self_fraction = per_group.get("1", 0) / expected

    return {
        "committed_batches": sum(per_group.values()),
        "per_group": per_group,
        "heals": heals,
        "heal_ms": heal_ms,
        "victim_downtime_s": victim_downtime,
        "victim_partial_step_s": victim_partial_step,
        "victim_restart_s": victim_restart,
        "victim_ft_resume_s": victim_ft_resume,
        "goodput_self_fraction": self_fraction,
        "metrics_stream": True,
    }


def _mean(values) -> float | None:
    vals = [v for v in values if v is not None]
    return round(sum(vals) / len(vals), 2) if vals else None


def kill_benchmark() -> dict:
    """Goodput under SIGKILL, measured per replica group over paired trials.

    Round-3 lesson: on this single-core host, TOTAL committed batches is
    the wrong unit — when group 1 dies, the surviving group's steps get
    FASTER (it stops sharing the CPU and the quorum shrinks), so the
    killed run committed 8% MORE total batches than the undisturbed run
    and the fraction could not resolve the <5% target.  The headline
    fraction is therefore computed on the VICTIM group only: the victim
    runs at the merged-quorum rate in both scenarios and simply loses its
    dead window, so victim_kill/victim_base <= 1 up to run-to-run noise,
    and the survivor speed-up cannot inflate it.  Totals are still
    reported (explained) as a secondary, and the baseline's own
    run-to-run spread is reported so the effect size can be judged
    against measurement noise."""
    window = float(os.environ.get("TPUFT_BENCH_KILL_WINDOW_S", "45"))
    trials = max(1, int(os.environ.get("TPUFT_BENCH_KILL_TRIALS", "3")))
    # One compile cache shared by every process of all scenarios: restarts
    # must not pay JIT compilation again (on a single-core host a recompile
    # starves every process and would swamp the FT cost being measured).
    bases, kills = [], []
    with tempfile.TemporaryDirectory(prefix="tpuft_bench_cache_") as cache_dir:
        for t in range(trials):
            with tempfile.TemporaryDirectory(prefix="tpuft_bench_nokill_") as d:
                bases.append(
                    _run_scenario(d, window_s=window, kill_at_s=None, cache_dir=cache_dir)
                )
            with tempfile.TemporaryDirectory(prefix="tpuft_bench_kill_") as d:
                kills.append(
                    _run_scenario(
                        d, window_s=window, kill_at_s=window / 3, cache_dir=cache_dir
                    )
                )

    def _victim(stats: dict) -> int:
        return stats["per_group"].get("1", 0)

    per_group_ok = all(b["per_group"] and k["per_group"] for b, k in zip(bases, kills))
    self_fracs = [k["goodput_self_fraction"] for k in kills]
    if all(f is not None for f in self_fracs):
        # Primary: within-run self-normalized victim goodput (see
        # _scenario_stats) — immune to run-to-run host-load variance.
        fractions = self_fracs
        unit = "victim_self_normalized"
    elif per_group_ok and all(_victim(b) > 0 for b in bases):
        fractions = [_victim(k) / _victim(b) for b, k in zip(bases, kills)]
        unit = "victim_group_paired"
    else:
        # Metrics stream unavailable: legacy total-count fraction (noisy).
        fractions = [
            k["committed_batches"] / max(1, b["committed_batches"])
            for b, k in zip(bases, kills)
        ]
        unit = "total(legacy)"

    mean = sum(fractions) / len(fractions)
    paired = (
        [round(_victim(k) / _victim(b), 4) for b, k in zip(bases, kills)]
        if per_group_ok and all(_victim(b) > 0 for b in bases)
        else None
    )
    base_victims = [_victim(b) for b in bases] if per_group_ok else []
    base_spread = (
        (max(base_victims) - min(base_victims)) / max(1, min(base_victims))
        if base_victims
        else None
    )
    downtimes = [k["victim_downtime_s"] for k in kills if k["victim_downtime_s"]]
    decomposed = [k for k in kills if k["victim_restart_s"] is not None]
    heal_ms = sorted(ms for k in kills for ms in k["heal_ms"])
    heals = sum(k["heals"] for k in kills)
    return {
        "window_s": window,
        "trials": trials,
        "goodput_unit": unit,
        "goodput_under_kill_fraction": round(mean, 4),
        "goodput_fraction_trials": [round(f, 4) for f in fractions],
        "goodput_fraction_spread": round(max(fractions) - min(fractions), 4),
        # Secondary: victim count vs the PAIRED undisturbed run — across-run
        # comparison, so host-load variance between trials shows up here.
        "goodput_paired_fraction_trials": paired,
        # Baseline noise floor: the undisturbed victim count's own
        # run-to-run spread.  The fraction is only meaningful if the
        # effect being measured exceeds this.
        "baseline_victim_committed": base_victims,
        "baseline_relative_spread": (
            round(base_spread, 4) if base_spread is not None else None
        ),
        "victim_downtime_s": _mean(downtimes),
        "victim_downtime_s_trials": [round(d, 2) for d in downtimes],
        # Downtime decomposition — partial_step + restart + ft_resume sums
        # to victim_decomposed_downtime_s: all four means are taken over
        # the SAME trial subset (those with a complete single-restart
        # decomposition; multi-restart trials report None and are counted
        # below — victim_downtime_s above averages ALL trials and can
        # differ when a multi-restart trial is present).
        # restart = scripted 3 s respawn delay + process spawn + JAX/XLA
        # init (environment floor — any per-step-FT system pays it,
        # including the reference's torchelastic restart); ft_resume =
        # quorum rejoin + live heal + first commit (the part THIS system
        # is responsible for).
        "victim_decomposed_downtime_s": _mean(
            [k["victim_downtime_s"] for k in decomposed]
        ),
        "victim_partial_step_s": _mean(
            [k["victim_partial_step_s"] for k in decomposed]
        ),
        "victim_restart_s": _mean([k["victim_restart_s"] for k in decomposed]),
        "victim_ft_resume_s": _mean([k["victim_ft_resume_s"] for k in decomposed]),
        "multi_restart_trials": sum(
            1
            for k in kills
            if k["victim_downtime_s"] is not None and k["victim_restart_s"] is None
        ),
        "heal_ms_median": heal_ms[len(heal_ms) // 2] if heal_ms else None,
        "committed_batches_undisturbed": sum(b["committed_batches"] for b in bases),
        "committed_batches_with_kill": sum(k["committed_batches"] for k in kills),
        "per_group_undisturbed": [b["per_group"] for b in bases],
        "per_group_with_kill": [k["per_group"] for k in kills],
        # A kill run where the victim never healed is NOT a valid goodput
        # measurement — surface it rather than presenting fraction as if the
        # north-star heal path had been exercised.
        "heals_with_kill": heals,
        "heal_verified": all(k["heals"] >= 1 for k in kills),
        # The per-window fraction charges ONE kill against a window_s-sized
        # window — a failure every 45 s, ~100x any realistic rate.  The
        # victim's downtime is a fixed per-failure cost (dominated by
        # process restart + JAX init on this host), so the steady-state
        # goodput loss at a given MTBF is downtime/MTBF; this field states
        # it for hourly failures, which is already far beyond BASELINE.md's
        # <5% target.
        "goodput_fraction_at_hourly_failures": (
            round(1 - _mean(downtimes) / 3600.0, 5) if downtimes else None
        ),
    }


def main() -> None:
    # The chip result is computed, assembled, and (on any kill-scenario
    # failure) still printed first: a failure on the subprocess-heavy kill
    # path must never discard the on-chip measurement again (round 2 lost its
    # numbers exactly that way).
    chip = chip_benchmark()
    result = {
        "metric": "ft_train_goodput",
        "value": chip["ft_tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": None,
        "detail": {
            **chip,
            "baseline_semantics": "vs_baseline = the KILLED group's "
            "committed batches over a window with one SIGKILL + live heal, "
            "relative to its own pre-kill commit rate extrapolated over "
            "the same window (self-normalized; mean of trials; <= 1 by "
            "construction).  Victim-only, within-run normalization: on a "
            "1-core host the survivor speeds up when its peer dies and "
            "run-to-run load variance exceeds the effect, which made the "
            "round-3 total-vs-paired-run fraction land above 1.  Context "
            "for the absolute value: the fraction charges one kill per "
            "window (a failure every ~45 s, ~100x any realistic rate), and "
            "victim_restart_s shows most of the dead window is the "
            "environment's process-respawn + JAX-init floor that ANY "
            "per-step-FT system pays — the FT resume itself "
            "(victim_ft_resume_s: rejoin + live heal + commit) is "
            "sub-second.  goodput_fraction_at_hourly_failures restates the "
            "measured downtime against BASELINE.md's <5% target at a "
            "realistic failure rate.  The reference publishes no absolute "
            "numbers.",
        },
    }
    try:
        kill = kill_benchmark()
    except Exception as e:  # noqa: BLE001
        result["detail"]["kill_benchmark_error"] = repr(e)
        print(json.dumps(result))
        raise
    result["vs_baseline"] = kill["goodput_under_kill_fraction"]
    result["detail"].update(kill)
    print(json.dumps(result))


def selftest() -> None:
    """Fast structural check (no chip, no subprocess windows): verifies both
    scenario entry points are callable with their real signatures so a
    refactor cannot silently break the headline artifact again."""
    import inspect

    sig = inspect.signature(_run_scenario)
    assert list(sig.parameters) == ["workdir", "window_s", "kill_at_s", "cache_dir"]
    inspect.signature(kill_benchmark).bind()
    inspect.signature(chip_benchmark).bind()
    print("bench selftest ok")


if __name__ == "__main__":
    if "--selftest" in sys.argv:
        selftest()
    else:
        main()
