"""Headline benchmark: fault-tolerant training goodput, measured honestly.

Three configurations:

  raw   — the compiled train step alone on the local chip (no FT machinery).
  ft    — the full per-step fault-tolerance loop (native Lighthouse + Manager,
          async quorum, cross-group allreduce path, two-phase commit vote,
          checkpoint-transport gating) on the same chip, one replica group.
  kill  — the north-star scenario (BASELINE.md): two replica-group processes
          with restart supervisors on the CPU platform, one killed with
          SIGKILL mid-run and healed live from its peer; goodput is committed
          work over a fixed wall-clock window relative to an identical run
          without the kill.

Timing discipline: on the axon TPU tunnel ``jax.block_until_ready`` does NOT
wait for device completion (measured: a chained-matmul loop "finishes" at 13x
the chip's peak FLOP/s) — every measurement here therefore ends with a host
materialization of a value data-dependent on the whole step chain, and the
raw/ft numbers carry an MFU plausibility gate: if measured MFU exceeds 100%
of the chip's peak the benchmark fails loudly instead of reporting garbage.

Prints ONE JSON line:
  value        = FT training goodput on the chip (tokens/sec)
  vs_baseline  = goodput-under-kill fraction (committed work with one
                 SIGKILL + heal vs the same window undisturbed).  The
                 reference publishes no absolute numbers (BASELINE.md); its
                 design target is <5% goodput loss => vs_baseline >= 0.95.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

# (device_kind substring, bf16 peak FLOP/s) — checked in order.
# NOTE: v5e's widely-quoted 394 TFLOP/s is the INT8 figure; bf16 peak is
# 197 TFLOP/s.  Rounds 1-3 used 394 here, which understated MFU by 2x and
# manufactured the "4x off roofline" mystery — per-op profiling (round 4)
# shows the big bf16 matmul fusions sustaining ~187 TFLOP/s, i.e. ~95% of
# the real peak, which is what pinned the error to this table.
_PEAKS = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e reports "TPU v5 lite"
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
]


def _peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for sub, peak in _PEAKS:
        if sub in kind:
            return peak
    return None


# ---------------------------------------------------------------------------
# On-chip: raw vs FT per-step goodput.
# ---------------------------------------------------------------------------


def flagship_config():
    """The headline benchmark model: (TransformerConfig, batch_size, seq).

    Shared with tools/profile_step.py so the per-op profile always
    corresponds to the shape the recorded numbers describe."""
    from torchft_tpu.models import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=32000,
        d_model=768,
        n_layers=12,
        # head_dim 128 = TPU lane width: the pallas flash-attention kernel
        # engages (d_head 64 falls back to XLA S^2 attention) and MXU tiles
        # are full.  Measured on v5e: 12 heads x 64 -> 273 ms/step, 6 x 128
        # -> 213 ms at identical param count (rounds 1-3; MFU percentages
        # from those rounds were computed against the wrong 394 TF/s peak —
        # see _PEAKS — the wall times stand).
        n_heads=6,
        n_kv_heads=6,
        d_ff=2048,
        max_seq=1024,
        # 134M params at batch 16 fits HBM without rematerialization; remat
        # would recompute every layer in backward (~4/3 the FLOPs) to save
        # memory this config doesn't need.
        remat=False,
        # Full unroll of the layer stack: XLA fuses/pipelines across layer
        # boundaries, and >= n_layers takes the static-Python-loop path
        # (constant-folded layer indexing — kills ~17 ms/step of
        # dynamic-update-slice grad writes the scan form leaves behind).
        # Measured on v5e at this config: scan 158 ms/step -> scan-unroll
        # 141 ms -> static loop 131 ms (round 3; now 108 ms with the
        # round-4 pallas backward + fused CE).  Partial unroll (4) was
        # slower than any of these.
        scan_unroll=12,
    )
    return cfg, 16, 1024


def chip_benchmark() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchft_tpu.models import init_params, loss_fn
    from torchft_tpu.parallel import TrainStep, ft_init_mesh

    cfg, batch_size, seq = flagship_config()
    tokens_per_step = batch_size * seq

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch_size, seq)), dtype=jnp.int32
    )
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}

    params = init_params(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(x.size) for x in jax.tree.leaves(params))
    # 6N per token for the dense path + causal attention term (6*L*s*d).
    flops_per_step = (6 * n_params + 6 * cfg.n_layers * seq * cfg.d_model) * tokens_per_step

    device = jax.devices()[0]
    peak = _peak_flops(device)

    ftmesh = ft_init_mesh({"data": 1}, devices=[device])
    tx = optax.adamw(3e-4)
    step = TrainStep(ftmesh, tx, lambda p, b: loss_fn(p, b, cfg))

    def fetch(x) -> float:
        # Host materialization is the only trustworthy completion barrier on
        # this platform (see module docstring).
        return float(np.asarray(x))

    # -- raw --------------------------------------------------------------
    state = {"params": params, "opt": step.init_opt_state(params)}

    def raw_step():
        state["params"], state["opt"], loss = step.full_step(
            state["params"], state["opt"], batch
        )
        return loss

    for _ in range(3):  # compile + warmup
        loss = raw_step()
    fetch(loss)

    # Estimate step time to size the measured run (>= ~6 s of device time,
    # and never fewer than 20 steps: at ~240 ms/step an 8-step window showed
    # ±1% run-to-run noise — larger than the FT overhead being measured).
    t0 = time.perf_counter()
    fetch(raw_step())
    est = max(1e-3, time.perf_counter() - t0)
    steps = max(20, min(200, int(6.0 / est)))

    # -- ft (one replica group, full stack) -------------------------------
    from torchft_tpu._native import LighthouseServer
    from torchft_tpu.checkpointing.http_transport import HTTPTransport
    from torchft_tpu.collectives import TCPCollective
    from torchft_tpu.manager import Manager

    lighthouse = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=100
    )
    params2 = init_params(jax.random.PRNGKey(0), cfg)
    state2 = {"params": params2, "opt": step.init_opt_state(params2)}
    manager = Manager(
        collective=TCPCollective(timeout=30.0),
        load_state_dict=lambda sd: state2.update(sd),
        state_dict=lambda: dict(state2),
        min_replica_size=1,
        rank=0,
        world_size=1,
        replica_id="bench",
        lighthouse_addr=lighthouse.address(),
        checkpoint_transport=HTTPTransport(timeout=30.0),
    )
    ftmesh.manager = manager

    def ft_one_step():
        manager.start_quorum()
        state2["params"], state2["opt"], loss, committed = step.ft_step(
            state2["params"], state2["opt"], batch
        )
        assert committed, "bench step failed to commit"
        return loss

    # INTERLEAVED measurement: raw and FT blocks alternate (R,F,R,F,...) so
    # slow host-load drift hits both paths equally; the FT overhead is then
    # judged against the raw blocks' own spread rather than stated as a
    # point estimate (round-4 lesson: ft measured *faster* than raw — the
    # difference is below run variance, and the honest claim is exactly
    # that).
    reps = 3
    block = max(7, steps // reps)
    raw_block_tps: list[float] = []
    ft_block_tps: list[float] = []
    try:
        for _ in range(3):  # FT warmup (compile path is shared with raw)
            loss = ft_one_step()
        fetch(loss)
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(block):
                loss = raw_step()
            fetch(loss)  # loss depends on params_{k-1}: forces the chain
            raw_block_tps.append(tokens_per_step * block / (time.perf_counter() - t0))

            t0 = time.perf_counter()
            for _ in range(block):
                loss = ft_one_step()
            fetch(loss)
            ft_block_tps.append(tokens_per_step * block / (time.perf_counter() - t0))
    finally:
        manager.shutdown()
        lighthouse.shutdown()

    raw_tps = sum(raw_block_tps) / reps
    ft_tps = sum(ft_block_tps) / reps
    raw_dt = tokens_per_step * block * reps / raw_tps
    ft_dt = tokens_per_step * block * reps / ft_tps
    steps = block * reps
    raw_mfu = (flops_per_step * steps / raw_dt / peak) if peak else None
    ft_mfu = (flops_per_step * steps / ft_dt / peak) if peak else None

    if raw_mfu is not None and raw_mfu > 1.0:
        print(
            json.dumps(
                {
                    "metric": "ft_train_goodput",
                    "value": 0,
                    "unit": "tokens/sec",
                    "vs_baseline": 0,
                    "error": f"implausible measurement: raw MFU {raw_mfu:.2f} "
                    f"exceeds 100% of {device.device_kind} peak — timing is "
                    "not capturing real device execution",
                }
            )
        )
        sys.exit(1)

    # Run-to-run noise floor: the raw path's own block-to-block spread.
    raw_noise = (max(raw_block_tps) - min(raw_block_tps)) / raw_tps
    overhead = 1 - ft_tps / raw_tps

    return {
        "device": str(device.device_kind),
        "model": f"transformer-lm 12L d768 bf16 seq{seq} batch{batch_size} "
        f"({n_params/1e6:.0f}M params)",
        "steps_timed": steps,
        "interleaved_blocks": reps,
        "raw_tokens_per_sec": round(raw_tps, 1),
        "ft_tokens_per_sec": round(ft_tps, 1),
        "raw_block_tokens_per_sec": [round(x, 1) for x in raw_block_tps],
        "ft_block_tokens_per_sec": [round(x, 1) for x in ft_block_tps],
        "ft_step_ms": round(ft_dt / steps * 1000, 2),
        "raw_step_ms": round(raw_dt / steps * 1000, 2),
        "ft_overhead_fraction": round(overhead, 4),
        "raw_noise_fraction": round(raw_noise, 4),
        # The claim the README is allowed to make: overhead resolved, or
        # below the measurement's own noise floor.
        "ft_overhead_below_noise": bool(abs(overhead) <= raw_noise),
        "raw_mfu": round(raw_mfu, 4) if raw_mfu is not None else None,
        "ft_mfu": round(ft_mfu, 4) if ft_mfu is not None else None,
    }


def large_config():
    """The scale-proof model: ~1B params, the largest round shape that fits
    one v5e chip (16 GB HBM) with f32 params + a memory-lean factored
    optimizer — withOUT rematerialization, which measured as a pure loss
    at this size (see the remat field comment).  VERDICT r4 #2: show the
    MFU and heal story survive a ~10x model (reference capability chased:
    'train models such as Llama 3 70B', reference README)."""
    from torchft_tpu.models import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=32000,
        d_model=2048,
        n_layers=12,
        n_heads=16,
        n_kv_heads=16,
        d_ff=8192,
        max_seq=1024,
        # Measured on v5e at batch 8: remat 410 ms/step (58.6% MFU) vs
        # NO remat 334 ms (71.9%) — the flash-attention kernels' O(S*D)
        # residuals and the fused CE's never-materialized logits leave
        # enough HBM at this size that paying the recompute tax is a pure
        # loss.  Larger-than-HBM configs flip remat back on.
        remat=False,
        scan_unroll=12,  # static layer loop, same as the flagship
    )
    return cfg, 8, 1024


def large_chip_benchmark() -> dict | None:
    """Step time / MFU for the ~1B model on the real chip, plus the live
    heal cost at that size (the full state dict through HTTPTransport on
    localhost — the same bytes a healing replica must ingest)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchft_tpu.models import init_params, loss_fn
    from torchft_tpu.parallel import TrainStep, ft_init_mesh

    device = jax.devices()[0]
    if "tpu" not in device.platform.lower() or os.environ.get(
        "TPUFT_BENCH_LARGE", "1"
    ) == "0":
        return None

    cfg, batch_size, seq = large_config()
    tokens_per_step = batch_size * seq
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch_size, seq)), dtype=jnp.int32
    )
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}

    params = init_params(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(x.size) for x in jax.tree.leaves(params))
    flops_per_step = (
        6 * n_params + 6 * cfg.n_layers * seq * cfg.d_model
    ) * tokens_per_step
    # Remat recomputes the layer stack in backward (~+2N per token of the
    # layer FLOPs); MFU is still stated against the USEFUL flops above —
    # that is the number that compares across configs.
    peak = _peak_flops(device)

    ftmesh = ft_init_mesh({"data": 1}, devices=[device])
    tx = optax.adafactor(3e-4)  # factored second moments: O(d) state, not O(d^2)
    step = TrainStep(ftmesh, tx, lambda p, b: loss_fn(p, b, cfg))
    state = {"params": params, "opt": step.init_opt_state(params)}

    def fetch(x) -> float:
        return float(np.asarray(x))

    def raw_step():
        state["params"], state["opt"], loss = step.full_step(
            state["params"], state["opt"], batch
        )
        return loss

    for _ in range(2):
        loss = raw_step()
    fetch(loss)
    t0 = time.perf_counter()
    fetch(raw_step())
    est = max(1e-3, time.perf_counter() - t0)
    steps = max(10, min(60, int(8.0 / est)))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = raw_step()
    fetch(loss)
    dt = time.perf_counter() - t0
    tps = tokens_per_step * steps / dt
    mfu = (flops_per_step * steps / dt / peak) if peak else None

    # Heal cost at this size: stream the full live state dict through the
    # HTTP checkpoint transport (send + chunked recv) on localhost.  This
    # is the byte path a healed replica pays on top of restart.
    flat = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(state["params"]):
        flat["p" + jax.tree_util.keystr(path)] = np.asarray(leaf)
    state_bytes = sum(a.nbytes for a in flat.values())
    # Both live transports; on this 1-core host both endpoints share one
    # core, so these are FLOORS — real multi-host hardware has a NIC and
    # cores per endpoint (TRANSFER_BENCH.json records the same floor for
    # the 2 GB synthetic state).
    heal = {"state_gb": round(state_bytes / 1e9, 2)}
    try:
        from bench_transfer import bench_collective, bench_http

        heal["http"] = bench_http(flat, state_bytes, num_chunks=4)
        heal["collective"] = bench_collective(flat, state_bytes)
    except Exception as e:  # noqa: BLE001
        heal["error"] = repr(e)

    return {
        "model": f"transformer-lm {cfg.n_layers}L d{cfg.d_model} bf16 seq{seq} "
        f"batch{batch_size} ({n_params/1e6:.0f}M params, "
        f"{'remat' if cfg.remat else 'no-remat'}, adafactor)",
        "steps_timed": steps,
        "step_ms": round(dt / steps * 1000, 2),
        "tokens_per_sec": round(tps, 1),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "heal_transfer": heal,
    }


# ---------------------------------------------------------------------------
# Goodput under kill -9 (the BASELINE.md north-star scenario).
# ---------------------------------------------------------------------------


def _read_events(metrics_path: str) -> list:
    # The hardened reader: skips torn/garbage lines AND JSON that parses to
    # a non-dict (a corrupt line reading as a bare scalar would crash every
    # ev.get() consumer below) — one implementation, shared with the
    # attribution/report tooling.
    from torchft_tpu.obs.report import read_events

    return read_events([metrics_path])


class _MetricsTail:
    """Incremental reader of the shared metrics.jsonl.

    The churn watcher polls every 250 ms on the same single core being
    measured; re-parsing the whole (growing) file each tick would steal
    CPU from the heal interval whose duration is the headline number.
    Appends are line-atomic (O_APPEND), so tailing from the last consumed
    newline is safe."""

    def __init__(self, path: str) -> None:
        self._path = path
        self._pos = 0
        self.events: list = []

    def poll(self) -> list:
        try:
            with open(self._path, "rb") as f:
                f.seek(self._pos)
                chunk = f.read()
        except OSError:
            return self.events
        if not chunk:
            return self.events
        # Only consume up to the last complete line.
        end = chunk.rfind(b"\n")
        if end < 0:
            return self.events
        self._pos += end + 1
        for line in chunk[: end + 1].splitlines():
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if isinstance(ev, dict):  # scalar-parsing garbage: skip, see
                self.events.append(ev)  # obs/report.py::read_events
        return self.events


def _victim_incarnations(events, group: str) -> dict:
    """{replica_id: (first_event_ts, first_commit_ts|None)} for one group."""
    out: dict = {}
    for ev in events:
        rid = str(ev.get("replica_id", ""))
        if rid.split(":", 1)[0] != group:
            continue
        ts = float(ev.get("ts", 0.0))
        first, commit = out.get(rid, (ts, None))
        first = min(first, ts)
        if ev.get("event") == "commit" and ev.get("committed") and (
            commit is None or ts < commit
        ):
            commit = ts
        out[rid] = (first, commit)
    return out


def _run_scenario(
    workdir: str, window_s: float, plan: dict | None, cache_dir: str
) -> dict:
    """Two supervised replica-group processes; `plan` scripts the fault:

      None                          — undisturbed baseline window.
      {"type": "single", "victim"}  — one SIGKILL at window/3.
      {"type": "single_spare", "victim"} — one SIGKILL, but the launcher
          runs a hot-spare pool: the dead group's id is handed to a
          pre-initialized spare immediately (no scripted respawn delay —
          adoption IS the respawn), measuring the spare-pool downtime.
      {"type": "double", "victim"}  — SIGKILL at window/4; once the
          restarted incarnation COMMITS, kill it again (back-to-back
          failures, the churn the reference's integ tests repeat,
          torchft/manager_integ_test.py:304-352).
      {"type": "during_heal", "victim"} — SIGKILL at window/4; the moment
          the restarted incarnation shows its FIRST event (it is
          rejoining/healing, has not committed), kill it again — a failure
          landing inside recovery.
      {"type": "drain", "victim"}   — cooperative drain at window/3: the
          launcher (spare pool enabled) writes the drain notice and hands
          the group id to a pre-warmed spare; the donor finishes its
          in-flight step, votes commit, tells the lighthouse, and exits.
          Measures the PLANNED-departure path (GCE maintenance /
          preemption notices, SIGTERM grace periods) next to the crash
          numbers: dead time is the donor-to-replacement commit gap, and
          the survivors must see ZERO failed should_commit rounds.
      {"type": "straggler", "victim", "auto_drain"} — no kill at all: at
          window/3 the victim gets an injected per-step sleep (pid-pinned
          straggle file read by examples/_common.maybe_straggle), modeling
          the degraded-but-alive host no heartbeat timeout catches.  The
          lighthouse's straggler sentinel must detect it (healthy ->
          suspect -> straggler on /metrics, alert on /alerts.json; the
          driver stamps the observation into the stream as an ``alert``
          record).  With auto_drain the launcher runs a spare pool +
          sentinel poll and rotates the slow host out through the
          cooperative-drain path; the scenario's post-injection commit
          rate then measures the goodput the sentinel recovered vs the
          no-sentinel run that keeps pacing on the slow host.

    The measurement window only starts once BOTH groups have committed a
    step: startup JIT compilation is excluded from both scenarios, and a
    shared persistent compilation cache keeps the post-kill restart from
    paying it again (on this single-core host a restart recompile starves
    every process, which would swamp the FT cost being measured).

    Process management is the framework's own Launcher (torchft_tpu/launch.py)
    — the same supervisor a user gets from ``python -m torchft_tpu.launch``;
    the bench only adds the scripted SIGKILLs.

    Counting is primarily from the Manager's structured metrics stream
    (metrics.jsonl "commit"/"heal_fetched" events — O_APPEND lines are
    atomic on Linux so both groups share one file); the log-grep remains as
    a cross-checked fallback."""
    repo = os.path.dirname(os.path.abspath(__file__))
    from torchft_tpu.launch import Launcher
    from torchft_tpu.metrics import MetricsLogger

    metrics_path = os.path.join(workdir, "metrics.jsonl")
    # The bench driver writes its fault schedule INTO the shared metrics
    # stream ("fault" records), so obs/report.py sees the exact timeline
    # the goodput accounting below charges — the report reproduces the
    # benchmark number from the JSONL alone.
    fault_log = MetricsLogger(metrics_path, replica_id="bench-driver")
    victim = str(plan["victim"]) if plan else None
    kind = plan["type"] if plan else None
    straggler = kind == "straggler"
    auto_drain = bool(plan.get("auto_drain")) if plan else False
    straggle_sleep_s = float(os.environ.get("TPUFT_BENCH_STRAGGLE_SLEEP_S", "1.0"))
    straggle_info: dict = {}
    spares = 1 if kind in ("single_spare", "drain") or (straggler and auto_drain) else 0
    child_env: dict = {
        "JAX_PLATFORMS": None,  # parent may have pinned the TPU platform
        "TPUFT_JAX_PLATFORM": "cpu",  # env alone is overridden by site hooks
        "TPUFT_METRICS_PATH": metrics_path,
        # Worker managers dump their flight recorders here on clean exit
        # (drained donors); SIGKILLed victims leave no dump — their story
        # lives in the LIGHTHOUSE's recorder, which dumps at launcher stop.
        "TPUFT_FLIGHT_DIR": workdir,
    }
    # The embedded lighthouse runs in THIS process; it reads the dump path
    # from the driver's environment at SHUTDOWN, so the var only needs to
    # be set inside the try below (children get it via child_env) — a
    # Launcher construction failure then cannot leak it.
    prev_flight_dir = os.environ.get("TPUFT_FLIGHT_DIR")
    if straggler:
        child_env["TPUFT_STRAGGLE_DIR"] = workdir
    launcher = Launcher(
        [sys.executable, os.path.join(repo, "examples", "train_ddp.py"),
         "--steps", "1000000"],
        num_groups=2,
        lighthouse="embed",
        min_replicas=1,
        join_timeout_ms=2000,
        log_dir=workdir,
        cache_dir=cache_dir,
        env=child_env,
        cwd=repo,
        spares=spares,
        straggler_auto_drain=auto_drain if straggler else None,
    )
    kill_events: list[tuple[float, str]] = []
    # Churn windows get extra tail so the LAST heal still has room to
    # complete and commit inside the measured window.
    total_window = window_s + (20.0 if kind in ("double", "during_heal") else 0.0)

    def kill_victim():
        now = time.time()
        if straggler:
            # Not a kill: drop the pid-pinned straggle file the victim's
            # train loop polls — from now on its every step pays an extra
            # sleep, until the sentinel rotates the incarnation out (the
            # replacement has a new pid and stays fast).
            pid = launcher.pid(int(victim))
            if pid is None:
                # Victim momentarily dead (supervisor restarting it): a
                # pid-less file would pin the slowness to EVERY future
                # incarnation.  Skip; the next poll tick retries.
                return
            path = os.path.join(workdir, f"straggle_{victim}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"sleep_s": straggle_sleep_s, "pid": pid}, f)
            os.replace(tmp, path)
            fault_log.emit(
                "fault", ts=now, kind="straggler", group=victim, plan=kind
            )
            fault_log.emit(
                "straggler_injected",
                group=victim,
                sleep_s=straggle_sleep_s,
                pid=pid,
            )
            straggle_info["inject_ts"] = now
            straggle_info["sleep_s"] = straggle_sleep_s
            return
        kill_events.append((now, victim))
        # Same ts as the in-memory kill list (the explicit ts field
        # overrides the logger's own clock) so the recorded stream yields
        # bit-identical goodput arithmetic.
        fault_log.emit(
            "fault",
            ts=now,
            kind="drain" if kind == "drain" else "kill",
            group=victim,
            plan=kind,
        )
        if kind == "drain":
            # Planned departure: the launcher hands the id to a pre-warmed
            # spare and notifies the donor; no kill at all.  A victim that
            # crashed in the poll gap makes drain() raise — record the
            # trial as unrecovered instead of aborting the whole bench
            # (kill() tolerates the same race silently).
            try:
                launcher.drain(int(victim), deadline_s=20.0)
            except RuntimeError as e:
                print(f"drain trial lost its victim before the notice: {e}",
                      file=sys.stderr)
            return
        launcher.kill(int(victim))  # SIGKILL, the real thing
        if spares:
            # Hot adoption IS the respawn: no scripted environment delay.
            launcher.spawn(int(victim))
        else:
            time.sleep(3.0)  # restart delay: the dead window is real
            launcher.spawn(int(victim))

    try:
        os.environ["TPUFT_FLIGHT_DIR"] = workdir
        with launcher:
            start = time.monotonic()
            first_kill_at = None if plan is None else (
                total_window / 3
                if kind in ("single", "single_spare", "drain", "straggler")
                else total_window / 4
            )
            pre_kill_ids: set = set()
            second_done = kind in ("single", "single_spare", "drain", "straggler")
            second_deadline = None
            last_alert_poll = 0.0
            tail = _MetricsTail(metrics_path)
            # Incident auto-capture: poll the embedded lighthouse's
            # /incident.json and bundle the live evidence the moment a
            # trigger lands (replica_stale for kills, alert:<kind> for
            # sentinel raises) — the shutdown dumps are folded in by the
            # finalize pass after the launcher exits.
            from torchft_tpu.obs import incident as obs_incident

            incident_watch = obs_incident.IncidentWatcher(
                launcher.lighthouse_http_address
            )
            incident_bundles: dict[str, dict] = {}
            last_incident_poll = 0.0

            def poll_incidents() -> None:
                nonlocal last_incident_poll
                if time.monotonic() - last_incident_poll < 1.0:
                    return
                last_incident_poll = time.monotonic()
                for trig in incident_watch.poll():
                    try:
                        bundle = obs_incident.capture_bundle(
                            workdir,
                            launcher.lighthouse_http_address,
                            trig,
                            metrics_paths=[metrics_path],
                        )
                    except OSError:
                        # Transient capture failure: re-queue the trigger
                        # so the next poll retries instead of losing the
                        # incident the feed already recorded.
                        incident_watch.unsee(trig.get("id"))
                        continue
                    incident_bundles[bundle] = trig
                    fault_log.emit(
                        "incident_captured",
                        bundle=os.path.basename(bundle),
                        reason=trig.get("reason"),
                        incident_replica=trig.get("replica_id"),
                        incident_id=trig.get("id"),
                    )
            while time.monotonic() - start < total_window:
                time.sleep(0.25)
                if first_kill_at is not None and time.monotonic() - start >= first_kill_at:
                    # Draining a group that never committed (still in its first
                    # JIT) measures nothing: the handoff gap needs a donor
                    # commit timeline on both sides — and a straggler injection
                    # before the first commit has no pre-injection pace to
                    # score against.  Hold the fault until the first commit —
                    # WITHOUT skipping the supervision below (the window clock
                    # keeps running either way).
                    fire_ok = kind not in ("drain", "straggler") or any(
                        commit is not None
                        for _, commit in _victim_incarnations(
                            tail.poll(), victim
                        ).values()
                    )
                    if straggler and fire_ok:
                        # The scenario models a host degrading MID-RUN, so the
                        # injection additionally waits until the victim has
                        # cleared the sentinel's warmup gate (which exists to
                        # ignore JIT-phase pace skew) — injecting during warmup
                        # would measure the gate, not the detection contract.
                        try:
                            warmup = max(
                                0,
                                int(os.environ.get(
                                    "TPUFT_STRAGGLER_WARMUP_STEPS", "10")),
                            )
                        except ValueError:
                            warmup = 10
                        n_commits = sum(
                            1
                            for ev in tail.poll()
                            if ev.get("event") == "commit"
                            and ev.get("committed")
                            and str(ev.get("replica_id", "")).split(":", 1)[0]
                            == victim
                        )
                        fire_ok = n_commits > warmup
                    if fire_ok:
                        pre_kill_ids = set(
                            _victim_incarnations(tail.poll(), victim)
                        )
                        kill_victim()
                        if not straggler or "inject_ts" in straggle_info:
                            # A straggler injection can decline to fire (victim
                            # pid momentarily gone); leave the trigger armed so
                            # the next tick retries instead of silently running
                            # a fault-free window.
                            first_kill_at = None
                            second_deadline = time.monotonic() + 25.0
                elif not second_done and kill_events:
                    # Watch for the respawned incarnation to reach the trigger
                    # state, with a deadline fallback so a stuck restart can't
                    # hang the bench.
                    inc = _victim_incarnations(tail.poll(), victim)
                    fresh = {k: v for k, v in inc.items() if k not in pre_kill_ids}
                    fire = False
                    if kind == "double":
                        fire = any(commit is not None for _, commit in fresh.values())
                    elif kind == "during_heal":
                        fire = bool(fresh)
                    if fire or (second_deadline and time.monotonic() > second_deadline):
                        kill_victim()
                        second_done = True
                # Straggler scenario: watch the lighthouse's /alerts.json for
                # the sentinel's detection and stamp it into the stream (the
                # `alert` record), so detection latency and the trace view come
                # from the recorded data alone.
                if (
                    straggler
                    and "inject_ts" in straggle_info
                    and "alert" not in straggle_info
                    and time.monotonic() - last_alert_poll >= 1.0
                ):
                    last_alert_poll = time.monotonic()
                    alert = _poll_straggler_alert(
                        launcher.lighthouse_http_address, victim,
                        after_ts=straggle_info["inject_ts"],
                    )
                    if alert is not None:
                        straggle_info["alert"] = alert
                        fault_log.emit(
                            "alert",
                            group=victim,
                            alert_id=alert.get("id"),
                            kind=alert.get("kind"),
                            replica_id=alert.get("replica_id"),
                            raised_ms=alert.get("raised_ms"),
                            ratio=alert.get("ratio"),
                            step_time_ms=alert.get("step_time_ms"),
                            auto_drained=alert.get("auto_drained"),
                        )
                poll_incidents()
                # Supervisor: restart any group that died for other reasons.
                launcher.supervise_once()
            # Final sweep while the lighthouse is still serving: a trigger
            # that landed in the last poll gap (e.g. the straggler alert
            # raising near window end) still gets its live snapshot.
            last_incident_poll = 0.0
            poll_incidents()

    finally:
        fault_log.close()
        # Env restore runs on EVERY exit path (a spawn failure or ^C must
        # not leave the driver pointing dumps at a dead temp workdir).
        if prev_flight_dir is None:
            os.environ.pop("TPUFT_FLIGHT_DIR", None)
        else:
            os.environ["TPUFT_FLIGHT_DIR"] = prev_flight_dir
    stats = _scenario_stats(workdir, metrics_path, kill_events, plan)
    stats["flight"] = _flight_stats(workdir, assert_dump=bool(kill_events))
    if straggler:
        stats["straggler"] = _straggler_stats(
            metrics_path, straggle_info, victim, plan
        )
    stats["incident"] = _incident_stats(
        workdir, metrics_path, incident_bundles, victim, plan
    )
    return stats


def _incident_stats(
    workdir: str,
    metrics_path: str,
    incident_bundles: dict,
    victim: str | None,
    plan: dict | None,
) -> dict | None:
    """Finalizes every captured incident bundle (fold in the shutdown
    dumps, compute verdicts) and — for injected-fault plans — ASSERTS the
    auto-capture contract: a bundle exists, its verdict names the
    injected victim group, and (kill plans) >= 90% of the measured lost
    wall time is charged to the matching cause."""
    from torchft_tpu.obs import incident as obs_incident

    if not incident_bundles:
        if plan is not None and plan.get("type") != "drain":
            # A fault was injected but nothing triggered: the auto-capture
            # contract is broken (kills must trip replica_stale; straggler
            # plans trip alert:straggler when the sentinel detects).
            # Drains are PLANNED departures — no incident by design.
            raise AssertionError(
                f"injected fault ({plan.get('type')}) produced no incident "
                "trigger on /incident.json — auto-capture contract broken"
            )
        return None
    events = _read_events(metrics_path)
    out: dict = {"bundles": []}
    named_victim = False
    for bundle in sorted(incident_bundles):
        manifest = obs_incident.finalize_bundle(bundle, workdir, events=events)
        v = manifest.get("verdict", {})
        out["bundles"].append({"path": bundle, "verdict": v})
        if victim is not None and v.get("replica") == victim:
            named_victim = True
            out["verdict"] = v
    if plan is not None and victim is not None and plan.get("type") != "drain":
        assert named_victim, (
            f"no incident verdict named the injected victim {victim!r}: "
            + json.dumps([b["verdict"] for b in out["bundles"]])
        )
        if plan.get("type") in ("single", "single_spare", "double",
                                "during_heal"):
            cf = out.get("verdict", {}).get("charged_fraction")
            assert cf is None or cf >= 0.9, (
                f"kill verdict charged only {cf} of the lost wall to the "
                "dead window — cause attribution too weak"
            )
    return out


def _flight_stats(workdir: str, assert_dump: bool) -> dict:
    """Flight-recorder dump inventory for one scenario workdir.

    Kill trials ASSERT the black box: the embedded lighthouse must have
    dumped at launcher stop, the dump must parse, and the quorum-transition
    sequence around the SIGKILL must be reconstructable from it — the
    post-mortem contract ISSUE 7's acceptance pins.  Fault-free baselines
    report whatever dumped without asserting (a baseline window forms ONE
    quorum whose membership never changes, which is still >= 1 transition).
    """
    import glob as _glob

    from torchft_tpu.obs import flight as obs_flight

    paths = sorted(
        _glob.glob(os.path.join(workdir, "flight_*.json"))
    )
    lighthouse_paths = [p for p in paths if "lighthouse" in os.path.basename(p)]
    if assert_dump:
        assert lighthouse_paths, (
            f"kill trial left no lighthouse flight-recorder dump in {workdir} "
            "(TPUFT_FLIGHT_DIR contract broken)"
        )
    out: dict = {"paths": paths, "dumps": []}
    for path in paths:
        try:
            dump = obs_flight.load_flight_dump(path)
        except (OSError, ValueError) as e:
            if assert_dump and path in lighthouse_paths:
                raise AssertionError(f"flight dump {path} unparseable: {e}")
            out["dumps"].append({"path": path, "ok": False})
            continue
        events = obs_flight.flight_events(dump)
        transitions = obs_flight.quorum_transitions(events)
        out["dumps"].append(
            {
                "path": path,
                "ok": True,
                "server": dump.get("server"),
                "recorded": dump.get("recorded"),
                "events": len(events),
                "quorum_transitions": len(transitions),
            }
        )
        if "lighthouse" in os.path.basename(path):
            out["lighthouse_dump"] = path
            out["quorum_transitions"] = transitions[-8:]
    if assert_dump:
        assert out.get("quorum_transitions"), (
            "lighthouse flight dump holds no quorum_formed transitions — "
            "cannot reconstruct the kill post-mortem"
        )
    return out


def _poll_straggler_alert(http_address: str, victim: str, after_ts: float = 0.0):
    """First straggler alert for the victim group raised AFTER ``after_ts``
    on the lighthouse's /alerts.json, or None.  The time filter keeps a
    stale pre-injection alert (e.g. one the warmup gate would normally
    suppress) from masquerading as the injection's detection.  Any failure
    reads as 'not yet' — the poll runs inside the measured window and must
    never abort the trial."""
    from torchft_tpu.launch import fetch_alerts

    alerts = fetch_alerts(http_address)
    if alerts is None:
        return None
    for alert in alerts.get("alerts", []):
        if alert.get("kind") != "straggler":
            continue
        if float(alert.get("raised_ms", 0)) / 1e3 < after_ts:
            continue
        if str(alert.get("replica_id", "")).split(":", 1)[0] == victim:
            return alert
    return None


def _straggler_stats(
    metrics_path: str, info: dict, victim: str, plan: dict
) -> dict:
    """Sentinel scorecard for one straggler trial: detection latency (wall
    seconds AND victim steps vs the grace budget) plus the post-injection
    cluster commit rate — the number the auto-drain run must beat the
    no-sentinel run on."""
    from torchft_tpu.obs import report as obs_report

    events = _read_events(metrics_path)
    # Same per-group commit timelines the goodput accounting uses — one
    # implementation of the commit-record semantics (obs/report.py).
    commits = obs_report.commit_timelines(events)
    try:
        grace = max(1, int(os.environ.get("TPUFT_STRAGGLER_GRACE_STEPS", "5")))
    except ValueError:
        grace = 5
    try:
        ratio = float(os.environ.get("TPUFT_STRAGGLER_RATIO", "1.5"))
    except ValueError:
        ratio = 1.5
    inject_ts = info.get("inject_ts")
    alert = info.get("alert")
    out: dict = {
        "auto_drain": bool(plan.get("auto_drain")),
        "sleep_s": info.get("sleep_s"),
        "inject_ts": inject_ts,
        "grace_steps": grace,
        "ratio_threshold": ratio,
        "detected": alert is not None,
        "alert": alert,
        "detect_latency_s": None,
        "detect_latency_steps": None,
        "detected_within_grace": None,
        "rotated_out": any(ev.get("event") == "straggler_drain" for ev in events),
        "post_inject_commits": None,
        "post_inject_span_s": None,
        "post_inject_rate_per_s": None,
        "pre_inject_rate_per_s": None,
    }
    if inject_ts is None:
        return out
    all_ts = sorted(ts for lst in commits.values() for ts in lst)
    if all_ts:
        t0 = max(min(lst) for lst in commits.values())
        post = [ts for ts in all_ts if ts >= inject_ts]
        pre = [ts for ts in all_ts if t0 <= ts < inject_ts]
        span_post = max(all_ts) - inject_ts
        span_pre = inject_ts - t0
        out["post_inject_commits"] = len(post)
        out["post_inject_span_s"] = round(span_post, 2)
        if span_post > 0:
            out["post_inject_rate_per_s"] = round(len(post) / span_post, 3)
        if span_pre > 0 and pre:
            out["pre_inject_rate_per_s"] = round(len(pre) / span_pre, 3)
    if alert is not None and alert.get("raised_ms"):
        raised_s = float(alert["raised_ms"]) / 1e3
        out["detect_latency_s"] = round(raised_s - inject_ts, 2)
        steps = sum(
            1 for ts in commits.get(victim, []) if inject_ts < ts <= raised_s
        )
        out["detect_latency_steps"] = steps
        # The sentinel's contract is promotion on the grace-th SLOW step
        # observation.  The raw commit count above includes 1-2 boundary
        # commits (steps in flight when the injection landed, whose
        # telemetry still reflects pre-injection pace), so the contract is
        # checked against the count of commits that actually MEASURED slow
        # — victim step_summaries in the window whose busy time shows the
        # injected sleep.
        slow_thresh_ms = float(info.get("sleep_s", 0.0)) * 1e3 * 0.5
        slow_steps = sum(
            1
            for ev in events
            if ev.get("event") == "step_summary"
            and str(ev.get("replica_id", "")).split(":", 1)[0] == victim
            and inject_ts < float(ev.get("ts", 0.0)) <= raised_s
            and float(ev.get("step_time_ms", 0.0) or 0.0) >= slow_thresh_ms
        )
        out["detect_latency_slow_steps"] = slow_steps
        out["detected_within_grace"] = slow_steps <= grace
    return out


def _scenario_stats(
    workdir: str, metrics_path: str, kill_events: list | None, plan: dict | None = None
) -> dict:
    """Parses the metrics stream into per-group committed counts, the
    dead-window goodput fraction, and (single-kill runs) the victim's
    downtime decomposition.

    Counting starts at t0 = the first moment BOTH groups have committed a
    step, so startup JIT compilation is excluded from the counts (not just
    from the wall window).  Group identity is the prefix of replica_id
    ("<group>:<uuid>").

    The PRIMARY goodput number is dead-window based: for every killed
    group, each commit gap that contains >= 1 kill is charged as downtime
    (minus one median step interval — the step it would have taken
    anyway), and goodput = 1 - total_dead / span.  This accounting is
    robust to host-load rate drift (a slow second half of the window does
    not read as FT loss, which is what made the round-4 rate-extrapolated
    fraction spread 0.23 over 3 trials) and it handles single, double, and
    during-heal kill plans identically: overlapping kills simply land in
    one longer gap."""
    kill_events = kill_events or []
    events = _read_events(metrics_path)

    commits: dict[str, list[float]] = {}
    failed: dict[str, list[float]] = {}
    heals = 0
    heal_ms: list[float] = []
    for ev in events:
        if ev.get("event") == "commit":
            group = str(ev.get("replica_id", "")).split(":", 1)[0]
            if ev.get("committed"):
                commits.setdefault(group, []).append(float(ev["ts"]))
            else:
                failed.setdefault(group, []).append(float(ev["ts"]))
        elif ev.get("event") == "heal_fetched":
            heals += 1
            if ev.get("heal_ms") is not None:
                heal_ms.append(float(ev["heal_ms"]))

    if not commits:
        # Metrics stream missing or empty: fall back to the log contract
        # (pinned by tests/test_bench_contract.py) — totals only, no
        # per-group timing.
        committed = 0
        heals = 0
        # Every process log in the workdir: g<i>.log plus spare_<sid>.log —
        # an adopted hot spare keeps writing to its spare log.
        try:
            logs = [n for n in os.listdir(workdir) if n.endswith(".log")]
        except OSError:
            logs = []
        for name in logs:
            try:
                with open(os.path.join(workdir, name), "rb") as f:
                    for line in f:
                        if b"committed=True" in line:
                            committed += 1
                        if b"healing from replica" in line:
                            heals += 1
            except OSError:
                pass
        return {
            "committed_batches": committed,
            "per_group": {},
            "heals": heals,
            "heal_ms": [],
            "kills": len(kill_events),
            "dead_time_s": None,
            "goodput_deadwindow_fraction": None,
            "victim_downtime_s": None,
            "victim_partial_step_s": None,
            "victim_restart_s": None,
            "victim_ft_resume_s": None,
            "victim_heal_transfer_s": None,
            "goodput_self_fraction": None,
            "victims_recovered": False,
            "drain_handoff_gap_s": None,
            "failed_commits_after_kill": {},
            "step_time_stats": None,
            "metrics_stream": False,
        }

    t0 = max(min(ts_list) for ts_list in commits.values())
    t_end = max(max(ts_list) for ts_list in commits.values())
    per_group = {
        g: sum(1 for ts in ts_list if ts >= t0)
        for g, ts_list in sorted(commits.items())
    }

    # Per-step wall-time distributions (perf-trajectory evidence beyond the
    # goodput scalar): commit-interval percentiles per group, plus the
    # Manager's own BUSY-time telemetry (step_summary step_time_ms — wall
    # minus FT waits, the straggler sentinel's signal) where present.
    def _dist(values: list, unit_round: int) -> dict | None:
        ordered = sorted(values)
        if not ordered:
            return None
        return {
            "p50": round(ordered[len(ordered) // 2], unit_round),
            "p99": round(ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))],
                         unit_round),
            "max": round(ordered[-1], unit_round),
            "n": len(ordered),
        }

    step_time_stats: dict[str, dict] = {}
    busy_ms: dict[str, list[float]] = {}
    for ev in events:
        if ev.get("event") == "step_summary" and ev.get("step_time_ms") is not None:
            group = str(ev.get("replica_id", "")).split(":", 1)[0]
            busy_ms.setdefault(group, []).append(float(ev["step_time_ms"]))
    for g, ts_list in sorted(commits.items()):
        ordered = sorted(ts for ts in ts_list if ts >= t0)
        intervals = [b - a for a, b in zip(ordered, ordered[1:])]
        entry: dict = {}
        iv = _dist(intervals, 4)
        if iv:
            entry["interval_s"] = iv
        bz = _dist(busy_ms.get(g, []), 2)
        if bz:
            entry["busy_ms"] = bz
        if entry:
            step_time_stats[g] = entry

    # --- dead-window accounting (all kill plans) -------------------------
    # Shared with the attribution tool: obs/report.py::deadwindow is the
    # single implementation of this arithmetic, so `python -m
    # torchft_tpu.obs.report metrics.jsonl` reproduces the headline
    # fraction from the recorded stream (tests/test_bench_contract.py pins
    # the equality).
    from torchft_tpu.obs import report as obs_report

    dead_total = None
    deadwindow_fraction = None
    victims_recovered = True
    if kill_events:
        dw = obs_report.deadwindow(commits, kill_events)
        dead_total = dw["dead_time_s"] if dw["dead_time_s"] is not None else 0.0
        deadwindow_fraction = dw["fraction"]
        victims_recovered = dw["victims_recovered"]

    # --- cooperative drain: incarnation-aware accounting -----------------
    # The donor keeps COMMITTING after the notice (that is the point), so
    # the gap containing the notice is a normal step gap and the real
    # handoff cost is the incarnation boundary: last donor commit -> first
    # replacement commit.  A negative gap means the replacement overlapped
    # the donor's tail — genuine zero dead time.
    drain_handoff_gap = None
    failed_after_kill: dict[str, int] = {}
    if kill_events:
        first_kill = min(ts for ts, _ in kill_events)
        failed_after_kill = {
            g: sum(1 for ts in ts_list if ts >= first_kill)
            for g, ts_list in sorted(failed.items())
        }
    if plan is not None and plan.get("type") == "drain" and len(kill_events) == 1:
        notice_ts, victim = kill_events[0]
        pre_ids = {
            str(ev.get("replica_id"))
            for ev in events
            if str(ev.get("replica_id", "")).split(":", 1)[0] == victim
            and float(ev["ts"]) <= notice_ts
        }
        old = sorted(
            float(ev["ts"]) for ev in events
            if ev.get("event") == "commit" and ev.get("committed")
            and str(ev.get("replica_id", "")).split(":", 1)[0] == victim
            and str(ev.get("replica_id")) in pre_ids
        )
        new = sorted(
            float(ev["ts"]) for ev in events
            if ev.get("event") == "commit" and ev.get("committed")
            and str(ev.get("replica_id", "")).split(":", 1)[0] == victim
            and str(ev.get("replica_id")) not in pre_ids
        )
        if old and new:
            drain_handoff_gap = min(new) - max(old)
            steps_iv = [b - a for a, b in zip(old, old[1:])]
            med = sorted(steps_iv)[len(steps_iv) // 2] if steps_iv else 0.0
            dead_total = max(0.0, drain_handoff_gap - med)
            victims_recovered = True
            span = t_end - t0
            if span > 0:
                deadwindow_fraction = max(0.0, 1.0 - dead_total / span)
        else:
            victims_recovered = False
            deadwindow_fraction = None

    # --- single-kill decomposition + self-normalized secondary -----------
    victim_downtime = None
    victim_partial_step = None
    victim_restart = None
    victim_ft_resume = None
    victim_heal_transfer = None
    self_fraction = None
    if len(kill_events) == 1:
        kill_ts, victim = kill_events[0]
        before = [ts for ts in commits.get(victim, []) if ts <= kill_ts]
        after = [ts for ts in commits.get(victim, []) if ts > kill_ts]
        if before and after:
            victim_downtime = min(after) - max(before)
            victim_partial_step = kill_ts - max(before)
        # Decompose the dead window so the parts SUM to victim_downtime_s:
        #   downtime = partial_step (last pre-kill commit -> kill)
        #            + restart     (kill -> restarted process's first event)
        #            + ft_resume   (first event -> first post-kill commit).
        # Replica ids are "<group>:<uuid>" with a fresh uuid per
        # incarnation, so the restarted process's first event of any kind
        # marks "process up + JAX initialized"; restart is environment cost
        # (scripted respawn delay + spawn + init), ft_resume is the FT
        # system's own path (rejoin + heal + vote).  Only single-restart
        # trials decompose — if the respawned process died again before its
        # first commit (>1 new incarnation by then), attributing the extra
        # dead window to "FT resume" would be false, so the trial reports
        # None and is counted separately.
        pre_ids = {
            str(ev.get("replica_id"))
            for ev in events
            if str(ev.get("replica_id", "")).split(":", 1)[0] == victim
            and float(ev["ts"]) <= kill_ts
        }
        new_events = [
            (float(ev["ts"]), str(ev.get("replica_id")))
            for ev in events
            if str(ev.get("replica_id", "")).split(":", 1)[0] == victim
            and str(ev.get("replica_id")) not in pre_ids
            and float(ev["ts"]) > kill_ts
        ]
        if new_events and after:
            t_commit = min(after)
            incarnations_by_commit = {
                rid for ts, rid in new_events if ts <= t_commit
            }
            if len(incarnations_by_commit) == 1:
                t_up = min(ts for ts, _ in new_events)
                victim_restart = t_up - kill_ts
                victim_ft_resume = t_commit - t_up
                # Split ft_resume further: heal TRANSFER time is the part
                # striped multi-donor fetch buys down (it scales with donor
                # count), vs rejoin/vote overhead which does not.  The new
                # incarnation's heal_fetched spans before its first commit
                # carry the measured fetch duration.
                heal_transfer_ms = [
                    float(ev["heal_ms"])
                    for ev in events
                    if ev.get("event") == "heal_fetched"
                    and str(ev.get("replica_id")) in incarnations_by_commit
                    and float(ev["ts"]) <= t_commit
                    and ev.get("heal_ms") is not None
                ]
                if heal_transfer_ms:
                    victim_heal_transfer = sum(heal_transfer_ms) / 1e3
        # Self-normalized goodput (SECONDARY; see docstring): the victim's
        # committed count vs its own pre-kill rate extrapolated over the
        # span.  Sensitive to host-load rate drift, which is why the
        # dead-window fraction above is the headline.
        pre = [ts for ts in before if ts >= t0]
        span_pre = kill_ts - t0
        if len(pre) >= 10 and span_pre > 5.0 and t_end > kill_ts:
            rate_pre = len(pre) / span_pre
            expected = rate_pre * (t_end - t0)
            if expected > 0:
                self_fraction = per_group.get(victim, 0) / expected
        if plan is not None and plan.get("type") == "drain":
            # before/after split by the NOTICE time mixes the donor's
            # post-notice commits into "after"; the honest downtime is the
            # incarnation boundary computed above (clamped: an overlapped
            # handoff costs zero, not negative).
            victim_downtime = (
                max(0.0, drain_handoff_gap) if drain_handoff_gap is not None else None
            )
            victim_partial_step = None

    # Goodput cross-check (obs/ledger.py): the commit-count headline vs
    # the ledger/report classification of the SAME stream — two
    # independent accountings that must agree.  >5% disagreement fails
    # the trial: one of them is lying about where the wall time went.
    from torchft_tpu.obs.ledger import crosscheck_goodput

    try:
        crosscheck = crosscheck_goodput(events)
    except Exception as e:  # noqa: BLE001 — a malformed stream already
        # degrades the headline itself; record, don't abort the bench
        crosscheck = {"ok": True, "error": repr(e)}
    assert crosscheck.get("ok", True), (
        f"goodput cross-check failed: dead-window fraction "
        f"{crosscheck.get('deadwindow_fraction')} vs ledger fraction "
        f"{crosscheck.get('ledger_fraction')} disagree by "
        f"{crosscheck.get('disagreement')} (> 0.05) — the commit-count "
        "headline and the ledger accounting diverged on the same stream"
    )

    return {
        "committed_batches": sum(per_group.values()),
        "per_group": per_group,
        "heals": heals,
        "heal_ms": heal_ms,
        "kills": len(kill_events),
        "dead_time_s": round(dead_total, 2) if dead_total is not None else None,
        "goodput_deadwindow_fraction": (
            round(deadwindow_fraction, 4) if deadwindow_fraction is not None else None
        ),
        "goodput_crosscheck": crosscheck,
        "victim_downtime_s": victim_downtime,
        "victim_partial_step_s": victim_partial_step,
        "victim_restart_s": victim_restart,
        "victim_ft_resume_s": victim_ft_resume,
        "victim_heal_transfer_s": victim_heal_transfer,
        "goodput_self_fraction": self_fraction,
        "victims_recovered": victims_recovered,
        "drain_handoff_gap_s": (
            round(drain_handoff_gap, 3) if drain_handoff_gap is not None else None
        ),
        "failed_commits_after_kill": failed_after_kill,
        "step_time_stats": step_time_stats,
        "metrics_stream": True,
    }


def _mean(values) -> float | None:
    vals = [v for v in values if v is not None]
    return round(sum(vals) / len(vals), 2) if vals else None


def _trial_plans(trials: int) -> list:
    """The churn mix: alternating-victim single kills, hot-spare single
    kills (the launcher's spare pool adopts the dead group), back-to-back
    double kills and kill-during-heal trials (the repeated-failure
    scenarios of torchft/manager_integ_test.py:304-352), plus cooperative
    DRAIN trials — the planned-departure path (maintenance/preemption
    notices) measured next to the crash numbers.  >= 10 trials carries
    3 churn, 2 spare, and 2 drain trials."""
    plans: list[dict] = []
    churn = 3 if trials >= 9 else (2 if trials >= 4 else 0)
    spare = 2 if trials >= 8 else 0
    drain = 2 if trials >= 10 else (1 if trials >= 6 else 0)
    singles = max(0, trials - churn - spare - drain)
    for i in range(singles):
        plans.append({"type": "single", "victim": i % 2})
    for i in range(spare):
        plans.append({"type": "single_spare", "victim": (i + 1) % 2})
    for i in range(drain):
        plans.append({"type": "drain", "victim": i % 2})
    for i in range(churn):
        plans.append(
            {"type": "double" if i % 2 == 0 else "during_heal", "victim": (i + 1) % 2}
        )
    return plans


def kill_benchmark() -> dict:
    """Goodput under SIGKILL churn, measured over many scripted-fault trials.

    Round-3 lesson: on this single-core host, TOTAL committed batches is
    the wrong unit — when a group dies, the surviving group's steps get
    FASTER (it stops sharing the CPU and the quorum shrinks).  Round-4
    lesson: even victim-only rate extrapolation is noisy (spread 0.23 over
    3 trials) because host-load drift changes the commit rate within a
    window.  The headline is therefore the DEAD-WINDOW fraction: the
    victim's commit timeline is charged only for the gaps that contain a
    kill, which is exactly the work the fault cost and is insensitive to
    rate drift.  Trials vary the victim and include double-kill and
    kill-during-heal churn; the mean carries a 95% CI."""
    window = float(os.environ.get("TPUFT_BENCH_KILL_WINDOW_S", "45"))
    trials = max(1, int(os.environ.get("TPUFT_BENCH_KILL_TRIALS", "10")))
    base_trials = max(1, int(os.environ.get("TPUFT_BENCH_BASE_TRIALS", "2")))
    plans = _trial_plans(trials)
    # One compile cache shared by every process of all scenarios: restarts
    # must not pay JIT compilation again (on a single-core host a recompile
    # starves every process and would swamp the FT cost being measured).
    bases, kills = [], []
    with tempfile.TemporaryDirectory(prefix="tpuft_bench_cache_") as cache_dir:
        for _ in range(base_trials):
            with tempfile.TemporaryDirectory(prefix="tpuft_bench_nokill_") as d:
                bases.append(
                    _run_scenario(d, window_s=window, plan=None, cache_dir=cache_dir)
                )
        for plan in plans:
            with tempfile.TemporaryDirectory(prefix="tpuft_bench_kill_") as d:
                kills.append(
                    (plan, _run_scenario(d, window_s=window, plan=plan, cache_dir=cache_dir))
                )

    singles = [k for p, k in kills if p["type"] == "single"]
    spare_trials = [k for p, k in kills if p["type"] == "single_spare"]
    churny = [k for p, k in kills if p["type"] in ("double", "during_heal")]
    drain_pairs = [(p, k) for p, k in kills if p["type"] == "drain"]
    drains = [k for _, k in drain_pairs]

    # The headline fraction is computed over the SINGLE-kill trials only:
    # churn trials run a longer window and charge two kills, so mixing the
    # two populations into one mean/spread compares incommensurable
    # numbers.  Churn is summarized separately, and dead_time_per_kill_s
    # (invariant across classes) shows whether repeated failures cost more
    # per kill than isolated ones.
    fractions = [
        k["goodput_deadwindow_fraction"]
        for k in singles
        if k["goodput_deadwindow_fraction"] is not None
    ]
    if fractions:
        unit = "deadwindow_single_kill"
        mean = sum(fractions) / len(fractions)
        if len(fractions) > 1:
            var = sum((f - mean) ** 2 for f in fractions) / (len(fractions) - 1)
            half = 1.96 * (var ** 0.5) / (len(fractions) ** 0.5)
        else:
            half = 0.0
        ci95 = [round(mean - half, 4), round(min(1.0, mean + half), 4)]
    else:
        # Metrics stream unavailable: legacy total-count fraction (noisy).
        unit = "total(legacy)"
        totals_b = sum(b["committed_batches"] for b in bases) / max(1, len(bases))
        fractions = [
            k["committed_batches"] / max(1.0, totals_b) for _, k in kills
        ]
        mean = sum(fractions) / len(fractions)
        ci95 = None

    per_kill = [
        k["dead_time_s"] / k["kills"]
        for p, k in kills
        # victims_recovered guards the same case the fraction guards: an
        # unrecovered victim's gaps were never charged, so its dead time
        # would read ~0 and drag the per-kill mean down spuriously.
        # single_spare trials are excluded too: their per-kill cost is
        # ~2.8 s BY DESIGN, and mixing them in would break the
        # "churn costs the same per kill as singles" comparison this
        # number exists for (they get spare_victim_downtime_s instead).
        if k.get("dead_time_s") is not None
        and k["kills"]
        and k["victims_recovered"]
        and p["type"] not in ("single_spare", "drain")
    ]
    base_victims = [b["per_group"].get("1", 0) for b in bases if b["per_group"]]
    base_spread = (
        (max(base_victims) - min(base_victims)) / max(1, min(base_victims))
        if base_victims
        else None
    )
    downtimes = [k["victim_downtime_s"] for k in singles if k["victim_downtime_s"]]
    decomposed = [k for k in singles if k["victim_restart_s"] is not None]
    heal_ms = sorted(ms for _, k in kills for ms in k["heal_ms"])
    heals = sum(k["heals"] for _, k in kills)
    self_fracs = [
        k["goodput_self_fraction"]
        for k in singles
        if k["goodput_self_fraction"] is not None
    ]
    return {
        "window_s": window,
        "trials": len(kills),
        "trial_plans": [
            {"type": p["type"], "victim": p["victim"]} for p, _ in kills
        ],
        "goodput_unit": unit,
        "goodput_under_kill_fraction": round(mean, 4),
        "goodput_fraction_ci95": ci95,
        "goodput_fraction_trials": [round(f, 4) for f in fractions],
        "goodput_fraction_spread": round(max(fractions) - min(fractions), 4),
        # Churn evidence: trials that killed the victim AGAIN during or
        # right after recovery, and whether every victim still recovered.
        # Their windows are longer and charge 2 kills, so their fractions
        # are listed separately rather than averaged into the headline.
        "multi_restart_trials": len(churny),
        "churn_fractions": [
            round(k["goodput_deadwindow_fraction"], 4)
            for k in churny
            if k["goodput_deadwindow_fraction"] is not None
        ],
        # Invariant across trial classes: dead seconds charged PER KILL.
        # Churn matching singles here means repeated/overlapping failures
        # cost no more per failure than isolated ones.
        "dead_time_per_kill_s": _mean(per_kill),
        "dead_time_per_kill_s_trials": [round(x, 2) for x in per_kill],
        # Hot-spare pool (launch --spares): the dead group's id is handed
        # to a pre-initialized process, removing the respawn + runtime-init
        # floor from the dead window.  Compare spare_victim_downtime_s with
        # victim_downtime_s (cold restart) below.
        "spare_fractions": [
            round(k["goodput_deadwindow_fraction"], 4)
            for k in spare_trials
            if k["goodput_deadwindow_fraction"] is not None
        ],
        "spare_victim_downtime_s": _mean(
            [k["victim_downtime_s"] for k in spare_trials]
        ),
        "spare_victim_restart_s": _mean(
            [k["victim_restart_s"] for k in spare_trials]
        ),
        "spare_victim_ft_resume_s": _mean(
            [k["victim_ft_resume_s"] for k in spare_trials]
        ),
        # Cooperative drain (the planned-departure path): the replacement
        # is pre-warmed at notice time, so the handoff gap — last donor
        # commit to first replacement commit — is the whole cost; a
        # negative gap means the replacement overlapped the donor's tail.
        # drain_survivor_failed_commits MUST be 0: nobody crashed, so no
        # collective ever failed mid-step.
        "drain_fractions": [
            round(k["goodput_deadwindow_fraction"], 4)
            for k in drains
            if k["goodput_deadwindow_fraction"] is not None
        ],
        "drain_victim_downtime_s": _mean(
            [k["victim_downtime_s"] for k in drains]
        ),
        "drain_handoff_gap_s_trials": [
            k["drain_handoff_gap_s"] for k in drains
            if k.get("drain_handoff_gap_s") is not None
        ],
        "drain_dead_time_s": _mean(
            [k["dead_time_s"] for k in drains if k.get("dead_time_s") is not None]
        ),
        "drain_survivor_failed_commits": sum(
            n
            for p, k in drain_pairs
            for g, n in k.get("failed_commits_after_kill", {}).items()
            if g != str(p["victim"])
        ),
        "drains_recovered": all(k["victims_recovered"] for k in drains),
        "kills_total": sum(k["kills"] for _, k in kills),
        # Secondary: the round-4 self-normalized victim fraction (rate
        # extrapolation; sensitive to load drift — kept for comparability).
        "goodput_self_fraction_trials": [round(f, 4) for f in self_fracs],
        # Baseline noise floor: the undisturbed victim count's own
        # run-to-run spread.
        "baseline_victim_committed": base_victims,
        "baseline_relative_spread": (
            round(base_spread, 4) if base_spread is not None else None
        ),
        "victim_downtime_s": _mean(downtimes),
        "victim_downtime_s_trials": [round(d, 2) for d in downtimes],
        # Downtime decomposition — partial_step + restart + ft_resume sums
        # to victim_decomposed_downtime_s over the SAME single-kill trial
        # subset (multi-incarnation trials refuse to decompose).
        # restart = scripted 3 s respawn delay + process spawn + JAX/XLA
        # init (environment floor — any per-step-FT system pays it,
        # including the reference's torchelastic restart); ft_resume =
        # quorum rejoin + live heal + first commit (the part THIS system
        # is responsible for).
        "victim_decomposed_downtime_s": _mean(
            [k["victim_downtime_s"] for k in decomposed]
        ),
        "victim_partial_step_s": _mean(
            [k["victim_partial_step_s"] for k in decomposed]
        ),
        "victim_restart_s": _mean([k["victim_restart_s"] for k in decomposed]),
        "victim_ft_resume_s": _mean([k["victim_ft_resume_s"] for k in decomposed]),
        # ft_resume split: heal TRANSFER (the wire time striped multi-donor
        # fetch scales down with donor count) vs rejoin/vote overhead.
        "victim_heal_transfer_s": _mean(
            [k.get("victim_heal_transfer_s") for k in decomposed]
        ),
        "decomposition_skipped": sum(
            1
            for k in singles
            if k["victim_downtime_s"] is not None and k["victim_restart_s"] is None
        ),
        "heal_ms_median": heal_ms[len(heal_ms) // 2] if heal_ms else None,
        # Per-step wall-time distributions (commit intervals + Manager busy
        # time, p50/p99/max per replica group) so the perf trajectory
        # captures the step-time SHAPE, not just the goodput scalar.
        "step_time_stats_single_trials": [
            k.get("step_time_stats") for k in singles
        ],
        "step_time_stats_baseline": [b.get("step_time_stats") for b in bases],
        "committed_batches_undisturbed": sum(b["committed_batches"] for b in bases),
        "committed_batches_with_kill": sum(k["committed_batches"] for _, k in kills),
        "per_group_undisturbed": [b["per_group"] for b in bases],
        "per_group_with_kill": [k["per_group"] for _, k in kills],
        # A kill run where the victim never healed is NOT a valid goodput
        # measurement — surface it rather than presenting fraction as if the
        # north-star heal path had been exercised.
        "heals_with_kill": heals,
        "heal_verified": all(
            k["heals"] >= 1 and k["victims_recovered"] for _, k in kills
        ),
        # The per-window fraction charges 1-2 kills against a ~45-60 s
        # window — a failure rate ~100x anything realistic.  The victim's
        # downtime is a fixed per-failure cost, so the steady-state goodput
        # loss at a given MTBF is downtime/MTBF; this field states it for
        # hourly failures against BASELINE.md's <5% target.
        "goodput_fraction_at_hourly_failures": (
            round(1 - _mean(downtimes) / 3600.0, 5) if downtimes else None
        ),
    }


def kill_scenario_benchmark(trials: int | None = None) -> dict:
    """Standalone SIGKILL scenario (``--scenario kill``): N single-kill
    trials whose workdirs — including the per-trial ``metrics.jsonl`` — are
    KEPT, so the attribution tool can replay the exact streams the numbers
    came from::

        python bench.py --scenario kill
        python -m torchft_tpu.obs.report <workdir>/kill_0/metrics.jsonl

    The printed goodput fraction and the report's dead-window fraction are
    the same function over the same data (obs/report.py::deadwindow; the
    fault schedule rides in the stream as ``fault`` records), pinned by
    tests/test_bench_contract.py."""
    window = float(os.environ.get("TPUFT_BENCH_KILL_WINDOW_S", "45"))
    trials = trials if trials is not None else max(
        1, int(os.environ.get("TPUFT_BENCH_KILL_TRIALS", "2"))
    )
    out_root = os.environ.get("TPUFT_BENCH_WORKDIR") or tempfile.mkdtemp(
        prefix="tpuft_bench_kill_"
    )
    results = []
    with tempfile.TemporaryDirectory(prefix="tpuft_bench_cache_") as cache_dir:
        for i in range(trials):
            d = os.path.join(out_root, f"kill_{i}")
            os.makedirs(d, exist_ok=True)
            plan = {"type": "single", "victim": i % 2}
            results.append(
                _run_scenario(d, window_s=window, plan=plan, cache_dir=cache_dir)
            )
    fractions = [
        k["goodput_deadwindow_fraction"]
        for k in results
        if k["goodput_deadwindow_fraction"] is not None
    ]
    return {
        "window_s": window,
        "trials": trials,
        "workdir": out_root,
        "metrics_jsonl": [
            os.path.join(out_root, f"kill_{i}", "metrics.jsonl")
            for i in range(trials)
        ],
        "kill_fractions": [round(f, 4) for f in fractions],
        "kill_goodput_fraction": (
            round(sum(fractions) / len(fractions), 4) if fractions else None
        ),
        "victim_downtime_s": _mean([k["victim_downtime_s"] for k in results]),
        "victim_heal_transfer_s": _mean(
            [k.get("victim_heal_transfer_s") for k in results]
        ),
        "heals": sum(k["heals"] for k in results),
        "victims_recovered": all(k["victims_recovered"] for k in results),
        "step_time_stats": [k.get("step_time_stats") for k in results],
    }


def drain_benchmark(trials: int | None = None) -> dict:
    """Standalone cooperative-drain benchmark (``--scenario drain``): N
    drain trials, no kill baseline needed — the criterion is absolute
    (zero survivor commit failures, handoff gap ~one step interval), and
    the numbers land next to the SIGKILL figures in the BENCH_* artifact."""
    window = float(os.environ.get("TPUFT_BENCH_KILL_WINDOW_S", "45"))
    trials = trials if trials is not None else max(
        1, int(os.environ.get("TPUFT_BENCH_DRAIN_TRIALS", "3"))
    )
    results = []
    with tempfile.TemporaryDirectory(prefix="tpuft_bench_cache_") as cache_dir:
        for i in range(trials):
            plan = {"type": "drain", "victim": i % 2}
            with tempfile.TemporaryDirectory(prefix="tpuft_bench_drain_") as d:
                results.append(
                    (plan, _run_scenario(d, window_s=window, plan=plan, cache_dir=cache_dir))
                )
    fractions = [
        k["goodput_deadwindow_fraction"]
        for _, k in results
        if k["goodput_deadwindow_fraction"] is not None
    ]
    return {
        "window_s": window,
        "trials": trials,
        "drain_fractions": [round(f, 4) for f in fractions],
        "drain_goodput_fraction": (
            round(sum(fractions) / len(fractions), 4) if fractions else None
        ),
        "drain_victim_downtime_s": _mean([k["victim_downtime_s"] for _, k in results]),
        "drain_handoff_gap_s_trials": [
            k["drain_handoff_gap_s"] for _, k in results
            if k.get("drain_handoff_gap_s") is not None
        ],
        "drain_dead_time_s": _mean(
            [k["dead_time_s"] for _, k in results if k.get("dead_time_s") is not None]
        ),
        "drain_victim_restart_s": _mean([k["victim_restart_s"] for _, k in results]),
        "drain_victim_ft_resume_s": _mean(
            [k["victim_ft_resume_s"] for _, k in results]
        ),
        "drain_survivor_failed_commits": sum(
            n
            for p, k in results
            for g, n in k.get("failed_commits_after_kill", {}).items()
            if g != str(p["victim"])
        ),
        "drains_recovered": all(k["victims_recovered"] for _, k in results),
        "heals": sum(k["heals"] for _, k in results),
    }


def straggler_benchmark(trials: int | None = None) -> dict:
    """Straggler sentinel benchmark (``--scenario straggler``): paired
    runs on the same schedule — per trial, one run WITHOUT auto-drain (the
    sentinel detects, but the cluster keeps pacing on the slow host for
    the rest of the window: the MegaScale-style goodput killer) and one
    WITH ``TPUFT_STRAGGLER_AUTO_DRAIN=1`` + a hot spare (the sentinel's
    alert triggers the cooperative-drain rotation).  Reported:

    - detection latency, in wall seconds AND victim steps, against the
      ``TPUFT_STRAGGLER_GRACE_STEPS`` budget (the sentinel's contract is
      detection within grace steps of the slowness onset);
    - post-injection cluster commit rate for both runs, and their ratio —
      the goodput the auto-drain rotation recovered.

    Workdirs (with per-trial ``metrics.jsonl``) are KEPT so
    ``tools/trace_export.py`` can render the sentinel arc as a timeline."""
    window = float(
        os.environ.get(
            "TPUFT_BENCH_STRAGGLER_WINDOW_S",
            os.environ.get("TPUFT_BENCH_KILL_WINDOW_S", "45"),
        )
    )
    trials = trials if trials is not None else max(
        1, int(os.environ.get("TPUFT_BENCH_STRAGGLER_TRIALS", "1"))
    )
    # Sentinel knobs for the embedded lighthouse (read from THIS process's
    # environment at Launcher construction).  Grace 3 keeps detection well
    # inside a 45 s window at ~1 s steps.  Every mutation is restored on
    # exit: a later benchmark in the same process must see the documented
    # defaults, not this scenario's tuning.
    prior = {
        k: os.environ.get(k)
        for k in (
            "TPUFT_STRAGGLER_RATIO",
            "TPUFT_STRAGGLER_GRACE_STEPS",
            "TPUFT_STRAGGLER_AUTO_DRAIN",
        )
    }
    os.environ.setdefault("TPUFT_STRAGGLER_RATIO", "1.5")
    os.environ.setdefault("TPUFT_STRAGGLER_GRACE_STEPS", "3")
    # Effective knobs, captured while set (the finally below restores the
    # caller's environment before the summary is built).
    ratio_used = float(os.environ["TPUFT_STRAGGLER_RATIO"])
    grace_used = int(os.environ["TPUFT_STRAGGLER_GRACE_STEPS"])
    out_root = os.environ.get("TPUFT_BENCH_WORKDIR") or tempfile.mkdtemp(
        prefix="tpuft_bench_straggler_"
    )
    results: list[tuple[dict, dict]] = []
    try:
        with tempfile.TemporaryDirectory(prefix="tpuft_bench_cache_") as cache_dir:
            for i in range(trials):
                for auto in (False, True):
                    os.environ["TPUFT_STRAGGLER_AUTO_DRAIN"] = "1" if auto else "0"
                    d = os.path.join(
                        out_root,
                        f"straggler_{i}_{'auto' if auto else 'noauto'}",
                    )
                    os.makedirs(d, exist_ok=True)
                    plan = {
                        "type": "straggler",
                        "victim": i % 2,
                        "auto_drain": auto,
                    }
                    results.append(
                        (plan, _run_scenario(d, window_s=window, plan=plan,
                                             cache_dir=cache_dir))
                    )
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    no_auto = [k["straggler"] for p, k in results if not p["auto_drain"]]
    auto = [k["straggler"] for p, k in results if p["auto_drain"]]
    all_s = no_auto + auto
    latencies_s = [
        s["detect_latency_s"] for s in all_s if s["detect_latency_s"] is not None
    ]
    latencies_steps = [
        s["detect_latency_steps"]
        for s in all_s
        if s["detect_latency_steps"] is not None
    ]
    rate_no = _mean([s["post_inject_rate_per_s"] for s in no_auto])
    rate_auto = _mean([s["post_inject_rate_per_s"] for s in auto])
    recovered = (
        round(rate_auto / rate_no, 3) if rate_no and rate_auto else None
    )
    return {
        "window_s": window,
        "trials": len(results),
        "workdir": out_root,
        "metrics_jsonl": [
            os.path.join(out_root, f"straggler_{i}_{tag}", "metrics.jsonl")
            for i in range(trials)
            for tag in ("noauto", "auto")
        ],
        "sleep_s": float(os.environ.get("TPUFT_BENCH_STRAGGLE_SLEEP_S", "1.0")),
        "ratio_threshold": ratio_used,
        "grace_steps": grace_used,
        "detected_all": all(s["detected"] for s in all_s) if all_s else False,
        "detect_latency_s_trials": latencies_s,
        "detect_latency_s_mean": _mean(latencies_s),
        "detect_latency_steps_trials": latencies_steps,
        "detect_latency_steps_mean": _mean([float(x) for x in latencies_steps]),
        "detect_latency_slow_steps_trials": [
            s.get("detect_latency_slow_steps")
            for s in all_s
            if s.get("detect_latency_slow_steps") is not None
        ],
        "detected_within_grace": (
            all(s["detected_within_grace"] for s in all_s
                if s["detected_within_grace"] is not None)
            if any(s["detected_within_grace"] is not None for s in all_s)
            else False
        ),
        "rotated_out_all": all(s["rotated_out"] for s in auto) if auto else False,
        "pre_inject_rate_per_s": _mean(
            [s["pre_inject_rate_per_s"] for s in all_s]
        ),
        "post_inject_rate_no_drain": rate_no,
        "post_inject_rate_auto_drain": rate_auto,
        "goodput_recovered_fraction": recovered,
        "auto_drain_beats_no_sentinel": (
            rate_auto > rate_no if rate_no and rate_auto else None
        ),
        "per_trial": [
            {"plan": p, **k["straggler"]} for p, k in results
        ],
    }


def slo_benchmark() -> dict:
    """SLO engine + culprit attribution + IncidentWatcher arc
    (``--scenario slo``): two live control-plane cells on the native
    lighthouse, one degraded and one healthy control.

    Degraded cell: replica groups report healthy goodput ledgers over the
    warmup, then the victim turns stall-heavy mid-run (the straggler's
    ledger signature).  Asserted, per the acceptance criteria:

    - a ``goodput_floor`` incident fires whose attribution names the
      VICTIM replica (``culprit_replica``) with a dominant cause and
      positive ``charged_seconds`` — not "cluster";
    - an ``slo_burn`` alert is raised on ``/alerts.json`` carrying the
      same attribution;
    - the IncidentWatcher journals the recommended policy EXACTLY once
      (the flap guard folds the floor trigger and the burn alert into a
      single debounced recommendation).

    Control cell: the same schedule with every replica healthy — zero
    SLO alerts, zero goodput_floor incidents, empty watcher journal.

    The ledgers are pumped through ``ManagerServer.set_ledger`` (real
    heartbeats, real windowing, real attribution — only the train loop
    is synthetic), so the cell runs in seconds instead of warming up
    5 s windows at real step pace."""
    from torchft_tpu._native import LighthouseServer, ManagerServer
    from torchft_tpu.obs.ledger import LOST_CAUSES
    from torchft_tpu.obs.watcher import IncidentWatcher

    prior = {
        k: os.environ.get(k)
        for k in (
            "TPUFT_SLO_TARGET", "TPUFT_SLO_FAST_S", "TPUFT_SLO_SLOW_S",
            "TPUFT_GOODPUT_WARMUP_OBS", "TPUFT_WATCHER_POLL_S",
            "TPUFT_WATCHER_DEBOUNCE_S",
        )
    }
    os.environ["TPUFT_SLO_TARGET"] = "0.92"
    os.environ["TPUFT_SLO_FAST_S"] = "10"
    os.environ["TPUFT_SLO_SLOW_S"] = "20"
    os.environ["TPUFT_GOODPUT_WARMUP_OBS"] = "2"
    out_root = os.environ.get("TPUFT_BENCH_WORKDIR") or tempfile.mkdtemp(
        prefix="tpuft_bench_slo_"
    )
    stall_i = LOST_CAUSES.index("stall")

    def run_cell(name: str, degrade: bool) -> dict:
        workdir = os.path.join(out_root, name)
        os.makedirs(workdir, exist_ok=True)
        lh = LighthouseServer(
            bind="127.0.0.1:0", min_replicas=2, join_timeout_ms=200,
            quorum_tick_ms=20, heartbeat_timeout_ms=5000,
            http_bind="127.0.0.1:0",
        )
        groups = ("0", "1", "2")
        victim = groups[-1]
        mgrs = {
            g: ManagerServer(
                replica_id=f"{g}:slo", lighthouse_addr=lh.address(),
                bind="127.0.0.1:0", world_size=1, heartbeat_interval_ms=25,
            )
            for g in groups
        }
        watcher = IncidentWatcher(
            [lh.http_address()], workdir,
            poll_interval_s=0.05, debounce_s=60.0,
        )
        comp = {g: 0.0 for g in groups}
        stall = {g: 0.0 for g in groups}

        def pump(g: str, d_comp: float, d_stall: float) -> None:
            comp[g] += d_comp
            stall[g] += d_stall
            lost = [0.0] * len(LOST_CAUSES)
            lost[stall_i] = stall[g]
            tot = comp[g] + stall[g]
            mgrs[g].set_ledger(comp[g] / tot if tot else -1.0, comp[g], lost)

        try:
            # Healthy phase: everyone at ~97% goodput for several windows.
            for _ in range(8):
                for g in groups:
                    pump(g, 2.91, 0.09)
                watcher.poll_once(force=True)
                time.sleep(0.08)
            # Degraded phase: the victim's ledger turns stall-heavy.
            for _ in range(14):
                for g in groups:
                    if degrade and g == victim:
                        pump(g, 1.0, 9.0)
                    else:
                        pump(g, 2.91, 0.09)
                watcher.poll_once(force=True)
                time.sleep(0.08)
            time.sleep(0.3)
            watcher.poll_once(force=True)
            alerts = _fetch_json(lh.http_address(), "/alerts.json") or {}
            incidents = _fetch_json(lh.http_address(), "/incident.json") or {}
            slo = _fetch_json(lh.http_address(), "/slo.json") or {}
        finally:
            for m in mgrs.values():
                m.shutdown()
            lh.shutdown()
        journal_path = os.path.join(workdir, "watcher_journal.jsonl")
        journal = []
        if os.path.exists(journal_path):
            with open(journal_path, "r", encoding="utf-8") as f:
                journal = [json.loads(ln) for ln in f if ln.strip()]
        burn = [a for a in alerts.get("alerts", []) if a.get("kind") == "slo_burn"]
        floors = [
            r for r in incidents.get("incidents", [])
            if r.get("reason") == "goodput_floor"
        ]
        return {
            "victim": f"{victim}:slo",
            "slo": {k: slo.get(k) for k in (
                "burn_rate_fast", "burn_rate_slow", "error_budget_remaining",
                "alert_active",
            )},
            "slo_burn_alerts": burn,
            "goodput_floor_incidents": floors,
            "journal": journal,
            "workdir": workdir,
        }

    try:
        degraded = run_cell("degraded", degrade=True)
        control = run_cell("control", degrade=False)
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    victim = degraded["victim"]
    floors = degraded["goodput_floor_incidents"]
    burns = degraded["slo_burn_alerts"]
    journal = degraded["journal"]
    # Acceptance criteria (ISSUE 17): hard asserts, not soft reporting.
    assert floors, "degraded cell recorded no goodput_floor incident"
    named = [r for r in floors if r.get("culprit_replica") == victim]
    assert named, (
        f"goodput_floor verdicts named {[r.get('culprit_replica') for r in floors]},"
        f" not the victim {victim}"
    )
    assert named[0].get("dominant_cause") == "stall", named[0]
    assert float(named[0].get("charged_seconds") or 0.0) > 0.0, named[0]
    assert burns, "degraded cell raised no slo_burn alert"
    assert burns[-1].get("replica_id") == victim, burns[-1]
    assert len(journal) == 1, (
        f"watcher journal must hold exactly one flap-guarded entry, got "
        f"{len(journal)}: {journal}"
    )
    assert journal[0]["policy"] == "drain" and journal[0]["acted"] is False
    assert journal[0]["target"] == victim.split(":", 1)[0]
    assert not control["slo_burn_alerts"], control["slo_burn_alerts"]
    assert not control["goodput_floor_incidents"], (
        control["goodput_floor_incidents"]
    )
    assert not control["journal"], control["journal"]
    return {
        "ok": True,
        "workdir": out_root,
        "victim": victim,
        "dominant_cause": named[0].get("dominant_cause"),
        "charged_seconds": named[0].get("charged_seconds"),
        "burn_rate_fast": degraded["slo"].get("burn_rate_fast"),
        "burn_rate_slow": degraded["slo"].get("burn_rate_slow"),
        "error_budget_remaining": degraded["slo"].get("error_budget_remaining"),
        "journal_entries": len(journal),
        "journal_policy": journal[0]["policy"],
        "control_clean": True,
        "degraded": degraded,
        "control": control,
    }


def _fetch_json(address: str, path: str):
    from torchft_tpu.obs.incident import fetch_json

    return fetch_json(address, path)


def lighthouse_failover_benchmark() -> dict:
    """HA lighthouse failover scenario (``--scenario lighthouse-failover``):
    N lighthouse replicas behind the lease election, G Manager worker
    groups, one SIGKILL of the active leader mid-run.  Criteria (each
    recorded in HA_BENCH.json): quorum formation resumed within one lease
    period of the kill, ZERO failed commits on the (all-healthy) replica
    groups, straggler-sentinel state and /metrics history intact on the
    new leader at epoch+1, the takeover visible as a
    ``lighthouse_failover`` event in the obs stream, and any remaining
    standby still answering as a follower (no dual-serving).  The heavy
    lifting lives in bench_ha.py (quick mode is tier-1's
    test_ha_quick_smoke)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import bench_ha
    finally:
        sys.path.pop(0)
    workdir = os.environ.get("TPUFT_BENCH_WORKDIR") or tempfile.mkdtemp(
        prefix="tpuft_bench_ha_"
    )
    payload = bench_ha.run_failover(
        workdir,
        lighthouses=int(os.environ.get("TPUFT_BENCH_HA_LIGHTHOUSES", "3")),
        groups=int(os.environ.get("TPUFT_BENCH_HA_GROUPS", "2")),
        lease_ms=int(os.environ.get("TPUFT_BENCH_HA_LEASE_MS", "1500")),
        window_s=float(os.environ.get("TPUFT_BENCH_HA_WINDOW_S", "30")),
    )
    payload["workdir"] = workdir
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "HA_BENCH.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    return payload


def scale_benchmark() -> dict:
    """O(dozens)-group scale scenario (``--scenario scale``): control-plane
    cells at N in {4, 8, 16, 32} JAX-free Manager groups against one native
    lighthouse (quorum-formation / heartbeat-fan-in / scrape-cost
    histograms vs N, with a correlated half-N SIGKILL preemption wave at
    the largest N asserting quorum reformation, a flight-recorder
    reconstruction of the wave, and zero leaked fds), plus the
    flat-ring-vs-ring2d data-plane sweep on a shaped 60 ms-RTT link.  The
    heavy lifting lives in bench_scale.py (quick mode is tier-1's
    test_scale_quick_smoke); writes SCALE_BENCH.json."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import bench_scale
    finally:
        sys.path.pop(0)
    payload = bench_scale.run_full(
        ns=[int(n) for n in os.environ.get(
            "TPUFT_BENCH_SCALE_NS", "4,8,16,32").split(",")],
        window_s=float(os.environ.get("TPUFT_BENCH_SCALE_WINDOW_S", "10")),
        mbps=float(os.environ.get("TPUFT_BENCH_SCALE_MBPS", "200")),
        rtt_ms=float(os.environ.get("TPUFT_BENCH_SCALE_RTT_MS", "60")),
        trials=int(os.environ.get("TPUFT_BENCH_SCALE_TRIALS", "2")),
    )
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "SCALE_BENCH.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    return payload


def diloco_benchmark() -> dict:
    """Streaming semi-sync scenario (``--scenario diloco``): 2 replica
    groups on a shaped 60 ms-RTT link; inner-step throughput with a
    concurrent background fragment sync (int8+EF wire) vs the blocking
    port's stall vs a no-sync ceiling, plus the quantization-error-vs-
    convergence drift cell (int8+EF vs bf16 vs f32 over many rounds).
    The heavy lifting lives in bench_diloco.py (quick mode is tier-1's
    test_diloco_quick_smoke); writes DILOCO_BENCH.json."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import bench_diloco
    finally:
        sys.path.pop(0)
    payload = bench_diloco.run_full(
        rounds=int(os.environ.get("TPUFT_BENCH_DILOCO_ROUNDS", "6")),
        sync_every=int(os.environ.get("TPUFT_BENCH_DILOCO_SYNC_EVERY", "24")),
        inner_ms=float(os.environ.get("TPUFT_BENCH_DILOCO_INNER_MS", "50")),
        model_mb=float(os.environ.get("TPUFT_BENCH_DILOCO_MODEL_MB", "2")),
        mbps=float(os.environ.get("TPUFT_BENCH_DILOCO_MBPS", "200")),
        rtt_ms=float(os.environ.get("TPUFT_BENCH_DILOCO_RTT_MS", "60")),
    )
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "DILOCO_BENCH.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    return payload


def elastic_benchmark() -> dict:
    """Elastic quorum scenario (``--scenario elastic``): a seeded
    spot-market arrival/departure trace over live Manager groups with the
    elastic batch engine holding the global batch constant — cooperative
    drains + hot-admit joins crossing the ring2d/ring boundary both ways,
    EC re-shard at every transition, scored by the goodput ledger's commit
    stream against a fixed-size no-churn oracle.  The heavy lifting lives
    in bench_elastic.py (quick mode is tier-1's test_elastic_quick_smoke);
    writes ELASTIC_BENCH.json."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import bench_elastic
    finally:
        sys.path.pop(0)
    payload = bench_elastic.run_full(
        workdir=os.environ.get("TPUFT_BENCH_WORKDIR"),
        seed=int(os.environ.get("TPUFT_BENCH_ELASTIC_SEED", "20")),
        global_batch=int(os.environ.get("TPUFT_BENCH_ELASTIC_GLOBAL_BATCH", "32")),
        per_sample_s=float(
            os.environ.get("TPUFT_BENCH_ELASTIC_PER_SAMPLE_S", "0.02")
        ),
    )
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "ELASTIC_BENCH.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    return payload


def main() -> None:
    # The chip result is computed, assembled, and (on any kill-scenario
    # failure) still printed first: a failure on the subprocess-heavy kill
    # path must never discard the on-chip measurement again (round 2 lost its
    # numbers exactly that way).
    chip = chip_benchmark()
    result = {
        "metric": "ft_train_goodput",
        "value": chip["ft_tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": None,
        "detail": {
            **chip,
            "baseline_semantics": "vs_baseline = dead-window goodput under "
            "SIGKILL: over each single-kill trial window, every commit gap "
            "of the killed group that contains the kill is charged as "
            "downtime (minus one median step interval) and goodput = "
            "1 - dead/span; the mean over single-kill trials carries a 95% "
            "CI.  Churn trials (back-to-back double kills and "
            "kill-during-heal, multi_restart_trials) run longer windows "
            "with 2 kills, so they are summarized separately "
            "(churn_fractions) and compared through the class-invariant "
            "dead_time_per_kill_s — churn matching singles there means "
            "repeated failures cost no more per failure.  Dead-window "
            "accounting is insensitive to host-load rate drift, which made "
            "earlier rate-extrapolated fractions spread 0.23 over 3 trials "
            "on this 1-core host.  Context for the absolute value: each "
            "window charges a kill per ~45 s (~100x any realistic failure "
            "rate), and victim_restart_s shows most of the dead window is "
            "the environment's process-respawn + JAX-init floor that ANY "
            "per-step-FT system pays — the FT resume itself "
            "(victim_ft_resume_s: rejoin + live heal + commit) is "
            "sub-second.  goodput_fraction_at_hourly_failures restates the "
            "measured downtime against BASELINE.md's <5% target at a "
            "realistic failure rate.  Drain trials (drain_fractions) "
            "measure the PLANNED-departure path: the launcher pre-warms a "
            "replacement at notice time and the donor finishes its step "
            "and exits, so the cost is the donor-to-replacement commit "
            "gap (drain_handoff_gap_s_trials; negative = overlapped) and "
            "survivors must log zero failed commits "
            "(drain_survivor_failed_commits).  The reference publishes no "
            "absolute numbers.",
        },
    }
    try:
        large = large_chip_benchmark()
        if large is not None:
            result["detail"]["large_model"] = large
    except Exception as e:  # noqa: BLE001
        result["detail"]["large_model_error"] = repr(e)
    try:
        kill = kill_benchmark()
    except Exception as e:  # noqa: BLE001
        result["detail"]["kill_benchmark_error"] = repr(e)
        print(json.dumps(result))
        raise
    result["vs_baseline"] = kill["goodput_under_kill_fraction"]
    result["detail"].update(kill)
    print(json.dumps(result))


def selftest() -> None:
    """Fast structural check (no chip, no subprocess windows): verifies both
    scenario entry points are callable with their real signatures so a
    refactor cannot silently break the headline artifact again."""
    import inspect

    sig = inspect.signature(_run_scenario)
    assert list(sig.parameters) == ["workdir", "window_s", "plan", "cache_dir"]
    inspect.signature(kill_benchmark).bind()
    inspect.signature(chip_benchmark).bind()
    inspect.signature(drain_benchmark).bind()
    inspect.signature(kill_scenario_benchmark).bind()
    inspect.signature(straggler_benchmark).bind()
    inspect.signature(slo_benchmark).bind()
    inspect.signature(lighthouse_failover_benchmark).bind()
    inspect.signature(scale_benchmark).bind()
    inspect.signature(diloco_benchmark).bind()
    inspect.signature(elastic_benchmark).bind()
    plans = _trial_plans(10)
    assert len(plans) == 10
    assert {p["type"] for p in plans} == {
        "single", "single_spare", "drain", "double", "during_heal"
    }
    assert {p["victim"] for p in plans} == {0, 1}
    assert sum(p["type"] in ("double", "during_heal") for p in plans) >= 3
    assert sum(p["type"] == "drain" for p in plans) >= 2
    print("bench selftest ok")


if __name__ == "__main__":
    if "--selftest" in sys.argv:
        selftest()
    elif "--scenario" in sys.argv:
        which = sys.argv[sys.argv.index("--scenario") + 1:]
        if not which or which[0] not in (
            "drain", "kill", "straggler", "slo", "lighthouse-failover",
            "scale", "diloco", "elastic",
        ):
            print(f"unknown --scenario {which[:1] or '(missing)'}", file=sys.stderr)
            sys.exit(2)
        if which[0] == "elastic":
            elastic = elastic_benchmark()
            print(
                json.dumps(
                    {
                        "metric": "elastic_goodput",
                        "value": elastic["goodput_ratio_vs_oracle"],
                        "unit": "goodput_fraction_of_fixed_size_oracle",
                        "detail": {
                            "ok": elastic["ok"],
                            "max_transition_dead_s": elastic[
                                "max_transition_dead_s"
                            ],
                            "survivor_failed_commits": elastic[
                                "survivor_failed_commits"
                            ],
                            "constant_global_batch": elastic[
                                "constant_global_batch"
                            ],
                            "crossover_exercised": elastic[
                                "crossover_exercised"
                            ],
                        },
                    }
                )
            )
        elif which[0] == "diloco":
            diloco = diloco_benchmark()
            print(
                json.dumps(
                    {
                        "metric": "diloco_overlap",
                        "value": diloco["overlap"][
                            "inner_throughput_ratio_streaming_vs_nosync"
                        ],
                        "unit": "inner_throughput_fraction_of_nosync",
                        "detail": {
                            "ok": diloco["ok"],
                            "overlap": diloco["overlap"],
                            "quant": diloco["quant"],
                        },
                    }
                )
            )
        elif which[0] == "scale":
            scale = scale_benchmark()
            print(
                json.dumps(
                    {
                        "metric": "scale",
                        "value": scale["summary"].get("ring2d_speedup_by_n"),
                        "unit": "ring2d_speedup_by_group_count",
                        "detail": scale["summary"],
                    }
                )
            )
        elif which[0] == "lighthouse-failover":
            ha = lighthouse_failover_benchmark()
            print(
                json.dumps(
                    {
                        "metric": "lighthouse_failover",
                        "value": ha.get("takeover_s"),
                        "unit": "seconds_to_takeover",
                        "detail": ha,
                    }
                )
            )
        elif which[0] == "slo":
            slo = slo_benchmark()
            print(
                json.dumps(
                    {
                        "metric": "slo_attribution",
                        "value": slo["charged_seconds"],
                        "unit": "charged_seconds_on_named_culprit",
                        "detail": slo,
                    }
                )
            )
        elif which[0] == "straggler":
            straggler = straggler_benchmark()
            print(
                json.dumps(
                    {
                        "metric": "straggler_sentinel",
                        "value": straggler["detect_latency_steps_mean"],
                        "unit": "steps_to_detect",
                        "detail": straggler,
                    }
                )
            )
        elif which[0] == "drain":
            drain = drain_benchmark()
            print(
                json.dumps(
                    {
                        "metric": "drain_goodput",
                        "value": drain["drain_goodput_fraction"],
                        "unit": "deadwindow_drain_fraction",
                        "detail": drain,
                    }
                )
            )
        else:
            kill = kill_scenario_benchmark()
            print(
                json.dumps(
                    {
                        "metric": "kill_goodput",
                        "value": kill["kill_goodput_fraction"],
                        "unit": "deadwindow_single_kill_fraction",
                        "detail": kill,
                    }
                )
            )
    else:
        main()
