"""Headline benchmark: fault-tolerant training goodput, measured honestly.

Three configurations:

  raw   — the compiled train step alone on the local chip (no FT machinery).
  ft    — the full per-step fault-tolerance loop (native Lighthouse + Manager,
          async quorum, cross-group allreduce path, two-phase commit vote,
          checkpoint-transport gating) on the same chip, one replica group.
  kill  — the north-star scenario (BASELINE.md): two replica-group processes
          with restart supervisors on the CPU platform, one killed with
          SIGKILL mid-run and healed live from its peer; goodput is committed
          work over a fixed wall-clock window relative to an identical run
          without the kill.

Timing discipline: on the axon TPU tunnel ``jax.block_until_ready`` does NOT
wait for device completion (measured: a chained-matmul loop "finishes" at 13x
the chip's peak FLOP/s) — every measurement here therefore ends with a host
materialization of a value data-dependent on the whole step chain, and the
raw/ft numbers carry an MFU plausibility gate: if measured MFU exceeds 100%
of the chip's peak the benchmark fails loudly instead of reporting garbage.

Prints ONE JSON line:
  value        = FT training goodput on the chip (tokens/sec)
  vs_baseline  = goodput-under-kill fraction (committed work with one
                 SIGKILL + heal vs the same window undisturbed).  The
                 reference publishes no absolute numbers (BASELINE.md); its
                 design target is <5% goodput loss => vs_baseline >= 0.95.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

# (device_kind substring, bf16 peak FLOP/s) — checked in order.
# NOTE: v5e's widely-quoted 394 TFLOP/s is the INT8 figure; bf16 peak is
# 197 TFLOP/s.  Rounds 1-3 used 394 here, which understated MFU by 2x and
# manufactured the "4x off roofline" mystery — per-op profiling (round 4)
# shows the big bf16 matmul fusions sustaining ~187 TFLOP/s, i.e. ~95% of
# the real peak, which is what pinned the error to this table.
_PEAKS = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e reports "TPU v5 lite"
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
]


def _peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for sub, peak in _PEAKS:
        if sub in kind:
            return peak
    return None


# ---------------------------------------------------------------------------
# On-chip: raw vs FT per-step goodput.
# ---------------------------------------------------------------------------


def flagship_config():
    """The headline benchmark model: (TransformerConfig, batch_size, seq).

    Shared with tools/profile_step.py so the per-op profile always
    corresponds to the shape the recorded numbers describe."""
    from torchft_tpu.models import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=32000,
        d_model=768,
        n_layers=12,
        # head_dim 128 = TPU lane width: the pallas flash-attention kernel
        # engages (d_head 64 falls back to XLA S^2 attention) and MXU tiles
        # are full.  Measured on v5e: 12 heads x 64 -> 18.3% MFU, 6 x 128 ->
        # 23.4% at identical param count.
        n_heads=6,
        n_kv_heads=6,
        d_ff=2048,
        max_seq=1024,
        # 134M params at batch 16 fits HBM without rematerialization; remat
        # would recompute every layer in backward (~4/3 the FLOPs) to save
        # memory this config doesn't need.
        remat=False,
        # Full unroll of the layer stack: XLA fuses/pipelines across layer
        # boundaries, and >= n_layers takes the static-Python-loop path
        # (constant-folded layer indexing — kills ~17 ms/step of
        # dynamic-update-slice grad writes the scan form leaves behind).
        # Measured on v5e at this config: scan 158 ms/step (22.7% MFU) ->
        # scan-unroll 141 ms (25.4%) -> static loop 131 ms (27.3%).
        # Partial unroll (4) was slower than any of these.
        scan_unroll=12,
    )
    return cfg, 16, 1024


def chip_benchmark() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchft_tpu.models import init_params, loss_fn
    from torchft_tpu.parallel import TrainStep, ft_init_mesh

    cfg, batch_size, seq = flagship_config()
    tokens_per_step = batch_size * seq

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch_size, seq)), dtype=jnp.int32
    )
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}

    params = init_params(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(x.size) for x in jax.tree.leaves(params))
    # 6N per token for the dense path + causal attention term (6*L*s*d).
    flops_per_step = (6 * n_params + 6 * cfg.n_layers * seq * cfg.d_model) * tokens_per_step

    device = jax.devices()[0]
    peak = _peak_flops(device)

    ftmesh = ft_init_mesh({"data": 1}, devices=[device])
    tx = optax.adamw(3e-4)
    step = TrainStep(ftmesh, tx, lambda p, b: loss_fn(p, b, cfg))

    def fetch(x) -> float:
        # Host materialization is the only trustworthy completion barrier on
        # this platform (see module docstring).
        return float(np.asarray(x))

    # -- raw --------------------------------------------------------------
    state = {"params": params, "opt": step.init_opt_state(params)}

    def raw_step():
        state["params"], state["opt"], loss = step.full_step(
            state["params"], state["opt"], batch
        )
        return loss

    for _ in range(3):  # compile + warmup
        loss = raw_step()
    fetch(loss)

    # Estimate step time to size the measured run (>= ~6 s of device time,
    # and never fewer than 20 steps: at ~240 ms/step an 8-step window showed
    # ±1% run-to-run noise — larger than the FT overhead being measured).
    t0 = time.perf_counter()
    fetch(raw_step())
    est = max(1e-3, time.perf_counter() - t0)
    steps = max(20, min(200, int(6.0 / est)))

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = raw_step()
    fetch(loss)  # loss depends on params_{k-1}: forces the whole chain
    raw_dt = time.perf_counter() - t0
    raw_tps = tokens_per_step * steps / raw_dt
    raw_mfu = (flops_per_step * steps / raw_dt / peak) if peak else None

    if raw_mfu is not None and raw_mfu > 1.0:
        print(
            json.dumps(
                {
                    "metric": "ft_train_goodput",
                    "value": 0,
                    "unit": "tokens/sec",
                    "vs_baseline": 0,
                    "error": f"implausible measurement: raw MFU {raw_mfu:.2f} "
                    f"exceeds 100% of {device.device_kind} peak — timing is "
                    "not capturing real device execution",
                }
            )
        )
        sys.exit(1)

    # -- ft (one replica group, full stack) -------------------------------
    from torchft_tpu._native import LighthouseServer
    from torchft_tpu.checkpointing.http_transport import HTTPTransport
    from torchft_tpu.collectives import TCPCollective
    from torchft_tpu.manager import Manager

    lighthouse = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=100
    )
    params2 = init_params(jax.random.PRNGKey(0), cfg)
    state2 = {"params": params2, "opt": step.init_opt_state(params2)}
    manager = Manager(
        collective=TCPCollective(timeout=30.0),
        load_state_dict=lambda sd: state2.update(sd),
        state_dict=lambda: dict(state2),
        min_replica_size=1,
        rank=0,
        world_size=1,
        replica_id="bench",
        lighthouse_addr=lighthouse.address(),
        checkpoint_transport=HTTPTransport(timeout=30.0),
    )
    ftmesh.manager = manager

    def ft_one_step():
        manager.start_quorum()
        state2["params"], state2["opt"], loss, committed = step.ft_step(
            state2["params"], state2["opt"], batch
        )
        assert committed, "bench step failed to commit"
        return loss

    try:
        for _ in range(3):
            loss = ft_one_step()
        fetch(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = ft_one_step()
        fetch(loss)
        ft_dt = time.perf_counter() - t0
    finally:
        manager.shutdown()
        lighthouse.shutdown()

    ft_tps = tokens_per_step * steps / ft_dt
    ft_mfu = (flops_per_step * steps / ft_dt / peak) if peak else None

    return {
        "device": str(device.device_kind),
        "model": f"transformer-lm 12L d768 bf16 seq{seq} batch{batch_size} "
        f"({n_params/1e6:.0f}M params)",
        "steps_timed": steps,
        "raw_tokens_per_sec": round(raw_tps, 1),
        "ft_tokens_per_sec": round(ft_tps, 1),
        "ft_step_ms": round(ft_dt / steps * 1000, 2),
        "raw_step_ms": round(raw_dt / steps * 1000, 2),
        "ft_overhead_fraction": round(1 - ft_tps / raw_tps, 4),
        "raw_mfu": round(raw_mfu, 4) if raw_mfu is not None else None,
        "ft_mfu": round(ft_mfu, 4) if ft_mfu is not None else None,
    }


# ---------------------------------------------------------------------------
# Goodput under kill -9 (the BASELINE.md north-star scenario).
# ---------------------------------------------------------------------------


def _run_scenario(
    workdir: str, window_s: float, kill_at_s: float | None, cache_dir: str
) -> dict:
    """Two supervised replica-group processes; optionally SIGKILL group 1 at
    kill_at_s into the measurement window (supervisor restarts it, it heals
    live from group 0).  Returns committed-batch counts parsed from the logs.

    The measurement window only starts once BOTH groups have committed a
    step: startup JIT compilation is excluded from both scenarios, and a
    shared persistent compilation cache keeps the post-kill restart from
    paying it again (on this single-core host a restart recompile starves
    every process, which would swamp the FT cost being measured).

    Process management is the framework's own Launcher (torchft_tpu/launch.py)
    — the same supervisor a user gets from ``python -m torchft_tpu.launch``;
    the bench only adds the scripted SIGKILL.

    Counting is primarily from the Manager's structured metrics stream
    (metrics.jsonl "commit"/"heal_fetched" events — O_APPEND lines are
    atomic on Linux so both groups share one file); the log-grep remains as
    a cross-checked fallback."""
    repo = os.path.dirname(os.path.abspath(__file__))
    from torchft_tpu.launch import Launcher

    metrics_path = os.path.join(workdir, "metrics.jsonl")
    launcher = Launcher(
        [sys.executable, os.path.join(repo, "examples", "train_ddp.py"),
         "--steps", "1000000"],
        num_groups=2,
        lighthouse="embed",
        min_replicas=1,
        join_timeout_ms=2000,
        log_dir=workdir,
        cache_dir=cache_dir,
        env={
            "JAX_PLATFORMS": None,  # parent may have pinned the TPU platform
            "TPUFT_JAX_PLATFORM": "cpu",  # env alone is overridden by site hooks
            "TPUFT_METRICS_PATH": metrics_path,
        },
        cwd=repo,
    )
    with launcher:
        start = time.monotonic()
        killed = kill_at_s is None
        while time.monotonic() - start < window_s:
            time.sleep(0.25)
            if not killed and time.monotonic() - start >= kill_at_s:
                launcher.kill(1)  # SIGKILL, the real thing
                killed = True
                time.sleep(3.0)  # restart delay: the dead window is real
                launcher.spawn(1)
            # Supervisor: restart any group that died for other reasons.
            launcher.supervise_once()

    committed = 0
    healed = 0
    try:
        with open(metrics_path, "rb") as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("event") == "commit" and ev.get("committed"):
                    committed += 1
                if ev.get("event") == "heal_fetched":
                    healed += 1
    except OSError:
        pass
    if committed == 0:
        # Metrics stream missing or empty: fall back to the log contract
        # (pinned by tests/test_bench_contract.py).  Drop any metrics-derived
        # heal count so the two sources are never mixed.
        healed = 0
        for g in (0, 1):
            path = os.path.join(workdir, f"g{g}.log")
            with open(path, "rb") as f:
                for line in f:
                    if b"committed=True" in line:
                        committed += 1
                    if b"healing from replica" in line:
                        healed += 1
    return {"committed_batches": committed, "heals": healed}


def kill_benchmark() -> dict:
    window = float(os.environ.get("TPUFT_BENCH_KILL_WINDOW_S", "45"))
    # One compile cache shared by every process of both scenarios: the
    # post-kill restart must not pay JIT compilation again (on a single-core
    # host a recompile starves every process and would swamp the FT cost
    # being measured).
    with tempfile.TemporaryDirectory(prefix="tpuft_bench_cache_") as cache_dir:
        with tempfile.TemporaryDirectory(prefix="tpuft_bench_nokill_") as d:
            base = _run_scenario(d, window_s=window, kill_at_s=None, cache_dir=cache_dir)
        with tempfile.TemporaryDirectory(prefix="tpuft_bench_kill_") as d:
            killed = _run_scenario(
                d, window_s=window, kill_at_s=window / 3, cache_dir=cache_dir
            )
    frac = killed["committed_batches"] / max(1, base["committed_batches"])
    return {
        "window_s": window,
        "committed_batches_undisturbed": base["committed_batches"],
        "committed_batches_with_kill": killed["committed_batches"],
        # A kill run where the victim never healed is NOT a valid goodput
        # measurement — surface it rather than presenting fraction as if the
        # north-star heal path had been exercised.
        "heals_with_kill": killed["heals"],
        "heal_verified": killed["heals"] >= 1,
        "goodput_under_kill_fraction": round(frac, 4),
    }


def main() -> None:
    # The chip result is computed, assembled, and (on any kill-scenario
    # failure) still printed first: a failure on the subprocess-heavy kill
    # path must never discard the on-chip measurement again (round 2 lost its
    # numbers exactly that way).
    chip = chip_benchmark()
    result = {
        "metric": "ft_train_goodput",
        "value": chip["ft_tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": None,
        "detail": {
            **chip,
            "baseline_semantics": "vs_baseline = committed work in a "
            "fixed window with one SIGKILL + live heal, relative to "
            "the same window undisturbed (BASELINE.md north star; "
            "target >= 0.95).  The reference publishes no absolute "
            "numbers.",
        },
    }
    try:
        kill = kill_benchmark()
    except Exception as e:  # noqa: BLE001
        result["detail"]["kill_benchmark_error"] = repr(e)
        print(json.dumps(result))
        raise
    result["vs_baseline"] = kill["goodput_under_kill_fraction"]
    result["detail"].update(kill)
    print(json.dumps(result))


def selftest() -> None:
    """Fast structural check (no chip, no subprocess windows): verifies both
    scenario entry points are callable with their real signatures so a
    refactor cannot silently break the headline artifact again."""
    import inspect

    sig = inspect.signature(_run_scenario)
    assert list(sig.parameters) == ["workdir", "window_s", "kill_at_s", "cache_dir"]
    inspect.signature(kill_benchmark).bind()
    inspect.signature(chip_benchmark).bind()
    print("bench selftest ok")


if __name__ == "__main__":
    if "--selftest" in sys.argv:
        selftest()
    else:
        main()
