"""Headline benchmark: fault-tolerant training goodput on the local chip.

Trains the flagship transformer LM (GPT-small class: 12 layers, d=768,
seq 1024, bf16 compute) two ways on the real device:

  raw:  the compiled train step alone (no fault-tolerance machinery);
  ft:   the full per-step fault-tolerance loop — native Lighthouse +
        Manager servers, per-step async quorum, cross-group allreduce path,
        two-phase commit vote, checkpoint-transport gating — exactly the
        train_ddp.py flow, with one replica group on this chip.

Prints ONE JSON line:
  value        = FT training goodput (tokens/sec)
  vs_baseline  = FT goodput / raw goodput — the fault-tolerance overhead
                 fraction.  The reference publishes no absolute numbers
                 (BASELINE.md); its design target is <5% goodput loss, i.e.
                 vs_baseline >= 0.95.
"""

from __future__ import annotations

import json
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchft_tpu.models import TransformerConfig, init_params, loss_fn
    from torchft_tpu.models.transformer import param_axes
    from torchft_tpu.parallel import TrainStep, ft_init_mesh

    cfg = TransformerConfig(
        vocab_size=32000,
        d_model=768,
        n_layers=12,
        n_heads=12,
        n_kv_heads=12,
        d_ff=2048,
        max_seq=1024,
    )
    batch_size, seq = 8, 1024
    tokens_per_step = batch_size * seq

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch_size, seq)), dtype=jnp.int32
    )
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}

    params = init_params(jax.random.PRNGKey(0), cfg)
    ftmesh = ft_init_mesh({"data": 1}, devices=jax.devices()[:1])
    tx = optax.adamw(3e-4)
    step = TrainStep(ftmesh, tx, lambda p, b: loss_fn(p, b, cfg))

    def timed_loop(fn, steps: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(steps):
            out = fn()
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    # -- raw --------------------------------------------------------------
    state = {"params": params, "opt": step.init_opt_state(params)}

    def raw_step():
        state["params"], state["opt"], loss = step.full_step(
            state["params"], state["opt"], batch
        )
        return loss

    for _ in range(3):  # warmup / compile
        raw_step()
    jax.block_until_ready(state["params"])
    steps = 20
    raw_tps = tokens_per_step * steps / timed_loop(raw_step, steps)

    # -- ft ---------------------------------------------------------------
    from torchft_tpu._native import LighthouseServer
    from torchft_tpu.checkpointing.http_transport import HTTPTransport
    from torchft_tpu.collectives import TCPCollective
    from torchft_tpu.manager import Manager

    lighthouse = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=100
    )
    params2 = init_params(jax.random.PRNGKey(0), cfg)
    state2 = {"params": params2, "opt": step.init_opt_state(params2)}
    manager = Manager(
        collective=TCPCollective(timeout=30.0),
        load_state_dict=lambda sd: state2.update(sd),
        state_dict=lambda: dict(state2),
        min_replica_size=1,
        rank=0,
        world_size=1,
        replica_id="bench",
        lighthouse_addr=lighthouse.address(),
        checkpoint_transport=HTTPTransport(timeout=30.0),
    )
    ftmesh.manager = manager

    def ft_one_step():
        manager.start_quorum()
        state2["params"], state2["opt"], loss, committed = step.ft_step(
            state2["params"], state2["opt"], batch
        )
        assert committed, "bench step failed to commit"
        return loss

    try:
        for _ in range(3):
            ft_one_step()
        jax.block_until_ready(state2["params"])
        ft_tps = tokens_per_step * steps / timed_loop(ft_one_step, steps)
    finally:
        manager.shutdown()
        lighthouse.shutdown()

    print(
        json.dumps(
            {
                "metric": "ft_train_goodput",
                "value": round(ft_tps, 1),
                "unit": "tokens/sec",
                "vs_baseline": round(ft_tps / raw_tps, 4),
                "detail": {
                    "model": "transformer-lm 12L d768 bf16 seq1024 batch8",
                    "raw_tokens_per_sec": round(raw_tps, 1),
                    "baseline_semantics": "FT/raw goodput fraction; reference "
                    "publishes no absolute numbers (BASELINE.md), its design "
                    "target is <5% goodput loss (>=0.95)",
                },
            }
        )
    )


if __name__ == "__main__":
    main()
