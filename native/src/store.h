// Key-value rendezvous store with blocking waits.
// The native analogue of the c10d TCPStore the reference relies on for
// process-group rendezvous (torchft/process_group.py:85-104, src/manager.rs:501).
#pragma once

#include <condition_variable>
#include <map>
#include <memory>
#include <mutex>
#include <string>

#include "wire.h"

namespace tpuft {

class StoreServer {
 public:
  explicit StoreServer(std::string bind) : bind_(std::move(bind)) {}
  ~StoreServer();

  bool Start(std::string* err);
  void Shutdown();
  std::string address() const;

 private:
  Status Dispatch(uint16_t method, const std::string& req, Deadline deadline, std::string* resp);

  std::string bind_;
  std::unique_ptr<RpcServer> server_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> kv_;
  bool shutdown_ = false;
};

}  // namespace tpuft
