// Manager: per-replica-group coordination server.
//
// Reference parity: src/manager.rs.  Runs inside the group's rank-0 process.
// Aggregates the group's local ranks: waits until all `world_size` ranks call
// Quorum, performs a single Lighthouse quorum RPC on their behalf, computes
// the per-rank recovery plan, stores per-rank checkpoint metadata, implements
// the all-ranks should_commit vote, heartbeats to the Lighthouse, and exits
// the process on Kill.
#pragma once

#include <condition_variable>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <string>
#include <thread>
#include <vector>

#include "flight.h"
#include "tpuft.pb.h"
#include "wire.h"

namespace tpuft {

struct ManagerOpt {
  std::string replica_id;
  // Lighthouse RPC address, or a comma-separated list of them (HA replica
  // set, docs/wire.md "HA lighthouse"): calls fail over across the list
  // and follow "not the leader" redirects (FailoverRpcClient, wire.h).
  std::string lighthouse_addr;
  std::string bind = "[::]:0";
  // The group's rendezvous store address, advertised in the quorum member.
  std::string store_addr;
  uint64_t world_size = 1;
  // Reference default: 100 ms (torchft/manager.py:107).
  uint64_t heartbeat_interval_ms = 100;
  uint64_t connect_timeout_ms = 10000;
};

// Pure per-rank recovery-plan math over a formed quorum.
// Reference parity: compute_quorum_results, src/manager.rs:381-509.
//   - replica_rank: index of our replica id in the (sorted) participant list;
//   - up-to-date set: participants at max_step; at step 0 with init_sync the
//     set collapses to participant 0 so random init weights are synced;
//   - recovery assignment: recovering replica j heals from
//     up_to_date[(j + group_rank) % |up_to_date|] — the group_rank offset
//     stripes transfer load across sources per local rank;
//   - store striping: local rank r rendezvouses on the store of participant
//     (r % |participants|) to spread store load.
bool ComputeQuorumResults(const std::string& replica_id, int64_t group_rank, const Quorum& quorum,
                          bool init_sync, bool force_recover, ManagerQuorumResponse* resp,
                          std::string* err);

class ManagerServer {
 public:
  explicit ManagerServer(ManagerOpt opt);
  ~ManagerServer();

  bool Start(std::string* err);
  void Shutdown();
  std::string address() const;

  // Live training status pushed by the Python Manager (rank 0) at phase
  // transitions; carried on every subsequent lighthouse heartbeat so the
  // cluster's GET /metrics exposition and dashboard show per-replica step
  // and state without waiting for the next quorum snapshot.  The optional
  // step-time telemetry (rolling busy-time EWMA + last observation, ms; 0 =
  // not reported) feeds the lighthouse's straggler sentinel, and the
  // allreduce payload GB/s the /metrics tpuft_allreduce_gb_per_s gauge —
  // for which 0 IS a report (a committed step that moved no gradient
  // bytes) and only a negative value means "keep the prior reading", so
  // phase-only pushes must use the default.
  // ec_shards_held/ec_shard_step (heartbeat fields 8-9, the erasure-shard
  // inventory) follow the gauge convention: 0 is an authoritative report,
  // negative means "keep the prior reading".  ec_k (field 10) is the EC
  // geometry's data-shard count, the lighthouse coverage sentinel's
  // paging threshold input; same negative-keeps convention.
  // The link health EWMAs (heartbeat fields 11-13, the slow-link
  // sentinel's feed) follow the gauge convention too: 0 is an
  // authoritative "no observation yet / no traffic" report, negative
  // keeps the prior reading for phase-only pushes.
  void SetStatus(int64_t step, const std::string& state,
                 double step_time_ms_ewma = 0.0, double step_time_ms_last = 0.0,
                 double allreduce_gb_per_s = -1.0, int64_t ec_shards_held = -1,
                 int64_t ec_shard_step = -1, int64_t ec_k = -1,
                 double link_recv_gbps = -1.0, double link_send_gbps = -1.0,
                 double link_hop_rtt_ms = -1.0);

  // Goodput ledger push (heartbeat fields 14-16, docs/wire.md "Goodput
  // ledger"): the replica's cumulative productive fraction, productive
  // seconds, and per-cause lost seconds in the pinned taxonomy order
  // (torchft_tpu/obs/ledger.py LOST_CAUSES).  Called once per commit
  // vote by the Python Manager; counters are monotonic per incarnation.
  void SetLedger(double goodput_ratio, double compute_seconds,
                 const double* lost_seconds, int32_t n_causes);

  // RPC handlers (public for in-process tests).
  Status HandleQuorum(const ManagerQuorumRequest& req, Deadline deadline,
                      ManagerQuorumResponse* resp, std::string* err);
  Status HandleCheckpointMetadata(const CheckpointMetadataRequest& req,
                                  CheckpointMetadataResponse* resp, std::string* err);
  Status HandleShouldCommit(const ShouldCommitRequest& req, Deadline deadline,
                            ShouldCommitResponse* resp, std::string* err);

  // Flight-recorder snapshot (newest-first; 0 = all retained), exposed to
  // Python through the capi (`tf_manager_flight_json`).
  std::string FlightJson(size_t limit = 0) { return flight_.Json(limit); }

 private:
  // Outer dispatch: records the server-side RPC span (method, peer,
  // status, duration, trace id) around DispatchInner, which surfaces the
  // trace id from the request it parses anyway (no second parse).
  Status Dispatch(uint16_t method, const std::string& req, Deadline deadline,
                  const std::string& peer, std::string* resp);
  Status DispatchInner(uint16_t method, const std::string& req, Deadline deadline,
                       std::string* resp, std::string* trace_id);
  void HeartbeatLoop();

  ManagerOpt opt_;
  std::unique_ptr<RpcServer> server_;
  // Separate failover clients so a slow quorum call cannot head-of-line
  // block the heartbeat cadence (and vice versa).
  std::unique_ptr<FailoverRpcClient> heartbeat_client_;
  std::unique_ptr<FailoverRpcClient> quorum_client_;

  std::mutex mu_;
  std::condition_variable cv_;
  bool shutdown_ = false;

  // Quorum aggregation round state.  All world_size local ranks must call
  // Quorum; the rank completing the set performs the Lighthouse RPC
  // (reference: src/manager.rs:185-292).
  int64_t round_ = 0;
  std::map<int64_t, ManagerQuorumRequest> round_reqs_;
  int64_t result_round_ = -1;
  Status result_status_ = Status::kOk;
  std::string result_error_;
  Quorum result_quorum_;

  // Latest checkpoint metadata per local rank (served to healing peers).
  std::map<int64_t, std::string> checkpoint_metadata_;

  // Live status for heartbeat enrichment (SetStatus).
  int64_t status_step_ = 0;
  std::string status_state_ = "init";
  double status_step_time_ewma_ms_ = 0.0;
  double status_step_time_last_ms_ = 0.0;
  double status_allreduce_gbps_ = 0.0;
  // Erasure-shard inventory (heartbeat fields 8-9): shards held at the
  // newest encode generation + that generation's step.
  int64_t status_ec_shards_ = 0;
  int64_t status_ec_step_ = 0;
  int64_t status_ec_k_ = 0;
  // Per-neighbor link health (heartbeat fields 11-13, slow-link sentinel).
  double status_link_recv_gbps_ = 0.0;
  double status_link_send_gbps_ = 0.0;
  double status_link_rtt_ms_ = 0.0;
  // Goodput ledger (heartbeat fields 14-16): cumulative productive
  // fraction / seconds and per-cause lost seconds (pinned order).
  double status_goodput_ratio_ = 0.0;
  double status_ledger_compute_s_ = 0.0;
  std::vector<double> status_ledger_lost_s_;
  // Causal trace id of the last quorum round this manager aggregated —
  // stamped onto every lighthouse heartbeat (proto field 7) so the
  // lighthouse's RPC spans correlate with the step in flight.
  std::string status_trace_id_;

  // Control-plane black box: server-side RPC spans + quorum outcomes,
  // dumped to $TPUFT_FLIGHT_DIR on Shutdown.
  FlightRecorder flight_;

  // should_commit barrier per (step) round (reference: src/manager.rs:313-371).
  struct CommitRound {
    std::map<int64_t, bool> votes;
    bool decided = false;
    bool decision = false;
    int64_t handed_out = 0;
  };
  std::map<int64_t, CommitRound> commits_;

  std::thread hb_thread_;
};

}  // namespace tpuft
