#include "lighthouse.h"

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <set>
#include <sstream>

#include "http.h"
#include "log.h"

namespace tpuft {

int64_t NowEpochMs() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

// The goodput ledger's pinned lost-cause taxonomy, in the WIRE ORDER of
// the heartbeat's ledger_lost_seconds vector (proto field 16).  MUST stay
// identical to torchft_tpu/obs/ledger.py LOST_CAUSES — tests/
// test_ledger.py greps both sides, the same pinning discipline as the
// flight-event kinds.  Append-only; never reorder.
constexpr const char* kLedgerCauses[kLedgerCauseCount] = {
    "wire",        "stall", "combine", "shaping",  "quorum_server",
    "quorum_transport", "heal",  "drain",   "other_ft", "resize"};

// ---------------------------------------------------------------------------
// Pure quorum math.  Reference parity: quorum_compute, src/lighthouse.rs:133-261.
// Semantics (in evaluation order):
//   0. draining replicas (cooperative departure announced) are invisible:
//      neither candidates nor counted healthy — the quorum forms without
//      them instantly instead of waiting out join/heartbeat timeouts;
//   1. only replicas with a fresh heartbeat are candidates;
//   2. if any candidate requests shrink_only, membership may not grow beyond
//      the previous quorum;
//   3. "fast quorum": if every member of the previous quorum has re-joined
//      and is healthy, form the quorum immediately (steady-state path);
//   4. otherwise require >= min_replicas, and a strict majority of all
//      currently-heartbeating replicas (split-brain guard);
//   5. wait join_timeout (measured from the round's first joiner) for healthy
//      stragglers that have not re-joined yet, unless all have joined.
// ---------------------------------------------------------------------------
std::optional<std::vector<QuorumMember>> QuorumCompute(TimePoint now, const QuorumState& state,
                                                       const LighthouseOpt& opt,
                                                       std::string* reason) {
  auto hb_timeout = std::chrono::milliseconds(opt.heartbeat_timeout_ms);

  std::set<std::string> healthy;
  for (const auto& [id, last] : state.heartbeats) {
    if (state.draining.count(id)) continue;
    if (now - last < hb_timeout) healthy.insert(id);
  }

  std::vector<QuorumMember> candidates;
  bool shrink_only = false;
  for (const auto& [id, j] : state.participants) {
    if (!healthy.count(id)) continue;
    candidates.push_back(j.member);
    if (j.member.shrink_only()) shrink_only = true;
  }

  std::set<std::string> prev_ids;
  if (state.prev_quorum) {
    for (const auto& m : state.prev_quorum->participants()) prev_ids.insert(m.replica_id());
  }

  if (shrink_only && state.prev_quorum) {
    std::vector<QuorumMember> shrunk;
    for (auto& m : candidates) {
      if (prev_ids.count(m.replica_id())) shrunk.push_back(m);
    }
    candidates = std::move(shrunk);
  }

  std::sort(candidates.begin(), candidates.end(),
            [](const QuorumMember& a, const QuorumMember& b) {
              return a.replica_id() < b.replica_id();
            });

  std::set<std::string> candidate_ids;
  for (const auto& m : candidates) candidate_ids.insert(m.replica_id());

  if (candidates.size() < opt.min_replicas) {
    if (reason) {
      *reason = "need at least " + std::to_string(opt.min_replicas) + " replicas, have " +
                std::to_string(candidates.size());
    }
    return std::nullopt;
  }

  // Fast quorum: every previous member is healthy and has re-joined.
  bool fast = state.prev_quorum && !prev_ids.empty() &&
              std::all_of(prev_ids.begin(), prev_ids.end(), [&](const std::string& id) {
                return candidate_ids.count(id) > 0;
              });
  if (fast) {
    if (reason) *reason = "fast quorum (all previous members present)";
    return candidates;
  }

  // Split-brain guard: require a strict majority of everything heartbeating.
  if (candidates.size() * 2 <= healthy.size()) {
    if (reason) {
      *reason = "potential split brain: only " + std::to_string(candidates.size()) + " of " +
                std::to_string(healthy.size()) + " healthy replicas joined";
    }
    return std::nullopt;
  }

  // All healthy replicas joined -> no reason to wait.
  bool all_joined = std::all_of(healthy.begin(), healthy.end(), [&](const std::string& id) {
    return state.participants.count(id) > 0 ||
           (shrink_only && !prev_ids.count(id));
  });
  if (all_joined) {
    if (reason) *reason = "quorum (all healthy replicas joined)";
    return candidates;
  }

  // Wait for stragglers up to join_timeout from the round's first joiner.
  TimePoint first_join = TimePoint::max();
  for (const auto& [id, j] : state.participants) {
    first_join = std::min(first_join, j.joined_at);
  }
  if (first_join != TimePoint::max() &&
      now - first_join >= std::chrono::milliseconds(opt.join_timeout_ms)) {
    if (reason) *reason = "quorum (join timeout elapsed, proceeding without stragglers)";
    return candidates;
  }
  if (reason) {
    *reason = "waiting for stragglers to join (" + std::to_string(candidates.size()) + "/" +
              std::to_string(healthy.size()) + " healthy joined)";
  }
  return std::nullopt;
}

// ---------------------------------------------------------------------------
// Lighthouse server
// ---------------------------------------------------------------------------

Lighthouse::Lighthouse(LighthouseOpt opt) : opt_(std::move(opt)) {
  // Pre-populate the per-method latency histograms so Dispatch's lookups
  // never mutate the map (lock-free reads against a frozen key set).
  for (uint16_t m : {kLighthouseQuorum, kLighthouseHeartbeat, kLighthouseStatus,
                     kLighthouseEvict, kLighthouseDrain, kLighthouseReplicate,
                     kLighthouseLeaderInfo, kLighthouseRegionDigest,
                     kLighthouseRegions}) {
    rpc_hist_[m];
  }
}

Lighthouse::~Lighthouse() { Shutdown(); }

bool Lighthouse::AdminAllowed(const std::string& token, bool peer_loopback) const {
  if (!admin_token_.empty()) return token == admin_token_;
  return peer_loopback;
}

// ---------------------------------------------------------------------------
// HA role (docs/wire.md "HA lighthouse")
// ---------------------------------------------------------------------------

bool Lighthouse::IsLeaderLocked() const {
  if (!role_leader_) return false;
  // Serve-time lease guard: a leader whose lease lapsed (stalled renewal
  // thread, frozen process resumed) must refuse authoritative answers —
  // a rival may already hold the lease.  0 = no lease (standalone).
  return lease_expires_ms_ == 0 || NowEpochMs() < lease_expires_ms_;
}

std::string Lighthouse::NotLeaderErrLocked() const {
  // kNotLeaderPrefix contract (wire.h): clients parse "leader=<addr>".
  // A leader we can name only when it is NOT ourselves (a demoted/expired
  // leader must not redirect clients back to itself).
  std::string addr, http;
  if (!role_leader_) {
    addr = leader_addr_;
    http = leader_http_;
  }
  return std::string(kNotLeaderPrefix) + "; leader=" + addr + " http=" + http +
         " epoch=" + std::to_string(leader_epoch_);
}

void Lighthouse::SetRole(bool leader, const std::string& leader_addr,
                         const std::string& leader_http, int64_t epoch,
                         int64_t lease_expires_ms) {
  std::lock_guard<std::mutex> lk(mu_);
  bool was = role_leader_;
  role_leader_ = leader;
  leader_addr_ = leader_addr;
  leader_http_ = leader_http;
  leader_epoch_ = epoch;
  lease_expires_ms_ = lease_expires_ms;
  if (was != leader) {
    if (leader) {
      LOGI("lighthouse: became LEADER (epoch %lld, lease until +%lld ms)",
           static_cast<long long>(epoch),
           static_cast<long long>(lease_expires_ms ? lease_expires_ms - NowEpochMs()
                                                   : 0));
    } else {
      LOGW("lighthouse: demoted to FOLLOWER (leader %s, epoch %lld)",
           leader_addr.empty() ? "<unknown>" : leader_addr.c_str(),
           static_cast<long long>(epoch));
    }
    flight_.RecordEvent(kFlightRoleChange,
                        std::string("role=") + (leader ? "leader" : "follower") +
                            " epoch=" + std::to_string(epoch) +
                            " leader_addr=" + leader_addr);
    // Blocked quorum joins on a demoted leader must abort with the
    // redirect instead of waiting out their deadlines.
    quorum_cv_.notify_all();
  }
}

int Lighthouse::Role() {
  std::lock_guard<std::mutex> lk(mu_);
  return IsLeaderLocked() ? 1 : 0;
}

int64_t Lighthouse::LeaderEpoch() {
  std::lock_guard<std::mutex> lk(mu_);
  return leader_epoch_;
}

std::string Lighthouse::SnapshotState() {
  LighthouseReplicateRequest req;
  std::lock_guard<std::mutex> lk(mu_);
  auto* l = req.mutable_leader();
  l->set_leader_address(leader_addr_);
  l->set_leader_http_address(leader_http_);
  l->set_leader_epoch(leader_epoch_);
  l->set_lease_expires_ms(lease_expires_ms_);
  if (state_.prev_quorum) *req.mutable_prev_quorum() = *state_.prev_quorum;
  req.set_quorum_id(state_.quorum_id);
  auto now = Clock::now();
  for (const auto& [id, last] : state_.heartbeats) {
    auto* r = req.add_replicas();
    r->set_replica_id(id);
    r->set_heartbeat_age_ms(
        std::chrono::duration_cast<std::chrono::milliseconds>(now - last).count());
    auto step = hb_step_.find(id);
    if (step != hb_step_.end()) r->set_step(step->second);
    auto st = hb_state_.find(id);
    if (st != hb_state_.end()) r->set_state(st->second);
    auto lc = last_commit_ms_.find(id);
    if (lc != last_commit_ms_.end()) r->set_last_commit_ms(lc->second);
    auto gbps = allreduce_gbps_.find(id);
    if (gbps != allreduce_gbps_.end()) r->set_allreduce_gb_per_s(gbps->second);
    auto ec = ec_shards_.find(id);
    if (ec != ec_shards_.end()) {
      r->set_ec_shard_step(ec->second.first);
      r->set_ec_shards_held(ec->second.second);
      // The latched geometry rides each EC record so a promoted standby's
      // coverage sentinel keeps the same k + 1 threshold.
      r->set_ec_k(ec_k_);
    }
    auto h = health_.find(id);
    if (h != health_.end()) {
      r->set_step_time_ms_ewma(h->second.ewma_ms);
      r->set_step_time_ms_last(h->second.last_ms);
      r->set_straggler_state(h->second.state);
      r->set_straggler_over(h->second.over);
      r->set_straggler_under(h->second.under);
      r->set_straggler_last_step(h->second.last_step);
      r->set_straggler_observations(h->second.observations);
      r->set_straggler_ratio(h->second.ratio);
    }
    auto lh = link_health_.find(id);
    if (lh != link_health_.end()) {
      r->set_link_recv_gbps(lh->second.recv_gbps);
      r->set_link_send_gbps(lh->second.send_gbps);
      r->set_link_hop_rtt_ms(lh->second.rtt_ms);
      r->set_link_state(lh->second.state);
      r->set_link_over(lh->second.over);
      r->set_link_under(lh->second.under);
      r->set_link_ratio(lh->second.ratio);
      r->set_link_last_step(lh->second.last_step);
      r->set_link_observations(lh->second.observations);
    }
    if (state_.draining.count(id)) {
      r->set_draining(true);
      auto dl = drain_deadline_ms_.find(id);
      if (dl != drain_deadline_ms_.end()) r->set_drain_deadline_ms(dl->second);
    }
    auto led = ledger_.find(id);
    if (led != ledger_.end()) {
      r->set_goodput_ratio(led->second.goodput_ratio);
      r->set_ledger_compute_seconds(led->second.compute_s);
      for (size_t i = 0; i < kLedgerCauseCount; ++i) {
        r->add_ledger_lost_seconds(led->second.lost_s[i]);
      }
    }
  }
  // Cluster ledger bank: a promoted standby's /goodput.json must keep the
  // totals of incarnations that departed before the failover.
  req.set_ledger_banked_compute_seconds(ledger_banked_compute_);
  for (size_t i = 0; i < kLedgerCauseCount; ++i) {
    req.add_ledger_banked_lost_seconds(ledger_banked_lost_[i]);
  }
  for (const auto& a : alerts_) {
    auto* out = req.add_alerts();
    out->set_id(a.id);
    out->set_kind(a.kind);
    out->set_replica_id(a.replica_id);
    out->set_raised_ms(a.raised_ms);
    out->set_resolved_ms(a.resolved_ms);
    out->set_ratio(a.ratio);
    out->set_step_time_ms(a.step_time_ms);
    out->set_auto_drained(a.auto_drained);
    out->set_coverage(a.coverage);
    out->set_threshold(a.threshold);
    out->set_gbps(a.gbps);
    out->set_src_replica_id(a.src_replica_id);
  }
  req.set_alert_seq(alert_seq_);
  std::string out;
  req.SerializeToString(&out);
  return out;
}

Status Lighthouse::HandleReplicate(const LighthouseReplicateRequest& req,
                                   LighthouseReplicateResponse* resp) {
  std::lock_guard<std::mutex> lk(mu_);
  int64_t in_epoch = req.leader().leader_epoch();
  // Fencing: a push from a LOWER epoch is a deposed leader that has not
  // noticed yet; and a live leader refuses pushes from its own epoch or
  // below (two same-epoch leaders cannot exist under the lease protocol —
  // refusing is the safe answer to a confused peer either way).
  if (in_epoch < leader_epoch_ || (role_leader_ && in_epoch <= leader_epoch_)) {
    resp->set_applied(false);
    resp->set_leader_epoch(leader_epoch_);
    return Status::kOk;
  }
  if (role_leader_) {
    // A push from a higher epoch: we were deposed (e.g. this process froze
    // past its lease and a rival won).  Demote before applying.
    LOGW("lighthouse: replication push from epoch %lld > own %lld — demoted",
         static_cast<long long>(in_epoch), static_cast<long long>(leader_epoch_));
    role_leader_ = false;
    flight_.RecordEvent(kFlightRoleChange,
                        "role=follower epoch=" + std::to_string(in_epoch) +
                            " leader_addr=" + req.leader().leader_address() +
                            " cause=replication_fence");
    quorum_cv_.notify_all();
  }
  leader_addr_ = req.leader().leader_address();
  leader_http_ = req.leader().leader_http_address();
  leader_epoch_ = in_epoch;
  // Full-state replace: the leader's view is authoritative for a standby.
  // Local tombstones (evicted_) stand — they fence zombies this instance
  // itself observed.  Pending joins are untouched (a follower refuses
  // joins, so there are none).
  state_.heartbeats.clear();
  state_.draining.clear();
  drain_deadline_ms_.clear();
  hb_step_.clear();
  hb_state_.clear();
  last_commit_ms_.clear();
  allreduce_gbps_.clear();
  ec_shards_.clear();
  health_.clear();
  link_health_.clear();
  ledger_.clear();
  // Bank-undo entries describe the OLD local view; the leader's push is
  // authoritative for both the live entries and the bank.
  ledger_banked_entries_.clear();
  auto now = Clock::now();
  for (const auto& r : req.replicas()) {
    const std::string& id = r.replica_id();
    if (evicted_.count(id)) continue;
    state_.heartbeats[id] =
        now - std::chrono::milliseconds(r.heartbeat_age_ms());
    hb_step_[id] = r.step();
    if (!r.state().empty()) hb_state_[id] = r.state();
    if (r.last_commit_ms() > 0) last_commit_ms_[id] = r.last_commit_ms();
    allreduce_gbps_[id] = r.allreduce_gb_per_s();
    if (r.ec_shards_held() > 0 || r.ec_shard_step() > 0) {
      ec_shards_[id] = {r.ec_shard_step(), r.ec_shards_held()};
      if (r.ec_shards_held() > 0) ec_seen_ = true;
      if (r.ec_k() > 0) ec_k_ = r.ec_k();
    }
    if (r.step_time_ms_ewma() > 0.0 || r.straggler_state() != 0) {
      ReplicaHealth& h = health_[id];
      h.ewma_ms = r.step_time_ms_ewma();
      h.last_ms = r.step_time_ms_last();
      h.ratio = r.straggler_ratio();
      h.state = static_cast<int>(r.straggler_state());
      h.over = r.straggler_over();
      h.under = r.straggler_under();
      h.last_step = r.straggler_last_step();
      h.observations = r.straggler_observations();
    }
    if (r.link_send_gbps() > 0.0 || r.link_state() != 0) {
      // Full hysteresis position, like the straggler fields above: a
      // failover must not restart the warmup gate (observations) or the
      // per-step cursor, and the ratio gauge must not blank out.
      LinkHealth& lh = link_health_[id];
      lh.recv_gbps = r.link_recv_gbps();
      lh.send_gbps = r.link_send_gbps();
      lh.rtt_ms = r.link_hop_rtt_ms();
      lh.state = static_cast<int>(r.link_state());
      lh.over = r.link_over();
      lh.under = r.link_under();
      lh.ratio = r.link_ratio();
      lh.last_step = r.link_last_step();
      lh.observations = r.link_observations();
    }
    if (r.draining()) {
      state_.draining[id] = now;
      if (r.drain_deadline_ms() > 0) drain_deadline_ms_[id] = r.drain_deadline_ms();
    }
    if (r.ledger_compute_seconds() > 0.0 || r.ledger_lost_seconds_size() > 0) {
      ReplicaLedger& rl = ledger_[id];
      rl.goodput_ratio = r.goodput_ratio();
      rl.compute_s = r.ledger_compute_seconds();
      for (size_t i = 0; i < kLedgerCauseCount; ++i) {
        rl.lost_s[i] = i < static_cast<size_t>(r.ledger_lost_seconds_size())
                           ? r.ledger_lost_seconds(static_cast<int>(i))
                           : 0.0;
      }
    }
  }
  // Cluster bank: the leader's view is AUTHORITATIVE, like every other
  // replicated field — assignment, not max-merge.  A max would pin a
  // stale high bank after the leader legitimately LOWERED its own (the
  // resume-undo path subtracts a banked share when a stalled incarnation
  // comes back), double-counting that incarnation on the standby
  // forever.  A follower's own sweep may bank a replicated entry between
  // pushes; the next push restores the consistent (bank, live-entry)
  // pair either way.
  ledger_banked_compute_ = req.ledger_banked_compute_seconds();
  for (size_t i = 0; i < kLedgerCauseCount; ++i) {
    ledger_banked_lost_[i] =
        i < static_cast<size_t>(req.ledger_banked_lost_seconds_size())
            ? req.ledger_banked_lost_seconds(static_cast<int>(i))
            : 0.0;
  }
  if (req.prev_quorum().participants_size() > 0) {
    state_.prev_quorum = req.prev_quorum();
  }
  if (req.quorum_id() > state_.quorum_id) state_.quorum_id = req.quorum_id();
  alerts_.clear();
  for (const auto& a : req.alerts()) {
    AlertRecord rec;
    rec.id = a.id();
    rec.kind = a.kind();
    rec.replica_id = a.replica_id();
    rec.raised_ms = a.raised_ms();
    rec.resolved_ms = a.resolved_ms();
    rec.ratio = a.ratio();
    rec.step_time_ms = a.step_time_ms();
    rec.auto_drained = a.auto_drained();
    rec.coverage = a.coverage();
    rec.threshold = a.threshold();
    rec.gbps = a.gbps();
    rec.src_replica_id = a.src_replica_id();
    alerts_.push_back(std::move(rec));
  }
  if (req.alert_seq() > alert_seq_) alert_seq_ = req.alert_seq();
  resp->set_applied(true);
  resp->set_leader_epoch(leader_epoch_);
  return Status::kOk;
}

void Lighthouse::FillLeaderInfo(LighthouseLeaderInfoResponse* resp) {
  std::lock_guard<std::mutex> lk(mu_);
  auto* l = resp->mutable_leader();
  l->set_leader_address(leader_addr_);
  l->set_leader_http_address(leader_http_);
  l->set_leader_epoch(leader_epoch_);
  l->set_lease_expires_ms(lease_expires_ms_);
  resp->set_role(IsLeaderLocked() ? 1 : 0);
}

// ---------------------------------------------------------------------------
// Federation (docs/wire.md "Federation"): two-tier lighthouse topology.
// Regional CHILD lighthouses keep owning heartbeats, sentinel scoring and
// the goodput-ledger rollup for their O(N/R) groups; a push loop reports a
// bounded membership + ledger digest upward (wire method 8), and the ROOT
// computes the global quorum over digests only — no instance ever handles
// O(N) heartbeat or scrape traffic.  A lighthouse that never calls
// SetFederation and never receives a digest is bit-identical to the flat
// single-tier service.
// ---------------------------------------------------------------------------

void Lighthouse::SetFederation(const std::string& region,
                               const std::string& root_addrs,
                               int64_t push_interval_ms) {
  bool start_thread = false;
  {
    std::lock_guard<std::mutex> lk(mu_);
    fed_region_ = region;
    fed_root_addrs_ = root_addrs;
    if (push_interval_ms > 0) fed_push_interval_ms_ = push_interval_ms;
    bool child = !region.empty() && !root_addrs.empty();
    start_thread = child && !fed_child_;
    fed_child_ = child;
  }
  if (start_thread) {
    fed_thread_ = std::thread([this] { FederationLoop(); });
    LOGI("lighthouse: federated CHILD for region '%s' -> root %s (push every "
         "%lld ms)", region.c_str(), root_addrs.c_str(),
         static_cast<long long>(fed_push_interval_ms_));
  }
}

void Lighthouse::BuildDigestLocked(RegionDigest* d) {
  d->set_region(fed_region_);
  d->set_child_epoch(leader_epoch_);
  d->set_seq(++fed_digest_seq_);
  d->set_root_gen(fed_root_gen_);
  auto now = Clock::now();
  auto hb_timeout = std::chrono::milliseconds(opt_.heartbeat_timeout_ms);
  int64_t fresh = 0;
  // One row per heartbeating id: the root's QuorumCompute needs the FULL
  // healthy set (its strict-majority guard divides joined by healthy), not
  // just the joiners — so ages ride along and install at the root via the
  // same freshness-carry the HA replication path uses.
  for (const auto& [id, last] : state_.heartbeats) {
    auto* rm = d->add_members();
    auto p = state_.participants.find(id);
    if (p != state_.participants.end()) {
      *rm->mutable_member() = p->second.member;
      rm->set_joined(true);
    } else {
      rm->mutable_member()->set_replica_id(id);
    }
    rm->set_heartbeat_age_ms(
        std::chrono::duration_cast<std::chrono::milliseconds>(now - last)
            .count());
    if (state_.draining.count(id)) rm->set_draining(true);
    auto hs = hb_state_.find(id);
    if (hs != hb_state_.end()) rm->set_state(hs->second);
    auto st = hb_step_.find(id);
    if (st != hb_step_.end()) {
      rm->set_hb_step(st->second);
      if (p == state_.participants.end()) {
        rm->mutable_member()->set_step(st->second);
      }
    }
    if (now - last < hb_timeout) ++fresh;
  }
  d->set_replicas_total(static_cast<int64_t>(state_.heartbeats.size()));
  d->set_replicas_fresh(fresh);
  double compute = 0.0, lost[kLedgerCauseCount];
  ClusterLedgerLocked(&compute, lost);
  d->set_ledger_compute_seconds(compute);
  double lost_total = 0.0;
  for (size_t i = 0; i < kLedgerCauseCount; ++i) {
    d->add_ledger_lost_seconds(lost[i]);
    lost_total += lost[i];
  }
  double accounted = compute + lost_total;
  d->set_goodput_ratio(accounted > 0.0 ? compute / accounted : 0.0);
  int64_t active = 0;
  for (const auto& a : alerts_) {
    if (a.resolved_ms == 0) ++active;
  }
  d->set_alerts_active(active);
  d->set_incident_seq(incident_seq_);
}

void Lighthouse::InstallGlobalQuorumLocked(const Quorum& q, int64_t root_gen) {
  fed_root_gen_ = root_gen;
  bool changed = true;
  std::set<std::string> new_ids;
  for (const auto& m : q.participants()) new_ids.insert(m.replica_id());
  if (state_.prev_quorum) {
    std::set<std::string> old_ids;
    for (const auto& m : state_.prev_quorum->participants()) {
      old_ids.insert(m.replica_id());
    }
    changed = old_ids != new_ids;
  }
  state_.prev_quorum = q;
  state_.quorum_id = q.quorum_id();
  // Same broadcast discipline as a local formation: every member re-joins
  // for the next round, blocked joiners wake with the GLOBAL quorum.
  state_.participants.clear();
  latest_quorum_ = q;
  quorum_gen_ += 1;
  quorum_cv_.notify_all();
  if (changed) {
    std::string ids;
    for (const auto& id : new_ids) {
      if (!ids.empty()) ids += ",";
      ids += id;
    }
    LOGI("lighthouse: installed GLOBAL quorum %lld (%zu members) from root "
         "gen %lld", static_cast<long long>(q.quorum_id()), new_ids.size(),
         static_cast<long long>(root_gen));
    flight_.RecordEvent(kFlightQuorumFormed,
                        "quorum_id=" + std::to_string(q.quorum_id()) +
                            " members=[" + ids + "] joined=[] left=[] " +
                            "formation_ms=0 source=root");
    logged_reasons_.clear();
  }
}

void Lighthouse::FederationLoop() {
  // One failover client for the root's HA replica set: a "not the leader"
  // rejection jumps to the named root leader, transport failures rotate —
  // the exact client Managers use against a child's address list.
  FailoverRpcClient client(fed_root_addrs_);
  TimePoint next_push = Clock::now();
  while (true) {
    LighthouseRegionDigestRequest req;
    bool push = false;
    int64_t interval_ms;
    {
      std::unique_lock<std::mutex> lk(mu_);
      quorum_cv_.wait_until(lk, next_push, [&] { return shutdown_; });
      if (shutdown_) return;
      interval_ms = fed_push_interval_ms_;
      // Only the region's LEASE HOLDER reports upward: a follower child's
      // replicated view would race the leader's digests at the root (and a
      // deposed leader is fenced there by child_epoch anyway).
      if (fed_child_ && IsLeaderLocked()) {
        BuildDigestLocked(req.mutable_digest());
        push = true;
      }
    }
    next_push = Clock::now() + std::chrono::milliseconds(interval_ms);
    if (!push) continue;
    std::string body, resp_body, err;
    req.SerializeToString(&body);
    Status st = client.Call(kLighthouseRegionDigest, body,
                            static_cast<uint64_t>(interval_ms) * 4, &resp_body,
                            &err);
    if (st != Status::kOk) {
      std::lock_guard<std::mutex> lk(mu_);
      ++fed_pushes_rejected_;
      // Dedup through logged_reasons_ (cleared on membership change) so a
      // dead root logs once per episode, not once per push.
      std::string reason = "region digest push failed: " + StatusName(st);
      if (logged_reasons_.insert(reason).second) {
        LOGW("lighthouse: region '%s' digest push failed (%s: %s)",
             fed_region_.c_str(), StatusName(st).c_str(), err.c_str());
      }
      continue;
    }
    LighthouseRegionDigestResponse resp;
    if (!resp.ParseFromString(resp_body)) continue;
    // Downward directives first (they take mu_ themselves): the root's
    // evict/drain decisions act on THIS region's members.
    for (const auto& prefix : resp.evict_prefixes()) {
      EvictReplica(prefix);
    }
    for (const auto& prefix : resp.drain_prefixes()) {
      DrainReplica(prefix, resp.drain_deadline_ms());
    }
    std::lock_guard<std::mutex> lk(mu_);
    if (!resp.applied()) {
      ++fed_pushes_rejected_;
      // Fenced: the root saw a HIGHER epoch from this region — a rival
      // child leader took the lease.  The local HA driver demotes this
      // instance on its own; stop pushing authoritative digests now.
      LOGW("lighthouse: region '%s' digest fenced by root (our epoch %lld, "
           "root holds %lld)", fed_region_.c_str(),
           static_cast<long long>(leader_epoch_),
           static_cast<long long>(resp.leader_epoch()));
      continue;
    }
    ++fed_pushes_ok_;
    // Install the root's global quorum only on generation CHANGE: a
    // repeated response must not re-clear the round's pending joins,
    // while a gen that moved backwards is a failed-over root whose
    // counter restarted — its formations are still authoritative.
    // (Presence test by content: the local pb codegen has no has_quorum.)
    if (resp.quorum().participants_size() > 0 &&
        resp.quorum_gen() != fed_root_gen_) {
      InstallGlobalQuorumLocked(resp.quorum(), resp.quorum_gen());
    }
  }
}

Status Lighthouse::HandleRegionDigest(const LighthouseRegionDigestRequest& req,
                                      LighthouseRegionDigestResponse* resp,
                                      std::string* err) {
  const RegionDigest& d = req.digest();
  if (d.region().empty()) {
    if (err) *err = "region digest without a region name";
    return Status::kInvalidArgument;
  }
  std::lock_guard<std::mutex> lk(mu_);
  if (!IsLeaderLocked()) {
    // Root standby: the child's failover client parses the redirect and
    // jumps to the live root leader, exactly like a Manager client would.
    if (err) *err = NotLeaderErrLocked();
    return Status::kUnavailable;
  }
  auto& entry = regions_[d.region()];
  // Per-region epoch fence: a deposed child leader (older lease epoch than
  // the newest this region has pushed) must not overwrite its successor's
  // digests.  Mirrors HandleReplicate's fencing, per tier.
  if (d.child_epoch() < entry.child_epoch) {
    resp->set_applied(false);
    resp->set_leader_epoch(entry.child_epoch);
    return Status::kOk;
  }
  bool first = entry.digests == 0;
  bool was_stale = entry.stale;
  entry.child_epoch = d.child_epoch();
  entry.seq = d.seq();
  entry.last_push = Clock::now();
  entry.stale = false;
  entry.digests += 1;
  entry.replicas_total = d.replicas_total();
  entry.replicas_fresh = d.replicas_fresh();
  // Region ledger rollup advances monotonically per child incarnation;
  // goodput observation below fires only when the totals actually moved.
  double prev_accounted = entry.compute_s;
  for (size_t i = 0; i < kLedgerCauseCount; ++i) prev_accounted += entry.lost_s[i];
  entry.compute_s = d.ledger_compute_seconds();
  double new_accounted = entry.compute_s;
  for (size_t i = 0; i < kLedgerCauseCount &&
                     i < static_cast<size_t>(d.ledger_lost_seconds_size());
       ++i) {
    entry.lost_s[i] = d.ledger_lost_seconds(i);
    new_accounted += entry.lost_s[i];
  }
  entry.goodput_ratio = d.goodput_ratio();
  entry.alerts_active = d.alerts_active();
  entry.incident_seq = d.incident_seq();
  if (first) {
    LOGI("lighthouse: region '%s' joined the federation (%lld replicas, "
         "child epoch %lld)", d.region().c_str(),
         static_cast<long long>(d.replicas_total()),
         static_cast<long long>(d.child_epoch()));
  } else if (was_stale) {
    LOGI("lighthouse: region '%s' digest pushes recovered", d.region().c_str());
  }
  // Member ingestion: heartbeats install via the SAME freshness-carry the
  // HA replication path uses (now - age), so the root's QuorumCompute
  // applies its ordinary staleness rule to region members; joined members
  // register as participants (the digest is the region's bulk join),
  // preserving joined_at across re-pushes so join_timeout still measures
  // from the round's true first joiner.
  auto now = Clock::now();
  // `joined` flags are only valid relative to the quorum generation the
  // child has installed: a digest built before the child saw the latest
  // formation re-reports joins that formation already consumed, and
  // ingesting those phantom rows would form rounds with members that
  // never re-joined (their stale steps then trigger spurious heals).
  // Heartbeats/steps/draining stay welcome from any generation.
  bool joins_current = d.root_gen() >= quorum_gen_;
  std::set<std::string> seen;
  for (const auto& rm : d.members()) {
    const std::string& id = rm.member().replica_id();
    if (id.empty() || evicted_.count(id)) continue;
    seen.insert(id);
    region_of_[id] = d.region();
    state_.heartbeats[id] =
        now - std::chrono::milliseconds(rm.heartbeat_age_ms());
    auto st = hb_step_.find(id);
    int64_t step = std::max(rm.hb_step(), rm.member().step());
    if (st == hb_step_.end()) {
      hb_step_[id] = step;
    } else if (step > st->second) {
      st->second = step;
      last_commit_ms_[id] = NowEpochMs();
    }
    if (!rm.state().empty()) hb_state_[id] = rm.state();
    if (rm.draining()) state_.draining.emplace(id, now);
    if (rm.joined() && joins_current) {
      auto p = state_.participants.find(id);
      if (p == state_.participants.end()) {
        state_.participants.emplace(
            id, QuorumState::Joined{rm.member(), now});
      } else {
        p->second.member = rm.member();  // refresh the step snapshot
      }
    }
  }
  // Ids the child no longer reports left THERE (child-side evict/prune):
  // drop them here too so the global quorum stops counting them at digest
  // speed instead of heartbeat-staleness speed.
  for (auto it = region_of_.begin(); it != region_of_.end();) {
    if (it->second == d.region() && !seen.count(it->first)) {
      const std::string& id = it->first;
      state_.heartbeats.erase(id);
      state_.participants.erase(id);
      hb_step_.erase(id);
      hb_state_.erase(id);
      last_commit_ms_.erase(id);
      last_fresh_.erase(id);
      it = region_of_.erase(it);
    } else {
      ++it;
    }
  }
  // Cluster goodput observation across regions: the root's floor trigger
  // watches the FLEET ledger (its own members + every region's rollup).
  if (new_accounted > prev_accounted) ObserveGoodputLocked();
  // Try forming the global quorum right away (the digest may have
  // completed the joined set), then answer with whatever is newest.
  TickLocked();
  resp->set_applied(true);
  resp->set_leader_epoch(entry.child_epoch);
  if (latest_quorum_) {
    *resp->mutable_quorum() = *latest_quorum_;
    resp->set_quorum_gen(quorum_gen_);
  }
  for (const auto& p : entry.pending_evicts) resp->add_evict_prefixes(p);
  for (const auto& p : entry.pending_drains) resp->add_drain_prefixes(p);
  resp->set_drain_deadline_ms(entry.pending_drain_deadline_ms);
  entry.pending_evicts.clear();
  entry.pending_drains.clear();
  entry.pending_drain_deadline_ms = 0;
  return Status::kOk;
}

void Lighthouse::SweepRegionsLocked(TimePoint tick_now,
                                    std::chrono::milliseconds hb_timeout) {
  for (auto& [region, entry] : regions_) {
    if (entry.stale || tick_now - entry.last_push <= hb_timeout) continue;
    entry.stale = true;
    auto age_ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                      tick_now - entry.last_push)
                      .count();
    LOGW("lighthouse: region '%s' digest pushes stale (age %lld ms) — "
         "declaring the region dead", region.c_str(),
         static_cast<long long>(age_ms));
    // The cross-region kill signature: a whole region went dark (child
    // leader AND standbys, or the network partition ate it).  The incident
    // record NAMES the region — obs/incident.py's verdict surfaces it.
    RecordIncidentLocked("region_stale", region,
                         static_cast<double>(age_ms));
    // Drop its members from the current round immediately; their carried
    // heartbeats froze at the last push, so the ordinary freshness rule
    // already excludes them from QuorumCompute — this just stops a formed
    // round from waiting out join_timeout on corpses.
    for (auto it = state_.participants.begin();
         it != state_.participants.end();) {
      auto r = region_of_.find(it->first);
      if (r != region_of_.end() && r->second == region) {
        it = state_.participants.erase(it);
      } else {
        ++it;
      }
    }
  }
}

void Lighthouse::FillRegions(LighthouseRegionsResponse* resp) {
  std::lock_guard<std::mutex> lk(mu_);
  resp->set_role(!regions_.empty() ? "root" : (fed_child_ ? "child" : "flat"));
  resp->set_region(fed_region_);
  auto now = Clock::now();
  if (fed_child_) {
    // A child reports ITSELF as one region row (its own live totals): the
    // same shape the root would render for it, sourced locally.
    auto* ri = resp->add_regions();
    ri->set_region(fed_region_);
    ri->set_child_epoch(leader_epoch_);
    ri->set_seq(fed_digest_seq_);
    auto hb_timeout = std::chrono::milliseconds(opt_.heartbeat_timeout_ms);
    int64_t fresh = 0;
    for (const auto& [id, last] : state_.heartbeats) {
      if (now - last < hb_timeout) ++fresh;
    }
    ri->set_replicas_total(static_cast<int64_t>(state_.heartbeats.size()));
    ri->set_replicas_fresh(fresh);
    double compute = 0.0, lost[kLedgerCauseCount];
    ClusterLedgerLocked(&compute, lost);
    double lost_total = 0.0;
    for (size_t i = 0; i < kLedgerCauseCount; ++i) lost_total += lost[i];
    ri->set_ledger_compute_seconds(compute);
    double accounted = compute + lost_total;
    ri->set_goodput_ratio(accounted > 0.0 ? compute / accounted : 0.0);
    int64_t active = 0;
    for (const auto& a : alerts_) {
      if (a.resolved_ms == 0) ++active;
    }
    ri->set_alerts_active(active);
  }
  for (const auto& [name, e] : regions_) {
    auto* ri = resp->add_regions();
    ri->set_region(name);
    ri->set_child_epoch(e.child_epoch);
    ri->set_seq(e.seq);
    ri->set_replicas_total(e.replicas_total);
    ri->set_replicas_fresh(e.replicas_fresh);
    ri->set_last_push_age_ms(
        std::chrono::duration_cast<std::chrono::milliseconds>(now - e.last_push)
            .count());
    ri->set_stale(e.stale);
    ri->set_ledger_compute_seconds(e.compute_s);
    ri->set_goodput_ratio(e.goodput_ratio);
    ri->set_alerts_active(e.alerts_active);
  }
}

std::string Lighthouse::RegionsJson() {
  LighthouseRegionsResponse r;
  FillRegions(&r);
  std::ostringstream o;
  o << "{\"role\":\"" << JsonEscape(r.role()) << "\",\"region\":\""
    << JsonEscape(r.region()) << "\",\"regions\":[";
  bool first = true;
  for (const auto& ri : r.regions()) {
    if (!first) o << ",";
    first = false;
    o << "{\"region\":\"" << JsonEscape(ri.region())
      << "\",\"child_epoch\":" << ri.child_epoch() << ",\"seq\":" << ri.seq()
      << ",\"replicas_total\":" << ri.replicas_total()
      << ",\"replicas_fresh\":" << ri.replicas_fresh()
      << ",\"last_push_age_ms\":" << ri.last_push_age_ms()
      << ",\"stale\":" << (ri.stale() ? "true" : "false")
      << ",\"ledger_compute_seconds\":" << ri.ledger_compute_seconds()
      << ",\"goodput_ratio\":" << ri.goodput_ratio()
      << ",\"alerts_active\":" << ri.alerts_active() << "}";
  }
  o << "]}";
  return o.str();
}

bool Lighthouse::Start(std::string* err) {
  if (const char* tok = std::getenv("TPUFT_ADMIN_TOKEN")) admin_token_ = tok;
  // HA replicas start as followers BEFORE the listeners open (the HA
  // driver sets this env before constructing the server): the default
  // standalone-permanent-leader role would otherwise answer a heartbeat
  // or quorum authoritatively in the window between Start() and the
  // driver's first SetRole(false) — a brief dual-authoritative hole while
  // an election is already in progress elsewhere.
  if (const char* f = std::getenv("TPUFT_HA_START_FOLLOWER")) {
    if (f[0] != '\0' && f[0] != '0') role_leader_ = false;
  }
  // Straggler sentinel knobs.  Malformed values fall back to the defaults —
  // a bad tuning knob must not take the coordination plane down.
  if (const char* r = std::getenv("TPUFT_STRAGGLER_RATIO")) {
    char* end = nullptr;
    double v = std::strtod(r, &end);
    if (end != r && v > 1.0) straggler_ratio_ = v;
  }
  if (const char* g = std::getenv("TPUFT_STRAGGLER_GRACE_STEPS")) {
    long long v = std::atoll(g);
    if (v >= 1) straggler_grace_ = v;
  }
  if (const char* a = std::getenv("TPUFT_STRAGGLER_AUTO_DRAIN")) {
    straggler_auto_drain_ = std::string(a) == "1";
  }
  if (const char* w = std::getenv("TPUFT_STRAGGLER_WARMUP_STEPS")) {
    long long v = std::atoll(w);
    if (v >= 0) straggler_warmup_ = v;
  }
  // Slow-link sentinel knobs (same malformed-value discipline).
  if (const char* r = std::getenv("TPUFT_LINK_RATIO")) {
    char* end = nullptr;
    double v = std::strtod(r, &end);
    if (end != r && v > 1.0) link_ratio_ = v;
  }
  if (const char* g = std::getenv("TPUFT_LINK_GRACE_STEPS")) {
    long long v = std::atoll(g);
    if (v >= 1) link_grace_ = v;
  }
  if (const char* a = std::getenv("TPUFT_LINK_AUTO_DRAIN")) {
    link_auto_drain_ = std::string(a) == "1";
  }
  if (const char* w = std::getenv("TPUFT_LINK_WARMUP_STEPS")) {
    long long v = std::atoll(w);
    if (v >= 0) link_warmup_ = v;
  }
  // Goodput-floor incident-trigger knobs (same malformed-value discipline).
  if (const char* r = std::getenv("TPUFT_GOODPUT_DIP_RATIO")) {
    char* end = nullptr;
    double v = std::strtod(r, &end);
    if (end != r && v > 0.0 && v < 1.0) goodput_dip_ratio_ = v;
  }
  if (const char* w = std::getenv("TPUFT_GOODPUT_WARMUP_OBS")) {
    long long v = std::atoll(w);
    if (v >= 0) goodput_warmup_ = v;
  }
  // SLO engine knobs (same malformed-value discipline).  The engine is
  // OFF unless TPUFT_SLO_TARGET parses to a ratio in (0, 1).
  if (const char* t = std::getenv("TPUFT_SLO_TARGET")) {
    char* end = nullptr;
    double v = std::strtod(t, &end);
    if (end != t && v > 0.0 && v < 1.0) slo_target_ = v;
  }
  if (const char* f = std::getenv("TPUFT_SLO_FAST_S")) {
    char* end = nullptr;
    double v = std::strtod(f, &end);
    if (end != f && v > 0.0) slo_fast_s_ = v;
  }
  if (const char* s = std::getenv("TPUFT_SLO_SLOW_S")) {
    char* end = nullptr;
    double v = std::strtod(s, &end);
    if (end != s && v > 0.0) slo_slow_s_ = v;
  }
  if (slo_slow_s_ < slo_fast_s_) slo_slow_s_ = slo_fast_s_;
  server_ = std::make_unique<RpcServer>(
      opt_.bind, [this](uint16_t method, const std::string& req, Deadline dl,
                        const std::string& peer, std::string* resp) {
        return Dispatch(method, req, dl, peer, resp);
      });
  if (!server_->Start(err)) return false;
  flight_.SetIdentity("lighthouse", std::to_string(server_->port()));
  if (!opt_.http_bind.empty()) {
    http_ = std::make_unique<HttpServer>(
        opt_.http_bind,
        [this](const HttpRequestInfo& req) {
          const std::string& method = req.method;
          // Split an optional query string off the path ("?limit=N" on the
          // flight endpoint); route matching uses the bare path.
          std::string path = req.path;
          std::string query;
          if (auto qpos = path.find('?'); qpos != std::string::npos) {
            query = path.substr(qpos + 1);
            path = path.substr(0, qpos);
          }
          HttpResponse r;
          // HA standby: redirect everything except /metrics and the flight
          // recorder to the leader (docs/wire.md "HA lighthouse").
          // /metrics is served locally so each instance exposes its own
          // tpuft_lighthouse_role gauge — redirecting it would
          // double-count the leader under scrapes — and
          // /debug/flight.json is each instance's OWN black box
          // (redirecting a standby's recorder would hide exactly the
          // election evidence it exists to keep).  /regions.json is the
          // same shape: a per-instance federation view (wire method 9 is
          // answered by every instance too).
          if (path != "/metrics" && path != "/debug/flight.json" &&
              path != "/regions.json") {
            std::string leader_http;
            bool follower;
            {
              std::lock_guard<std::mutex> lk(mu_);
              follower = !IsLeaderLocked();
              leader_http = role_leader_ ? "" : leader_http_;
            }
            if (follower) {
              if (!leader_http.empty()) {
                r.code = 307;  // preserves the method: POSTs re-POST
                // leader_http may arrive with or without a scheme
                // (http_address() returns "http://host:port").
                r.location = (leader_http.rfind("http://", 0) == 0
                                  ? leader_http
                                  : "http://" + leader_http) +
                             path;
                r.content_type = "text/plain";
                r.body = "not the leader; see " + r.location + "\n";
              } else {
                r.code = 503;
                r.content_type = "text/plain";
                r.body = "not the leader; leader election in progress\n";
              }
              return r;
            }
          }
          bool is_mutation = method == "POST" && path.rfind("/replica/", 0) == 0;
          if (is_mutation && !AdminAllowed(req.token, req.peer_loopback)) {
            // Ops endpoints mutate cluster membership; see docs/wire.md
            // "Trust model" — remote callers must present the shared
            // secret when one is configured, and are refused outright
            // otherwise.
            r.code = 403;
            r.body = admin_token_.empty()
                         ? "forbidden: mutating endpoints are loopback-only "
                           "(set TPUFT_ADMIN_TOKEN to allow remote ops calls)"
                         : "forbidden: missing or wrong x-tpuft-token header";
            r.content_type = "text/plain";
            return r;
          }
          if (method == "GET" && (path == "/" || path == "/status")) {
            r.body = StatusHtml();
          } else if (method == "GET" && path == "/status.json") {
            r.content_type = "application/json";
            r.body = StatusJson();
          } else if (method == "GET" && path == "/metrics") {
            // Prometheus text exposition (read-only, ungated like
            // /status.json): cluster-level gauges a scraper can alert on.
            // Self-observed: the render duration lands in the
            // tpuft_metrics_scrape_seconds histogram AFTER the body is
            // built, so the cost of scrape N is visible from scrape N+1 —
            // the seed measurement for the scrape-cost-vs-N scale sweep.
            auto scrape_t0 = Clock::now();
            r.content_type = "text/plain; version=0.0.4; charset=utf-8";
            r.body = MetricsText();
            scrape_hist_.Observe(
                std::chrono::duration<double>(Clock::now() - scrape_t0).count());
          } else if (method == "GET" && path == "/debug/flight.json") {
            // Control-plane flight recorder (read-only, ungated): bounded,
            // newest-first RPC spans + state transitions.  ?limit=N caps
            // the event count for quick looks at a busy server.
            size_t limit = 0;
            if (auto lpos = query.find("limit="); lpos != std::string::npos) {
              long long v = atoll(query.c_str() + lpos + 6);
              if (v > 0) limit = static_cast<size_t>(v);
            }
            r.content_type = "application/json";
            r.body = FlightJson(limit);
          } else if (method == "GET" && path == "/alerts.json") {
            // Straggler-sentinel alert feed (read-only, ungated): raised
            // and resolved alerts with the scores that triggered them.
            r.content_type = "application/json";
            r.body = AlertsJson();
          } else if (method == "GET" && path == "/goodput.json") {
            // Goodput ledger (read-only, ungated): cluster + per-replica
            // cause-attributed lost-time rollup from heartbeat fields
            // 14-16 (docs/wire.md "Goodput ledger").
            r.content_type = "application/json";
            r.body = GoodputJson();
          } else if (method == "GET" && path == "/regions.json") {
            // Federation rollup (read-only, ungated): this instance's
            // role + one row per known region (docs/wire.md "Federation").
            r.content_type = "application/json";
            r.body = RegionsJson();
          } else if (method == "GET" && path == "/incident.json") {
            // Incident-trigger feed (read-only, ungated): the capture
            // driver (obs/incident.py) polls this and bundles the
            // evidence when a new record appears.
            r.content_type = "application/json";
            r.body = IncidentJson();
          } else if (method == "GET" && path == "/slo.json") {
            // SLO engine snapshot (read-only, ungated): target, burn
            // rates, error budget and the newest culprit attribution.
            // Served at every tier — a root answers over its digest
            // rollups (docs/observability.md "SLO engine").
            r.content_type = "application/json";
            r.body = SloJson();
          } else if (method == "POST" && path.rfind("/replica/", 0) == 0 &&
                     path.size() > 14 && path.substr(path.size() - 5) == "/kill") {
            std::string replica_id = path.substr(9, path.size() - 9 - 5);
            std::string kerr;
            if (KillReplica(replica_id, &kerr)) {
              r.body = "killed " + replica_id;
              r.content_type = "text/plain";
            } else {
              r.code = 500;
              r.body = kerr;
              r.content_type = "text/plain";
            }
          } else if (method == "POST" && path.rfind("/replica/", 0) == 0 &&
                     path.size() > 15 && path.substr(path.size() - 6) == "/evict") {
            std::string prefix = path.substr(9, path.size() - 9 - 6);
            int n = EvictReplica(prefix);
            r.body = "evicted " + std::to_string(n) + " id(s) for " + prefix;
            r.content_type = "text/plain";
          } else if (method == "POST" && path.rfind("/replica/", 0) == 0 &&
                     path.size() > 15 && path.substr(path.size() - 6) == "/drain") {
            std::string prefix = path.substr(9, path.size() - 9 - 6);
            // ?deadline_ms=N announces the grace period: the drain mark
            // outlives staleness pruning until the deadline passes, and
            // the "is draining" quorum rejection carries the remainder so
            // rejoining managers pace their auto-drain to it.
            int64_t deadline_ms = 0;
            if (auto dpos = query.find("deadline_ms=");
                dpos != std::string::npos) {
              long long v = atoll(query.c_str() + dpos + 12);
              if (v > 0) deadline_ms = v;
            }
            int n = DrainReplica(prefix, deadline_ms);
            r.body = "draining " + std::to_string(n) + " id(s) for " + prefix;
            r.content_type = "text/plain";
          } else {
            r.code = 404;
            r.body = "not found";
            r.content_type = "text/plain";
          }
          return r;
        });
    if (!http_->Start(err)) return false;
  }
  tick_thread_ = std::thread([this] { TickLoop(); });
  LOGI("lighthouse listening on %s (dashboard %s)", server_->address().c_str(),
       http_ ? http_->address().c_str() : "disabled");
  return true;
}

void Lighthouse::Shutdown() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (shutdown_) return;
    shutdown_ = true;
    quorum_cv_.notify_all();
  }
  if (tick_thread_.joinable()) tick_thread_.join();
  if (fed_thread_.joinable()) fed_thread_.join();
  if (server_) server_->Shutdown();
  if (http_) http_->Shutdown();
  // Black-box dump: with TPUFT_FLIGHT_DIR set, a shutting-down lighthouse
  // leaves flight_lighthouse_<port>.json next to the run's span JSONL —
  // the post-mortem artifact for runs whose WORKERS were SIGKILLed (the
  // recorder holds the quorum transitions around every kill).
  flight_.RecordEvent(kFlightShutdown, "server=lighthouse");
  std::string dump = flight_.DumpPathFromEnv();
  if (!dump.empty()) {
    if (flight_.DumpToFile(dump)) {
      LOGI("lighthouse: flight recorder dumped to %s", dump.c_str());
    } else {
      LOGW("lighthouse: flight recorder dump to %s failed", dump.c_str());
    }
  }
}

std::string Lighthouse::address() const { return server_ ? server_->address() : ""; }
std::string Lighthouse::http_address() const { return http_ ? http_->address() : ""; }

Status Lighthouse::Dispatch(uint16_t method, const std::string& req, Deadline dl,
                            const std::string& peer, std::string* resp) {
  // Server-side RPC span: recv (here) -> send (return) monotonic window,
  // stamped with the request's causal trace id.  The span is recorded even
  // for failed/redirected calls — a standby's rejection storm during an
  // election is exactly the evidence the black box exists to keep.
  auto t0 = Clock::now();
  std::string trace_id;
  Status st = DispatchInner(method, req, dl, resp, &trace_id);
  int64_t dur_us =
      std::chrono::duration_cast<std::chrono::microseconds>(Clock::now() - t0)
          .count();
  flight_.RecordRpc(MethodName(method).c_str(), peer,
                    static_cast<uint16_t>(st), dur_us, std::move(trace_id));
  auto hist = rpc_hist_.find(method);
  if (hist != rpc_hist_.end()) hist->second.Observe(dur_us / 1e6);
  if (method == kLighthouseHeartbeat) {
    // Fan-in accounting: summed per quorum tick into
    // tpuft_heartbeat_fanin_seconds by TickLoop.
    hb_fanin_accum_us_.fetch_add(dur_us, std::memory_order_relaxed);
    hb_fanin_count_.fetch_add(1, std::memory_order_relaxed);
  }
  return st;
}

Status Lighthouse::DispatchInner(uint16_t method, const std::string& req, Deadline dl,
                                 std::string* resp, std::string* trace_id) {
  switch (method) {
    case kLighthouseQuorum: {
      LighthouseQuorumRequest q;
      if (!q.ParseFromString(req)) return Status::kInvalidArgument;
      *trace_id = q.trace_id();
      LighthouseQuorumResponse r;
      std::string err;
      Status st = HandleQuorum(q, dl, &r, &err);
      if (st != Status::kOk) {
        *resp = err;
        return st;
      }
      r.SerializeToString(resp);
      return Status::kOk;
    }
    case kLighthouseHeartbeat: {
      LighthouseHeartbeatRequest h;
      if (!h.ParseFromString(req)) return Status::kInvalidArgument;
      *trace_id = h.trace_id();
      Status st = HandleHeartbeat(h);
      if (st == Status::kUnavailable) {
        // Standby rejection: carry the redirect in the error payload so
        // the manager's failover client can jump to the leader.
        std::lock_guard<std::mutex> lk(mu_);
        *resp = NotLeaderErrLocked();
        return st;
      }
      LighthouseHeartbeatResponse r;
      r.SerializeToString(resp);
      return st;
    }
    case kLighthouseStatus: {
      LighthouseStatusResponse r;
      FillStatus(&r);
      r.SerializeToString(resp);
      return Status::kOk;
    }
    case kLighthouseEvict: {
      LighthouseEvictRequest q;
      if (!q.ParseFromString(req)) return Status::kInvalidArgument;
      {
        // Membership mutations on a standby would fork the view the leader
        // replicates over it; redirect like Quorum/Heartbeat.
        std::lock_guard<std::mutex> lk(mu_);
        if (!IsLeaderLocked()) {
          *resp = NotLeaderErrLocked();
          return Status::kUnavailable;
        }
      }
      LighthouseEvictResponse r;
      r.set_evicted(EvictReplica(q.replica_prefix()));
      r.SerializeToString(resp);
      return Status::kOk;
    }
    case kLighthouseDrain: {
      LighthouseDrainRequest q;
      if (!q.ParseFromString(req)) return Status::kInvalidArgument;
      *trace_id = q.trace_id();
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (!IsLeaderLocked()) {
          *resp = NotLeaderErrLocked();
          return Status::kUnavailable;
        }
      }
      LighthouseDrainResponse r;
      r.set_drained(DrainReplica(q.replica_prefix(), q.deadline_ms()));
      r.SerializeToString(resp);
      return Status::kOk;
    }
    case kLighthouseReplicate: {
      LighthouseReplicateRequest q;
      if (!q.ParseFromString(req)) return Status::kInvalidArgument;
      LighthouseReplicateResponse r;
      Status st = HandleReplicate(q, &r);
      r.SerializeToString(resp);
      return st;
    }
    case kLighthouseLeaderInfo: {
      // Read-only leader discovery: answered by every replica regardless
      // of role (clients use it to find the leader without guessing).
      LighthouseLeaderInfoResponse r;
      FillLeaderInfo(&r);
      r.SerializeToString(resp);
      return Status::kOk;
    }
    case kLighthouseRegionDigest: {
      // Federation: a regional child leader pushing its membership + ledger
      // digest (docs/wire.md "Federation").
      LighthouseRegionDigestRequest q;
      if (!q.ParseFromString(req)) return Status::kInvalidArgument;
      *trace_id = q.trace_id();
      LighthouseRegionDigestResponse r;
      std::string err;
      Status st = HandleRegionDigest(q, &r, &err);
      if (st != Status::kOk) {
        *resp = err;
        return st;
      }
      r.SerializeToString(resp);
      return Status::kOk;
    }
    case kLighthouseRegions: {
      // Read-only federation rollup: answered by every instance regardless
      // of role (like LeaderInfo — each instance reports its own view).
      LighthouseRegionsResponse r;
      FillRegions(&r);
      r.SerializeToString(resp);
      return Status::kOk;
    }
    default:
      *resp = "unknown lighthouse method " + std::to_string(method);
      return Status::kUnknown;
  }
}

Status Lighthouse::HandleHeartbeat(const LighthouseHeartbeatRequest& req) {
  std::lock_guard<std::mutex> lk(mu_);
  if (!IsLeaderLocked()) {
    // A standby must not accept heartbeats: its membership view is written
    // by replication only, and the rejection (carrying the leader address)
    // is what steers the manager's failover client to the live leader.
    return Status::kUnavailable;
  }
  if (evicted_.count(req.replica_id())) {
    return Status::kAborted;  // a zombie's in-flight heartbeat
  }
  state_.heartbeats[req.replica_id()] = Clock::now();
  // Live step/state (wire method 2 fields 2-3; 0/"" from pre-observability
  // peers).  A step ADVANCE is a commit: steps increment exactly when a
  // step commits (or a heal fast-forwards, which is progress too), so the
  // advance time is the lighthouse's last-commit timestamp for /metrics
  // and /status.json.
  auto it = hb_step_.find(req.replica_id());
  bool advanced = it == hb_step_.end() || req.step() > it->second;
  if (advanced) {
    if (it != hb_step_.end()) last_commit_ms_[req.replica_id()] = NowEpochMs();
    hb_step_[req.replica_id()] = req.step();
  }
  if (!req.state().empty()) hb_state_[req.replica_id()] = req.state();
  // 0 is a real reading (committed step with no allreduce traffic —
  // healing, spare): letting it through is what stops a stale healthy
  // GB/s from masking a replica that moved zero gradient bytes for hours.
  allreduce_gbps_[req.replica_id()] = req.allreduce_gb_per_s();
  // Create an entry only on a nonzero report (proto3 cannot distinguish a
  // pre-EC sender from an authoritative empty store — both wire 0/0), but
  // UPDATE an existing entry unconditionally: a respawned incarnation's
  // empty store must clear its stale coverage out of the gauges, not keep
  // overstating redundancy at exactly the below-k moment they exist for.
  if (req.ec_shards_held() > 0 || req.ec_shard_step() > 0 ||
      ec_shards_.count(req.replica_id())) {
    ec_shards_[req.replica_id()] = {req.ec_shard_step(), req.ec_shards_held()};
    if (req.ec_shards_held() > 0) ec_seen_ = true;
    if (req.ec_k() > 0) ec_k_ = req.ec_k();
    CheckEcCoverageLocked();
  }
  // Straggler sentinel: keep the rolling step-time telemetry fresh on every
  // heartbeat, but run a state-machine OBSERVATION only when the replica's
  // reported step advances past the sentinel's own cursor — the hysteresis
  // grace is counted in steps, not heartbeats (at a 100 ms cadence one
  // slow step would otherwise burn the whole grace budget before a second
  // step ever committed).  The cursor is per-health-entry, NOT hb_step_:
  // quorum joins advance hb_step_ too and usually beat the heartbeat to a
  // fresh step, which would silently swallow most observations.
  if (req.step_time_ms_ewma() > 0.0) {
    ReplicaHealth& h = health_[req.replica_id()];
    h.ewma_ms = req.step_time_ms_ewma();
    h.last_ms = req.step_time_ms_last();
    if (req.step() > h.last_step) {
      h.last_step = req.step();
      ObserveStepTimeLocked(req.replica_id());
    }
  }
  // Slow-link sentinel: same step-cursor discipline as the straggler
  // sentinel above — telemetry refreshes on every heartbeat, the
  // hysteresis machine observes once per committed step.  The scored
  // signal is the OUTBOUND goodput (send_gbps): only the degraded edge's
  // sender localizes a link fault (wire.md "Slow-link sentinel").
  if (req.link_send_gbps() > 0.0) {
    LinkHealth& lh = link_health_[req.replica_id()];
    lh.recv_gbps = req.link_recv_gbps();
    lh.send_gbps = req.link_send_gbps();
    lh.rtt_ms = req.link_hop_rtt_ms();
    if (req.step() > lh.last_step) {
      lh.last_step = req.step();
      ObserveLinkLocked(req.replica_id());
    }
  }
  // Goodput ledger (heartbeat fields 14-16): the replica's cumulative
  // cause-attributed accounting.  Within one incarnation the counters are
  // monotonic, so the latest report is authoritative; restarts carry new
  // ids, whose predecessors are banked at prune/evict time.  Only a
  // cumulative ADVANCE runs a floor observation — the heartbeat cadence
  // (100 ms) resends identical counters between commits, and observing
  // those would dilute the windowed-goodput EWMA with empty windows.
  if (req.ledger_compute_seconds() > 0.0 || req.ledger_lost_seconds_size() > 0) {
    // A RESUMED incarnation (stalled past the graveyard horizon, then
    // recovered — the sweep banked it as departed) re-reports the same
    // monotonic counters: subtract its banked share first or the cluster
    // totals count it twice.
    auto banked = ledger_banked_entries_.find(req.replica_id());
    if (banked != ledger_banked_entries_.end()) {
      ledger_banked_compute_ = std::max(
          0.0, ledger_banked_compute_ - banked->second.first.compute_s);
      for (size_t i = 0; i < kLedgerCauseCount; ++i) {
        ledger_banked_lost_[i] = std::max(
            0.0, ledger_banked_lost_[i] - banked->second.first.lost_s[i]);
      }
      ledger_banked_entries_.erase(banked);
    }
    ReplicaLedger& rl = ledger_[req.replica_id()];
    double prev_total = rl.compute_s;
    for (size_t i = 0; i < kLedgerCauseCount; ++i) prev_total += rl.lost_s[i];
    rl.goodput_ratio = req.goodput_ratio();
    rl.compute_s = req.ledger_compute_seconds();
    for (size_t i = 0; i < kLedgerCauseCount; ++i) {
      rl.lost_s[i] = i < static_cast<size_t>(req.ledger_lost_seconds_size())
                         ? req.ledger_lost_seconds(static_cast<int>(i))
                         : 0.0;
    }
    double new_total = rl.compute_s;
    for (size_t i = 0; i < kLedgerCauseCount; ++i) new_total += rl.lost_s[i];
    if (new_total > prev_total) ObserveGoodputLocked();
  }
  return Status::kOk;
}

void Lighthouse::BankLedgerLocked(const std::string& id, bool undoable) {
  auto it = ledger_.find(id);
  if (it == ledger_.end()) return;
  ledger_banked_compute_ += it->second.compute_s;
  for (size_t i = 0; i < kLedgerCauseCount; ++i) {
    ledger_banked_lost_[i] += it->second.lost_s[i];
  }
  if (undoable) {
    ledger_banked_entries_[id] = {it->second, NowEpochMs()};
  }
}

void Lighthouse::ClusterLedgerLocked(double* compute_s,
                                     double lost_s[kLedgerCauseCount]) const {
  *compute_s = ledger_banked_compute_;
  for (size_t i = 0; i < kLedgerCauseCount; ++i) lost_s[i] = ledger_banked_lost_[i];
  for (const auto& [id, rl] : ledger_) {
    *compute_s += rl.compute_s;
    for (size_t i = 0; i < kLedgerCauseCount; ++i) lost_s[i] += rl.lost_s[i];
  }
  // Federation root: fold every region's digest rollup into the fleet
  // totals.  Region members heartbeat their own CHILD (never here), so
  // there is no double counting with the per-replica ledger above; a dead
  // region's last rollup stays in the totals (monotonic, like the bank).
  for (const auto& [name, e] : regions_) {
    *compute_s += e.compute_s;
    for (size_t i = 0; i < kLedgerCauseCount; ++i) lost_s[i] += e.lost_s[i];
  }
}

void Lighthouse::ObserveGoodputLocked() {
  double compute = 0.0, lost[kLedgerCauseCount];
  ClusterLedgerLocked(&compute, lost);
  double lost_total = 0.0;
  for (size_t i = 0; i < kLedgerCauseCount; ++i) lost_total += lost[i];
  // Windowed goodput: the productive fraction of the wall ADDED since the
  // previous observation.  The cumulative ratio barely moves late in a
  // run — a window is what a live dip actually shows up in.  Windows
  // close only once >= kMinWindowS of ACCOUNTED wall accumulated: ledger
  // pushes land per commit (every few ms on fast steps), and scoring
  // each tiny delta made the floor trigger fire on single-step
  // scheduler noise.
  constexpr double kMinWindowS = 5.0;
  double d_compute = compute - goodput_prev_compute_;
  double d_lost = lost_total - goodput_prev_lost_;
  if (d_compute + d_lost < kMinWindowS) return;  // window still open
  goodput_prev_compute_ = compute;
  goodput_prev_lost_ = lost_total;
  double d_total = d_compute + d_lost;
  if (d_total <= 0.0) return;  // no new accounted wall in this window
  double windowed = d_compute / d_total;
  last_windowed_goodput_ = windowed;
  // Score the closed window BEFORE the dip check so a firing trigger
  // carries the attribution of the very window that dipped, and the SLO
  // engine's burn rates move on the same cadence as the floor trigger.
  AttributeWindowLocked();
  EvaluateSloLocked(d_compute, d_lost);
  if (goodput_obs_ >= goodput_warmup_ && goodput_ewma_ >= 0.0 &&
      windowed < goodput_ewma_ * goodput_dip_ratio_) {
    // replica_id stays "cluster" (schema + debounce-key stability); the
    // culprit attribution of the dipped window rides the record's
    // culprit_* fields for the capture driver's verdict to name.
    RecordIncidentLocked("goodput_floor", "cluster", windowed, &last_attr_);
  }
  goodput_ewma_ = goodput_ewma_ < 0.0
                      ? windowed
                      : 0.2 * windowed + 0.8 * goodput_ewma_;
  ++goodput_obs_;
}

void Lighthouse::AttributeWindowLocked() {
  // Per-entity window delta vs the entity's OWN trailing baseline: a
  // replica that always spends 10% on wire is not news; one whose stall
  // seconds jumped 5x over its baseline in this window is.  Entities are
  // live replica incarnations (flat / child tier) and regions (root tier
  // over digest rollups) — the same scoring either way, so the verdict
  // names whichever granularity this instance can see.
  constexpr double kBaseAlpha = 0.2;  // baseline EWMA weight per window
  // Noise floor: a window must charge at least this many excess seconds
  // before anyone is blamed (float dust and scheduler jitter otherwise
  // elect a "culprit" in perfectly healthy windows).
  double best_excess = 1e-3;
  std::string best_id, best_cause;
  bool best_is_region = false;
  std::ostringstream deltas;
  deltas << "{";
  bool first = true;
  auto score = [&](const std::string& id, WindowDelta& w, double compute_s,
                   const double lost_s[kLedgerCauseCount], bool is_region) {
    double d_compute = compute_s - w.prev_compute;
    double d_lost[kLedgerCauseCount];
    double d_lost_total = 0.0;
    for (size_t i = 0; i < kLedgerCauseCount; ++i) {
      d_lost[i] = lost_s[i] - w.prev_lost[i];
      if (d_lost[i] < 0.0) d_lost[i] = 0.0;  // re-ingest undo can wobble
      d_lost_total += d_lost[i];
    }
    if (w.primed) {
      double excess = 0.0;
      double worst_excess = 0.0;
      size_t worst = kLedgerCauseCount;
      for (size_t i = 0; i < kLedgerCauseCount; ++i) {
        double e = d_lost[i] - w.base_lost[i];
        if (e > 0.0) excess += e;
        if (e > worst_excess) {
          worst_excess = e;
          worst = i;
        }
      }
      if (excess > best_excess && worst != kLedgerCauseCount) {
        best_excess = excess;
        best_id = id;
        best_cause = kLedgerCauses[worst];
        best_is_region = is_region;
      }
      // Idle entities (no accounted wall this window) stay out of the
      // delta map — an O(N) roster of zeros helps nobody.
      if (!is_region && d_compute + d_lost_total > 0.0) {
        if (!first) deltas << ",";
        first = false;
        deltas << "\"" << JsonEscape(id) << "\":{\"compute_s\":" << d_compute
               << ",\"lost_s\":" << d_lost_total
               << ",\"excess_s\":" << (excess > 0.0 ? excess : 0.0) << "}";
      }
    }
    // Baseline learns AFTER scoring: the culprit window must not teach
    // the baseline its own anomaly before being judged against it.
    for (size_t i = 0; i < kLedgerCauseCount; ++i) {
      w.base_lost[i] = w.primed
                           ? kBaseAlpha * d_lost[i] + (1.0 - kBaseAlpha) * w.base_lost[i]
                           : d_lost[i];
    }
    w.prev_compute = compute_s;
    for (size_t i = 0; i < kLedgerCauseCount; ++i) w.prev_lost[i] = lost_s[i];
    w.primed = true;
  };
  for (const auto& [id, rl] : ledger_) {
    score(id, win_replicas_[id], rl.compute_s, rl.lost_s, false);
  }
  for (const auto& [name, e] : regions_) {
    score(name, win_regions_[name], e.compute_s, e.lost_s, true);
  }
  // Prune delta state for departed incarnations (banked + pruned from
  // ledger_); regions_ entries live forever, so win_regions_ follows.
  for (auto it = win_replicas_.begin(); it != win_replicas_.end();) {
    if (!ledger_.count(it->first)) {
      it = win_replicas_.erase(it);
    } else {
      ++it;
    }
  }
  deltas << "}";
  if (best_id.empty()) {
    // Quiet window: keep the previous attribution (the alert-refresh path
    // reads it) but record that this window blamed nobody new.
    return;
  }
  last_attr_.replica = best_id;
  last_attr_.cause = best_cause;
  last_attr_.charged_s = best_excess;
  last_attr_.delta_json = deltas.str();
  if (best_is_region) {
    last_attr_.region = best_id;
  } else {
    auto ro = region_of_.find(best_id);
    last_attr_.region =
        ro != region_of_.end() ? ro->second : (fed_child_ ? fed_region_ : "");
  }
}

void Lighthouse::EvaluateSloLocked(double d_compute, double d_lost) {
  if (slo_target_ <= 0.0) return;  // engine off (TPUFT_SLO_TARGET unset)
  slo_windows_.push_back({d_compute, d_lost});
  // Prune to the slow horizon of ACCOUNTED seconds (windows are sized in
  // accounted wall, so the deque's depth is bounded by slow_s / 5 s).
  double total = 0.0;
  for (const auto& w : slo_windows_) total += w.compute_s + w.lost_s;
  while (slo_windows_.size() > 1) {
    double head = slo_windows_.front().compute_s + slo_windows_.front().lost_s;
    if (total - head < slo_slow_s_) break;
    total -= head;
    slo_windows_.pop_front();
  }
  // Burn rate over a horizon: lost fraction of the most recent windows
  // covering `horizon_s` accounted seconds, divided by the error budget
  // (1 - target).  burn == 1.0 consumes the budget exactly at the
  // sustainable rate; > 1.0 is on track to violate the SLO.
  double budget = 1.0 - slo_target_;
  auto burn = [&](double horizon_s) {
    double acc = 0.0, lost = 0.0;
    for (auto it = slo_windows_.rbegin(); it != slo_windows_.rend(); ++it) {
      acc += it->compute_s + it->lost_s;
      lost += it->lost_s;
      if (acc >= horizon_s) break;
    }
    if (acc <= 0.0) return 0.0;
    return (lost / acc) / budget;
  };
  slo_burn_fast_ = burn(slo_fast_s_);
  slo_burn_slow_ = burn(slo_slow_s_);
  // Multi-window discipline: raise only when the fast AND slow windows
  // both burn hot (a transient blip fails the slow window; a long slow
  // bleed fails the fast one once it is bad enough to page on), resolve
  // when the fast window cools.
  AlertRecord* active = nullptr;
  for (auto& a : alerts_) {
    if (a.kind == "slo_burn" && a.resolved_ms == 0) {
      active = &a;
      break;
    }
  }
  bool hot = slo_burn_fast_ > 1.0 && slo_burn_slow_ > 1.0;
  if (hot && active == nullptr) {
    AlertRecord a;
    a.kind = "slo_burn";
    a.replica_id = last_attr_.replica.empty() ? "cluster" : last_attr_.replica;
    a.raised_ms = NowEpochMs();
    a.ratio = slo_burn_fast_;
    a.burn_fast = slo_burn_fast_;
    a.burn_slow = slo_burn_slow_;
    a.dominant_cause = last_attr_.cause;
    a.charged_seconds = last_attr_.charged_s;
    LOGW("lighthouse: slo_burn alert raised (burn fast=%.2f slow=%.2f "
         "target=%.3f culprit=%s cause=%s)",
         slo_burn_fast_, slo_burn_slow_, slo_target_,
         a.replica_id.c_str(),
         a.dominant_cause.empty() ? "-" : a.dominant_cause.c_str());
    PushAlertLocked(std::move(a));
  } else if (active != nullptr) {
    if (slo_burn_fast_ < 1.0) {
      active->resolved_ms = NowEpochMs();
      LOGI("lighthouse: slo_burn alert resolved (burn fast=%.2f slow=%.2f)",
           slo_burn_fast_, slo_burn_slow_);
    } else {
      // Keep the burn rates current so /alerts.json pages with live
      // numbers, but the attribution stays the raise-time verdict: the
      // trailing baseline LEARNS a sustained degradation within a few
      // windows, after which the true victim's "excess" decays and a
      // refreshed culprit would rotate onto whichever healthy replica
      // wobbled last.  A bigger charge may still re-point the blame.
      active->ratio = slo_burn_fast_;
      active->burn_fast = slo_burn_fast_;
      active->burn_slow = slo_burn_slow_;
      if (!last_attr_.replica.empty() &&
          last_attr_.charged_s > active->charged_seconds) {
        active->replica_id = last_attr_.replica;
        active->dominant_cause = last_attr_.cause;
        active->charged_seconds = last_attr_.charged_s;
      }
    }
  }
}

void Lighthouse::RecordIncidentLocked(const std::string& reason,
                                      const std::string& replica_id,
                                      double detail,
                                      const IncidentAttribution* attr) {
  // Debounce per (reason, replica): a flapping trigger must not flood the
  // feed — the capture driver bundles the FIRST record of an episode.
  const int64_t kDebounceMs = 10000;
  int64_t now_ms = NowEpochMs();
  std::string key = reason + "|" + replica_id;
  auto it = incident_last_ms_.find(key);
  if (it != incident_last_ms_.end() && now_ms - it->second < kDebounceMs) return;
  incident_last_ms_[key] = now_ms;
  IncidentRecord rec;
  rec.id = ++incident_seq_;
  rec.reason = reason;
  rec.replica_id = replica_id;
  for (const auto& [id, step] : hb_step_) rec.step = std::max(rec.step, step);
  rec.ts_ms = now_ms;
  rec.detail = detail;
  if (attr != nullptr && !attr->replica.empty()) {
    rec.culprit_replica = attr->replica;
    rec.culprit_region = attr->region;
    rec.dominant_cause = attr->cause;
    rec.charged_seconds = attr->charged_s;
    rec.delta_by_replica_json = attr->delta_json;
  }
  char dbuf[32];
  snprintf(dbuf, sizeof(dbuf), "%.4f", detail);
  std::string msg = "reason=" + reason + " replica=" + replica_id +
                    " step=" + std::to_string(rec.step) + " detail=" + dbuf;
  if (!rec.culprit_replica.empty()) {
    msg += " culprit=" + rec.culprit_replica + " cause=" + rec.dominant_cause;
  }
  flight_.RecordEvent(kFlightIncident, msg);
  LOGW("lighthouse: incident %lld recorded (reason=%s replica=%s step=%lld) "
       "— capture drivers polling /incident.json will bundle the evidence",
       static_cast<long long>(rec.id), reason.c_str(), replica_id.c_str(),
       static_cast<long long>(rec.step));
  incidents_.push_back(std::move(rec));
  const size_t kMaxIncidents = 64;
  if (incidents_.size() > kMaxIncidents) {
    incidents_.erase(incidents_.begin());
  }
}

double Lighthouse::ClusterMedianEwmaLocked() const {
  // Lower median ((n-1)/2 after sort) of the eligible reporting replicas:
  // robust while a MINORITY is slow — with 2 replicas [fast, slow] the
  // upper median would be the slow one's own EWMA and its ratio would read
  // 1.0, hiding exactly the replica the sentinel exists to catch.  The dual
  // failure mode (a majority of stragglers reads as "the fast one is the
  // outlier") is inherent to relative scoring and documented in wire.md.
  auto now = Clock::now();
  auto hb_timeout = std::chrono::milliseconds(opt_.heartbeat_timeout_ms);
  std::vector<double> ewmas;
  for (const auto& [id, h] : health_) {
    if (h.ewma_ms <= 0.0) continue;
    if (state_.draining.count(id)) continue;
    auto hb = state_.heartbeats.find(id);
    if (hb == state_.heartbeats.end() || now - hb->second >= hb_timeout) continue;
    ewmas.push_back(h.ewma_ms);
  }
  if (ewmas.size() < 2) return 0.0;  // nothing to be relative to
  std::sort(ewmas.begin(), ewmas.end());
  return ewmas[(ewmas.size() - 1) / 2];
}

void Lighthouse::RecordSentinelLocked(const std::string& id, int prev,
                                      const ReplicaHealth& h) {
  if (prev == h.state) return;
  char rbuf[32];
  snprintf(rbuf, sizeof(rbuf), "%.3f", h.ratio);
  flight_.RecordEvent(kFlightSentinelTransition,
                      "replica=" + id + " from=" + std::to_string(prev) +
                          " to=" + std::to_string(h.state) + " ratio=" + rbuf);
}

void Lighthouse::ObserveStepTimeLocked(const std::string& id) {
  ReplicaHealth& h = health_[id];
  const int prev_state = h.state;
  h.observations += 1;
  double med = ClusterMedianEwmaLocked();
  h.ratio = med > 0.0 ? h.ewma_ms / med : 0.0;
  if (med <= 0.0) {
    // Unscorable (fewer than two eligible reporters): relative slowness is
    // meaningless, so the observation counts toward RECOVERY — without
    // this, a flagged straggler whose last peer died would stay in state 2
    // with an active alert forever (nothing else clears a state while the
    // replica keeps heartbeating), paging operators about a healthy sole
    // survivor.
    if (h.state != 0) {
      h.over = 0;
      h.under += 1;
      if (h.state == 1) {
        h.state = 0;
        h.under = 0;
      } else if (h.state == 2 && h.under >= straggler_grace_) {
        h.state = 0;
        h.under = 0;
        LOGI("lighthouse: replica %s straggler state cleared (no peers left "
             "to score against)", id.c_str());
        ResolveAlertsLocked(id);
      }
    }
    RecordSentinelLocked(id, prev_state, h);
    return;
  }
  if (h.ratio >= straggler_ratio_) {
    h.under = 0;
    h.over += 1;
    if (h.state == 0) {
      h.state = 1;
      LOGW("lighthouse: replica %s suspect straggler (step time %.1f ms, "
           "%.2fx cluster median)", id.c_str(), h.ewma_ms, h.ratio);
    } else if (h.state == 1 && h.over >= straggler_grace_ &&
               h.observations > straggler_warmup_) {
      // The warmup gate keeps JIT-compile asymmetry (first steps are
      // 10-100x steady state, and not evenly so across replicas) from
      // raising alerts — or worse, auto-draining — a replica that is
      // merely compiling.  A genuinely slow host stays suspect through
      // the warmup and promotes on the first eligible observation.
      h.state = 2;
      RaiseStragglerAlertLocked(id, &h);
    } else if (h.state == 2) {
      // Still confirmed slow: re-attempt a rotation that was skipped at
      // the min_replicas floor when the alert first raised — capacity may
      // have recovered since (a new replica joined), and "auto-drain,
      // never below the floor" must mean whenever capacity allows, not
      // only at the instant of the first alert.
      if (MaybeAutoDrainLocked(id, /*log_skip=*/false, straggler_auto_drain_)) {
        for (auto& a : alerts_) {
          if (a.replica_id == id && a.resolved_ms == 0) a.auto_drained = true;
        }
      }
    }
  } else {
    h.over = 0;
    h.under += 1;
    if (h.state == 1) {
      // A suspect that produced one on-pace step was a blip, not a slow
      // host; drop it immediately (promotion needed the full grace).
      h.state = 0;
      h.under = 0;
    } else if (h.state == 2 && h.under >= straggler_grace_) {
      h.state = 0;
      h.under = 0;
      LOGI("lighthouse: replica %s recovered from straggler state "
           "(step time %.1f ms, %.2fx median)", id.c_str(), h.ewma_ms, h.ratio);
      ResolveAlertsLocked(id);
    }
  }
  RecordSentinelLocked(id, prev_state, h);
}

void Lighthouse::RaiseStragglerAlertLocked(const std::string& id, ReplicaHealth* h) {
  for (const auto& a : alerts_) {
    if (a.replica_id == id && a.resolved_ms == 0) return;  // already active
  }
  AlertRecord a;
  a.id = ++alert_seq_;
  a.kind = "straggler";
  a.replica_id = id;
  a.raised_ms = NowEpochMs();
  a.ratio = h->ratio;
  a.step_time_ms = h->ewma_ms;
  LOGW("lighthouse: replica %s is a persistent straggler (step time %.1f ms, "
       "%.2fx cluster median over %lld steps) — alert %lld raised",
       id.c_str(), h->ewma_ms, h->ratio,
       static_cast<long long>(straggler_grace_), static_cast<long long>(a.id));
  a.auto_drained = MaybeAutoDrainLocked(id, /*log_skip=*/true, straggler_auto_drain_);
  PushAlertLocked(std::move(a));
}

double Lighthouse::ClusterMedianLinkGbpsLocked() const {
  // UPPER median of the eligible reporting replicas — the mirror image of
  // the straggler sentinel's lower median: goodput degrades DOWNWARD, so
  // with 2 replicas [slow, fast] the lower median would be the slow one's
  // own reading and its ratio would read 1.0, hiding exactly the edge the
  // sentinel exists to catch.  The dual failure mode (a majority of
  // degraded links reads as "the fast edge is the outlier") is inherent
  // to relative scoring, like the straggler case.
  auto now = Clock::now();
  auto hb_timeout = std::chrono::milliseconds(opt_.heartbeat_timeout_ms);
  std::vector<double> gbps;
  for (const auto& [id, lh] : link_health_) {
    if (lh.send_gbps <= 0.0) continue;
    if (state_.draining.count(id)) continue;
    auto hb = state_.heartbeats.find(id);
    if (hb == state_.heartbeats.end() || now - hb->second >= hb_timeout) continue;
    gbps.push_back(lh.send_gbps);
  }
  if (gbps.size() < 2) return 0.0;  // nothing to be relative to
  std::sort(gbps.begin(), gbps.end());
  return gbps[gbps.size() / 2];
}

std::string Lighthouse::RingSuccessorLocked(const std::string& id) const {
  // The cross-group ring orders participants by sorted replica id (the
  // quorum sort TCPCollective configures against), so the receiving
  // endpoint of `id`'s outbound edge is its successor in the last formed
  // quorum's participant list.
  if (!state_.prev_quorum) return "";
  const auto& parts = state_.prev_quorum->participants();
  int n = parts.size();
  for (int i = 0; i < n; ++i) {
    if (parts[i].replica_id() == id) {
      return n > 1 ? parts[(i + 1) % n].replica_id() : "";
    }
  }
  return "";
}

void Lighthouse::ObserveLinkLocked(const std::string& id) {
  LinkHealth& h = link_health_[id];
  const int prev_state = h.state;
  h.observations += 1;
  double med = ClusterMedianLinkGbpsLocked();
  h.ratio = (med > 0.0 && h.send_gbps > 0.0) ? med / h.send_gbps : 0.0;
  auto record = [&]() {
    if (prev_state == h.state) return;
    char rbuf[32];
    snprintf(rbuf, sizeof(rbuf), "%.3f", h.ratio);
    flight_.RecordEvent(kFlightSentinelTransition,
                        "sentinel=link replica=" + id + " from=" +
                            std::to_string(prev_state) + " to=" +
                            std::to_string(h.state) + " ratio=" + rbuf);
  };
  if (med <= 0.0) {
    // Unscorable (fewer than two eligible reporters): count toward
    // recovery exactly like the straggler sentinel, so a flagged edge
    // whose last peer died cannot page forever.
    if (h.state != 0) {
      h.over = 0;
      h.under += 1;
      if (h.state == 1) {
        h.state = 0;
        h.under = 0;
      } else if (h.state == 2 && h.under >= link_grace_) {
        h.state = 0;
        h.under = 0;
        ResolveLinkAlertsLocked(id);
      }
    }
    record();
    return;
  }
  if (h.ratio >= link_ratio_) {
    h.under = 0;
    h.over += 1;
    if (h.state == 0) {
      h.state = 1;
      LOGW("lighthouse: replica %s outbound link suspect (%.3f GB/s, "
           "%.2fx below cluster median)", id.c_str(), h.send_gbps, h.ratio);
    } else if (h.state == 1 && h.over >= link_grace_ &&
               h.observations > link_warmup_) {
      // Warmup mirrors the straggler gate: first steps mix rendezvous,
      // JIT warmup, and cold kernel socket buffers into the goodput
      // estimate asymmetrically across replicas.
      h.state = 2;
      RaiseLinkAlertLocked(id, &h);
    } else if (h.state == 2) {
      // Still confirmed degraded: re-attempt a rotation skipped at the
      // min_replicas floor (capacity may have recovered since).
      std::string dst = RingSuccessorLocked(id);
      if (!dst.empty() &&
          MaybeAutoDrainLocked(dst, /*log_skip=*/false, link_auto_drain_)) {
        for (auto& a : alerts_) {
          if (a.kind == "slow_link" && a.src_replica_id == id &&
              a.resolved_ms == 0) {
            a.auto_drained = true;
          }
        }
      }
    }
  } else {
    h.over = 0;
    h.under += 1;
    if (h.state == 1) {
      h.state = 0;
      h.under = 0;
    } else if (h.state == 2 && h.under >= link_grace_) {
      h.state = 0;
      h.under = 0;
      LOGI("lighthouse: replica %s outbound link recovered (%.3f GB/s, "
           "%.2fx median)", id.c_str(), h.send_gbps, h.ratio);
      ResolveLinkAlertsLocked(id);
    }
  }
  record();
}

void Lighthouse::RaiseLinkAlertLocked(const std::string& id, LinkHealth* h) {
  for (const auto& a : alerts_) {
    if (a.kind == "slow_link" && a.src_replica_id == id && a.resolved_ms == 0) {
      return;  // already active
    }
  }
  AlertRecord a;
  a.id = ++alert_seq_;
  a.kind = "slow_link";
  // The alert names the degraded EDGE by its receiving endpoint — the
  // node whose inbound path degraded and the auto-drain target; the
  // reporting sender rides in src_replica_id.  With no known quorum
  // order the alert falls back to naming the reporter itself.
  std::string dst = RingSuccessorLocked(id);
  a.replica_id = dst.empty() ? id : dst;
  a.src_replica_id = id;
  a.raised_ms = NowEpochMs();
  a.ratio = h->ratio;
  a.gbps = h->send_gbps;
  LOGW("lighthouse: link %s -> %s is persistently degraded (%.3f GB/s "
       "outbound, %.2fx below cluster median over %lld steps) — alert %lld "
       "raised", id.c_str(), a.replica_id.c_str(), h->send_gbps, h->ratio,
       static_cast<long long>(link_grace_), static_cast<long long>(a.id));
  a.auto_drained =
      MaybeAutoDrainLocked(a.replica_id, /*log_skip=*/true, link_auto_drain_);
  PushAlertLocked(std::move(a));
}

void Lighthouse::ResolveLinkAlertsLocked(const std::string& src_id) {
  for (auto& a : alerts_) {
    if (a.kind == "slow_link" && a.src_replica_id == src_id &&
        a.resolved_ms == 0) {
      a.resolved_ms = NowEpochMs();
    }
  }
}

void Lighthouse::PushAlertLocked(AlertRecord a) {
  // Every alert raise is an incident trigger: the sentinels page on
  // exactly the degradations whose evidence the auto-capture bundles
  // (straggler, slow_link, ec_coverage alike).
  RecordIncidentLocked("alert:" + a.kind, a.replica_id,
                       a.ratio > 0.0 ? a.ratio : a.gbps,
                       a.kind == "slo_burn" ? &last_attr_ : nullptr);
  alerts_.push_back(std::move(a));
  // Bounded history: drop the oldest RESOLVED record first; active alerts
  // are never evicted (there can be at most one per live replica id, plus
  // one cluster-scope record per cluster-level kind).
  const size_t kMaxAlerts = 64;
  if (alerts_.size() > kMaxAlerts) {
    for (auto it = alerts_.begin(); it != alerts_.end(); ++it) {
      if (it->resolved_ms != 0) {
        alerts_.erase(it);
        break;
      }
    }
  }
}

bool Lighthouse::HeartbeatFreshLocked(const std::string& id,
                                      TimePoint now) const {
  auto hb = state_.heartbeats.find(id);
  return hb != state_.heartbeats.end() &&
         now - hb->second < std::chrono::milliseconds(opt_.heartbeat_timeout_ms);
}

void Lighthouse::CheckEcCoverageLocked() {
  if (ec_k_ <= 0 || !ec_seen_) return;
  // Only heartbeat-FRESH holders count: a dead holder's inventory stays
  // in ec_shards_ until the 10x graveyard prune, but its shards are
  // unreachable the moment its heartbeats stop — redundancy the page
  // exists to notice losing.  (Same freshness rule the /metrics gauge
  // uses, so the alert fires exactly when the dashboard reads < k + 1.)
  auto now = Clock::now();
  auto fresh = [&](const std::string& id) { return HeartbeatFreshLocked(id, now); };
  int64_t ec_step = 0, coverage = 0;
  for (const auto& [id, sc] : ec_shards_) {
    if (fresh(id)) ec_step = std::max(ec_step, sc.first);
  }
  for (const auto& [id, sc] : ec_shards_) {
    if (fresh(id) && sc.first == ec_step) coverage += sc.second;
  }
  int64_t threshold = ec_k_ + 1;
  AlertRecord* active = nullptr;
  for (auto& a : alerts_) {
    if (a.kind == "ec_coverage" && a.resolved_ms == 0) {
      active = &a;
      break;
    }
  }
  int64_t now_ms = NowEpochMs();
  if (coverage >= threshold) {
    ec_low_since_ms_ = 0;
    if (active != nullptr) {
      active->coverage = coverage;
      active->resolved_ms = now_ms;
      LOGI("lighthouse: EC shard coverage recovered to %lld (>= k + 1 = %lld) "
           "— alert %lld resolved",
           static_cast<long long>(coverage), static_cast<long long>(threshold),
           static_cast<long long>(active->id));
    }
    return;
  }
  if (active != nullptr) {
    active->coverage = coverage;  // keep the live reading on the record
    return;
  }
  // Grace: each holder re-reports its count at a NEW encode generation as
  // its own heartbeats land, so coverage at the newest step legitimately
  // dips for up to a heartbeat interval per encode.  Only a dip that
  // outlives a full heartbeat timeout is a real redundancy loss.
  if (ec_low_since_ms_ == 0) {
    ec_low_since_ms_ = now_ms;
    return;
  }
  if (now_ms - ec_low_since_ms_ <
      static_cast<int64_t>(opt_.heartbeat_timeout_ms)) {
    return;
  }
  AlertRecord a;
  a.id = ++alert_seq_;
  a.kind = "ec_coverage";
  a.replica_id = "cluster";
  a.raised_ms = now_ms;
  a.coverage = coverage;
  a.threshold = threshold;
  LOGW("lighthouse: EC shard coverage %lld at encode step %lld is below "
       "k + 1 = %lld — one more holder loss makes the newest generation "
       "unreconstructable; alert %lld raised",
       static_cast<long long>(coverage), static_cast<long long>(ec_step),
       static_cast<long long>(threshold), static_cast<long long>(a.id));
  PushAlertLocked(std::move(a));
}

void Lighthouse::ResolveAlertsLocked(const std::string& id) {
  for (auto& a : alerts_) {
    if (a.replica_id == id && a.resolved_ms == 0) a.resolved_ms = NowEpochMs();
  }
}

bool Lighthouse::MaybeAutoDrainLocked(const std::string& id, bool log_skip,
                                      bool enabled) {
  // Rotate the slow host out through the cooperative-drain path, but only
  // while the remaining healthy set still satisfies the quorum floor —
  // the sentinel must never drain the cluster below min_replicas.  The
  // supervisor completes the handoff (Launcher polls /alerts.json and
  // pre-warms the replacement); the mark alone already removes the
  // straggler from the NEXT quorum so survivors stop pacing on it.
  if (!enabled) return false;
  if (state_.draining.count(id)) return true;  // already rotating
  auto now = Clock::now();
  auto hb_timeout = std::chrono::milliseconds(opt_.heartbeat_timeout_ms);
  int64_t healthy = 0;
  for (const auto& [hid, last] : state_.heartbeats) {
    if (!state_.draining.count(hid) && now - last < hb_timeout) ++healthy;
  }
  if (healthy > static_cast<int64_t>(opt_.min_replicas)) {
    DrainLocked(id, 0);
    return true;
  }
  if (log_skip) {
    LOGW("lighthouse: auto-drain of straggler %s deferred — only %lld "
         "healthy replicas (min_replicas %llu); retried while the replica "
         "stays a straggler", id.c_str(), static_cast<long long>(healthy),
         static_cast<unsigned long long>(opt_.min_replicas));
  }
  return false;
}

Status Lighthouse::HandleQuorum(const LighthouseQuorumRequest& req, Deadline deadline,
                                LighthouseQuorumResponse* resp, std::string* err) {
  const std::string& id = req.requester().replica_id();
  if (id.empty()) {
    *err = "replica_id must be set";
    return Status::kInvalidArgument;
  }
  std::unique_lock<std::mutex> lk(mu_);
  if (!IsLeaderLocked()) {
    // Split-brain guard: a standby (or an expired-lease leader) must never
    // serve a quorum — two lighthouses forming quorums independently could
    // hand two disjoint replica sets the same quorum id.  The rejection
    // names the leader so the client redirects instead of retrying here.
    *err = NotLeaderErrLocked();
    return Status::kUnavailable;
  }
  if (evicted_.count(id)) {
    // The supervisor declared this exact incarnation dead; a late join
    // from it is a zombie (e.g. a request already in flight when the
    // process was reaped) and must not resurrect the corpse.
    *err = "replica " + id + " was evicted by its supervisor";
    return Status::kAborted;
  }
  if (state_.draining.count(id)) {
    // The incarnation announced a cooperative departure: it finishes its
    // in-flight step and exits — it must not start a NEW round.  (The
    // drain controller stops the train loop before the next quorum; this
    // guards the race where the notice lands mid-call.)  "is draining" is
    // a GREP CONTRACT with the Python Manager (_async_quorum converts this
    // abort into a cooperative drain exit; pinned by
    // tests/test_straggler.py) — keep both message sites in sync if
    // rewording.  A "(deadline_ms=N)" suffix carries the announced grace
    // remainder so the manager paces its auto-drain to the real deadline
    // instead of a hardcoded default.
    *err = "replica " + id + " is draining; rejoin as a new incarnation";
    if (auto dl = drain_deadline_ms_.find(id); dl != drain_deadline_ms_.end()) {
      int64_t remain = dl->second - NowEpochMs();
      if (remain > 0) *err += " (deadline_ms=" + std::to_string(remain) + ")";
    }
    return Status::kAborted;
  }
  // First contact from this incarnation (no heartbeat on file): the join
  // that introduces a new member is a state transition worth keeping.
  if (state_.heartbeats.find(id) == state_.heartbeats.end()) {
    flight_.RecordEvent(kFlightReplicaJoin,
                        "replica=" + id + " step=" +
                            std::to_string(req.requester().step()),
                        req.trace_id());
  }
  // Joining is an implicit heartbeat (reference: src/lighthouse.rs:480-491).
  state_.heartbeats[id] = Clock::now();
  // ...and carries the requester's step: keep the live view fresh for
  // clients whose heartbeat loop lags the join (raw wire clients).
  {
    auto step_it = hb_step_.find(id);
    if (step_it == hb_step_.end() || req.requester().step() > step_it->second) {
      if (step_it != hb_step_.end()) last_commit_ms_[id] = NowEpochMs();
      hb_step_[id] = req.requester().step();
    }
  }
  state_.participants[id] = QuorumState::Joined{req.requester(), Clock::now()};
  // Only quorums broadcast after this join count — a stale quorum from a
  // previous round must not satisfy this request.
  int64_t start_gen = quorum_gen_;
  TickLocked();

  // Wait for a quorum broadcast that includes the requester; a member may be
  // excluded from the quorum its own join triggered (e.g. shrink_only), in
  // which case it keeps waiting for a later round (src/lighthouse.rs:494-530).
  while (true) {
    if (!IsLeaderLocked()) {
      // Demoted (or lease lapsed) while this join was blocked: the quorum
      // it waits for will never form HERE — unblock the caller with the
      // redirect so it rejoins at the new leader.  This is what "an
      // expired-lease leader stops answering Quorum authoritatively"
      // means for handlers already in flight.
      *err = NotLeaderErrLocked();
      return Status::kUnavailable;
    }
    if (evicted_.count(id)) {
      // Evicted while blocked here: abort instead of re-registering (the
      // re-register below would resurrect a corpse the supervisor already
      // replaced with a fresh incarnation).
      *err = "replica " + id + " was evicted by its supervisor";
      return Status::kAborted;
    }
    if (state_.draining.count(id)) {
      // Drain notice landed while this join was blocked: the quorum it is
      // waiting for will exclude it forever — unblock the caller so the
      // departing process can proceed to its drain exit.
      *err = "replica " + id + " is draining; rejoin as a new incarnation";
      if (auto dl = drain_deadline_ms_.find(id);
          dl != drain_deadline_ms_.end()) {
        int64_t remain = dl->second - NowEpochMs();
        if (remain > 0) *err += " (deadline_ms=" + std::to_string(remain) + ")";
      }
      return Status::kAborted;
    }
    if (latest_quorum_ && quorum_gen_ > start_gen) {
      for (const auto& m : latest_quorum_->participants()) {
        if (m.replica_id() == id) {
          *resp->mutable_quorum() = *latest_quorum_;
          return Status::kOk;
        }
      }
      // A quorum formed WITHOUT this requester (e.g. a shrink_only round
      // excluded a fresh joiner).  Formation cleared `participants`, so
      // re-register for the next round or this caller would never be
      // considered again (reference: the pending request stays queued,
      // src/lighthouse.rs:494-530 / test at src/lighthouse.rs:1078-1181).
      // Re-joining is an implicit heartbeat like the initial join above:
      // a raw wire client (docs/wire.md) with no heartbeat loop must not
      // age out of the healthy filter while it blocks here.
      state_.heartbeats[id] = Clock::now();
      state_.participants.emplace(id,
                                  QuorumState::Joined{req.requester(), Clock::now()});
    }
    int64_t gen = quorum_gen_;
    bool woke = quorum_cv_.wait_until(lk, deadline.at, [&] {
      return quorum_gen_ != gen || shutdown_ || evicted_.count(id) > 0 ||
             state_.draining.count(id) > 0 || !IsLeaderLocked();
    });
    if (shutdown_) {
      *err = "lighthouse shutting down";
      return Status::kUnavailable;
    }
    if (!woke && deadline.expired()) {
      *err = "timed out waiting for quorum";
      return Status::kDeadlineExceeded;
    }
  }
}

void Lighthouse::TickLoop() {
  while (true) {
    // Heartbeat fan-in cost since the previous tick: one histogram
    // observation per tick interval that handled >= 1 heartbeat.  Observed
    // here (not in TickLocked) so join-triggered quorum attempts do not
    // fabricate extra intervals.
    int64_t fanin_us = hb_fanin_accum_us_.exchange(0, std::memory_order_relaxed);
    int64_t fanin_n = hb_fanin_count_.exchange(0, std::memory_order_relaxed);
    if (fanin_n > 0) heartbeat_fanin_hist_.Observe(fanin_us / 1e6);
    {
      std::unique_lock<std::mutex> lk(mu_);
      if (shutdown_) return;
      TickLocked();
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(opt_.quorum_tick_ms));
  }
}

void Lighthouse::TickLocked() {
  // HA: only the live lease holder runs the quorum machine.  A follower's
  // tick would otherwise form quorums from its replicated view — the exact
  // split brain the role exists to prevent.  The wakeup covers the lease
  // LAPSING between SetRole calls (a stalled renewal thread): blocked
  // quorum joins must notice within a tick, not at their deadlines.
  if (!IsLeaderLocked()) {
    quorum_cv_.notify_all();
    return;
  }
  auto tick_now = Clock::now();
  auto hb_timeout = std::chrono::milliseconds(opt_.heartbeat_timeout_ms);
  // Housekeeping sweep below (freshness-transition logs + graveyard /
  // tombstone / drain-mark / live-status prunes) walks every per-replica
  // map.  TickLocked also runs once per quorum JOIN (HandleQuorum ticks to
  // try forming immediately), so a mass-preemption rejoin wave of N
  // replicas used to run these O(N) scans N times per round — O(N^2) map
  // visits exactly when the control plane is busiest.  The sweep is
  // bounded to a fraction of the heartbeat timeout instead (prune horizons
  // are 10x that timeout, so a sub-timeout sweep delay changes nothing
  // observable); the quorum math after it still runs on EVERY call.
  auto sweep_every = std::chrono::milliseconds(
      std::max<int64_t>(10, std::min<int64_t>(500, opt_.heartbeat_timeout_ms / 4)));
  if (tick_now - last_sweep_ >= sweep_every) {
    last_sweep_ = tick_now;
    SweepLocked(tick_now, hb_timeout);
  }

  // Federated child: quorum formation is the ROOT's job — the push loop
  // reports this region's membership upward and installs the root's
  // returned GLOBAL quorum (InstallGlobalQuorumLocked), which is what
  // wakes this instance's blocked joiners.  The sweep above still runs:
  // the child owns its region's sentinels, prunes and ledger banking.
  if (fed_child_) return;

  // Formation latency reference point: the round's first joiner (the same
  // origin QuorumCompute's straggler wait uses).  Captured before the
  // compute because formation clears `participants`.
  TimePoint first_join = TimePoint::max();
  for (const auto& [id, j] : state_.participants) {
    first_join = std::min(first_join, j.joined_at);
  }

  std::string reason;
  auto members = QuorumCompute(Clock::now(), state_, opt_, &reason);
  // Log each distinct reason ONCE per membership situation: during healthy
  // steady state the tick alternates between the waiting reason and the
  // formed reason every round, so last-value dedup printed both at O(steps).
  // The set resets whenever quorum membership changes (below), which is the
  // reference's ChangeLogger discipline (src/lighthouse.rs:68-84).
  if (!reason.empty() && logged_reasons_.insert(reason).second) {
    LOGI("lighthouse: %s", reason.c_str());
  }
  if (!members) return;

  double formation_s =
      first_join == TimePoint::max()
          ? 0.0
          : std::chrono::duration<double>(Clock::now() - first_join).count();
  quorum_formation_hist_.Observe(formation_s);

  // Bump the quorum id only when membership changed
  // (reference: src/lighthouse.rs:288-304).
  bool changed = true;
  std::set<std::string> new_ids;
  for (const auto& m : *members) new_ids.insert(m.replica_id());
  std::set<std::string> old_ids;
  if (state_.prev_quorum) {
    for (const auto& m : state_.prev_quorum->participants()) {
      old_ids.insert(m.replica_id());
    }
    changed = old_ids != new_ids;
  }
  if (changed) state_.quorum_id += 1;

  Quorum q;
  q.set_quorum_id(state_.quorum_id);
  q.set_created_ms(NowEpochMs());
  for (const auto& m : *members) *q.add_participants() = m;

  state_.prev_quorum = q;
  // Every replica must re-join for the next round (src/lighthouse.rs:314-319).
  state_.participants.clear();
  latest_quorum_ = q;
  quorum_gen_ += 1;
  quorum_cv_.notify_all();
  // Log formation only when membership actually changed: a healthy 2-group
  // job forms an identical quorum every training step, and logging each one
  // made the lighthouse log O(steps) (VERDICT r3 #5).
  if (changed) {
    std::string ids;
    for (const auto& m : q.participants()) {
      if (!ids.empty()) ids += ", ";
      ids += m.replica_id();
    }
    LOGI("lighthouse: formed quorum %lld with %d participants [%s]",
         static_cast<long long>(state_.quorum_id), q.participants_size(),
         ids.c_str());
    logged_reasons_.clear();
    // Flight event only on MEMBERSHIP TRANSITIONS (same dedup discipline
    // as the log line): the ring then retains the quorum-change history a
    // post-mortem reconstructs, instead of O(steps) identical formations.
    auto join_list = [](const std::set<std::string>& s) {
      std::string out;
      for (const auto& id : s) {
        if (!out.empty()) out += ",";
        out += id;
      }
      return out;
    };
    std::set<std::string> joined, left;
    for (const auto& id : new_ids) {
      if (!old_ids.count(id)) joined.insert(id);
    }
    for (const auto& id : old_ids) {
      if (!new_ids.count(id)) left.insert(id);
    }
    char fbuf[32];
    snprintf(fbuf, sizeof(fbuf), "%.3f", formation_s * 1e3);
    flight_.RecordEvent(
        kFlightQuorumFormed,
        "quorum_id=" + std::to_string(state_.quorum_id) +
            " members=[" + join_list(new_ids) + "] joined=[" +
            join_list(joined) + "] left=[" + join_list(left) +
            "] formation_ms=" + fbuf);
  }
}

void Lighthouse::SweepLocked(TimePoint tick_now,
                             std::chrono::milliseconds hb_timeout) {
  // Log healthy<->stale transitions: when a replica is declared dead (or
  // comes back) the operator must be able to see it and its heartbeat age.
  for (const auto& [id, last] : state_.heartbeats) {
    if (state_.draining.count(id)) continue;  // a drained donor's clean
    // exit makes its heartbeat stale by design — not a death to announce.
    bool fresh = tick_now - last < hb_timeout;
    auto it = last_fresh_.find(id);
    if (it == last_fresh_.end()) {
      last_fresh_[id] = fresh;
    } else if (it->second != fresh) {
      it->second = fresh;
      auto age_ms =
          std::chrono::duration_cast<std::chrono::milliseconds>(tick_now - last).count();
      if (fresh) {
        LOGI("lighthouse: replica %s heartbeat recovered", id.c_str());
      } else {
        LOGW("lighthouse: replica %s heartbeat stale (age %lld ms) — declaring dead",
             id.c_str(), static_cast<long long>(age_ms));
        // The kill signature: an UNANNOUNCED heartbeat loss (drains were
        // excluded above, evictions never reach here).  Trigger incident
        // auto-capture so the dead window's evidence is bundled while the
        // survivors' context is still hot.
        RecordIncidentLocked("replica_stale", id,
                             static_cast<double>(age_ms));
      }
    }
  }
  // Evict replicas dead for >10x the heartbeat timeout: they are invisible
  // to quorum already (the healthy filter uses age < timeout, so this cannot
  // change quorum or split-brain arithmetic) and under replica-id churn
  // (uuid-suffixed ids across restarts) the maps otherwise grow without
  // bound, with every tick iterating the graveyard.  Pending joiners are
  // exempt: a replica with a blocked Join RPC that stalls past the horizon
  // (e.g. JIT-compile starvation) and then recovers must still be counted
  // when the quorum finally forms — participants is cleared every quorum
  // round anyway, so this exemption cannot leak.
  for (auto it = state_.heartbeats.begin(); it != state_.heartbeats.end();) {
    if (tick_now - it->second > hb_timeout * 10 &&
        state_.participants.find(it->first) == state_.participants.end()) {
      it = state_.heartbeats.erase(it);
    } else {
      ++it;
    }
  }
  // Tombstones outlive any in-flight zombie RPC by far at 10x the
  // heartbeat timeout; prune so id churn cannot grow the map unboundedly.
  for (auto it = evicted_.begin(); it != evicted_.end();) {
    if (tick_now - it->second > hb_timeout * 10) {
      it = evicted_.erase(it);
    } else {
      ++it;
    }
  }
  // Drain marks age out on the same horizon — but never before the
  // ANNOUNCED deadline passes: a 5-minute Kubernetes grace period must
  // keep the donor excluded for all 5 minutes (it may legitimately keep
  // heartbeating while it serves a long final checkpoint), while
  // replacement incarnations carry fresh uuids so exact-id marks cannot
  // block a legitimate member either way.
  for (auto it = state_.draining.begin(); it != state_.draining.end();) {
    bool horizon_passed = tick_now - it->second > hb_timeout * 10;
    auto dl = drain_deadline_ms_.find(it->first);
    bool deadline_passed =
        dl == drain_deadline_ms_.end() || NowEpochMs() > dl->second;
    if (horizon_passed && deadline_passed) {
      drain_deadline_ms_.erase(it->first);
      it = state_.draining.erase(it);
    } else {
      ++it;
    }
  }
  for (auto it = last_fresh_.begin(); it != last_fresh_.end();) {
    if (state_.heartbeats.find(it->first) == state_.heartbeats.end()) {
      it = last_fresh_.erase(it);
    } else {
      ++it;
    }
  }
  // Live-status maps follow the heartbeat graveyard: under uuid-suffixed
  // id churn they would otherwise grow without bound.
  auto prune_with_heartbeats = [&](auto& m) {
    for (auto it = m.begin(); it != m.end();) {
      if (state_.heartbeats.find(it->first) == state_.heartbeats.end()) {
        it = m.erase(it);
      } else {
        ++it;
      }
    }
  };
  prune_with_heartbeats(hb_step_);
  prune_with_heartbeats(hb_state_);
  prune_with_heartbeats(last_commit_ms_);
  prune_with_heartbeats(allreduce_gbps_);
  prune_with_heartbeats(ec_shards_);
  prune_with_heartbeats(region_of_);
  // Ledger entries bank before they prune: a departed incarnation's
  // accounted seconds belong to the cluster totals forever — pruning
  // without banking would make tpuft_lost_seconds_total go backwards
  // under exactly the id churn a fault run produces.
  for (auto it = ledger_.begin(); it != ledger_.end();) {
    if (state_.heartbeats.find(it->first) == state_.heartbeats.end()) {
      BankLedgerLocked(it->first, /*undoable=*/true);
      it = ledger_.erase(it);
    } else {
      ++it;
    }
  }
  // Bank-undo entries age out on the tombstone horizon: a same-id resume
  // that late is beyond the system's zombie window everywhere else too.
  {
    int64_t now_ms = NowEpochMs();
    int64_t horizon_ms = static_cast<int64_t>(opt_.heartbeat_timeout_ms) * 10;
    for (auto it = ledger_banked_entries_.begin();
         it != ledger_banked_entries_.end();) {
      if (now_ms - it->second.second > horizon_ms) {
        it = ledger_banked_entries_.erase(it);
      } else {
        ++it;
      }
    }
  }
  // Incident-debounce stamps age out once far past any debounce window:
  // keys embed incarnation ids ("replica_stale|<group>:<uuid>"), so a
  // crash-looping group would otherwise grow the map one key per restart
  // for the life of the daemon.
  {
    int64_t now_ms = NowEpochMs();
    const int64_t kDebounceHorizonMs = 10 * 10000;  // 10x the debounce
    for (auto it = incident_last_ms_.begin(); it != incident_last_ms_.end();) {
      if (now_ms - it->second > kDebounceHorizonMs) {
        it = incident_last_ms_.erase(it);
      } else {
        ++it;
      }
    }
  }
  // Sentinel health follows the graveyard too, and a pruned replica's
  // active alert resolves here: a process that is gone (crashed, drained
  // out, auto-drained straggler that exited) can never post the recovery
  // observations that would clear it organically.
  for (auto it = health_.begin(); it != health_.end();) {
    if (state_.heartbeats.find(it->first) == state_.heartbeats.end()) {
      ResolveAlertsLocked(it->first);
      it = health_.erase(it);
    } else {
      ++it;
    }
  }
  // Slow-link health follows the graveyard: a pruned REPORTER can never
  // post the recovery observations that would resolve its edge's alert.
  for (auto it = link_health_.begin(); it != link_health_.end();) {
    if (state_.heartbeats.find(it->first) == state_.heartbeats.end()) {
      ResolveLinkAlertsLocked(it->first);
      it = link_health_.erase(it);
    } else {
      ++it;
    }
  }
  // Federation root: regions whose digest pushes stopped (docs/wire.md
  // "Federation") — the region-scale analogue of the stale transition
  // above.
  SweepRegionsLocked(tick_now, hb_timeout);
  // Coverage sentinel: the sweep is what notices holders DYING (their
  // freshness lapses without any heartbeat to trigger the check).
  CheckEcCoverageLocked();
}

void Lighthouse::FillStatus(LighthouseStatusResponse* resp) {
  std::lock_guard<std::mutex> lk(mu_);
  if (state_.prev_quorum) *resp->mutable_prev_quorum() = *state_.prev_quorum;
  for (const auto& [id, j] : state_.participants) *resp->add_pending_participants() = j.member;
  auto now = Clock::now();
  for (const auto& [id, last] : state_.heartbeats) {
    (*resp->mutable_heartbeat_age_ms())[id] =
        std::chrono::duration_cast<std::chrono::milliseconds>(now - last).count();
  }
  resp->set_quorum_id(state_.quorum_id);
  for (const auto& [id, _] : state_.draining) resp->add_draining(id);
  for (const auto& [id, step] : hb_step_) (*resp->mutable_replica_step())[id] = step;
  for (const auto& [id, ms] : last_commit_ms_) (*resp->mutable_last_commit_ts_ms())[id] = ms;
  for (const auto& [id, st] : hb_state_) (*resp->mutable_replica_state())[id] = st;
  // Straggler sentinel maps (ints on the wire: state, rounded EWMA ms,
  // slowness in permille; the full-precision doubles ride on /metrics).
  for (const auto& [id, h] : health_) {
    (*resp->mutable_straggler_state())[id] = h.state;
    (*resp->mutable_replica_step_time_ms())[id] =
        static_cast<int64_t>(h.ewma_ms + 0.5);
    if (h.ratio > 0.0) {
      (*resp->mutable_replica_slowness_permille())[id] =
          static_cast<int64_t>(h.ratio * 1000.0 + 0.5);
    }
  }
}

int Lighthouse::EvictReplica(const std::string& prefix) {
  // Tombstones cover IDS SEEN at evict time.  A first-contact join that
  // was serialized by the dying process but not yet dispatched here can
  // still register afterwards — that zombie self-heals within
  // heartbeat_timeout (it never commits or heartbeats again), which is the
  // pre-eviction behavior for a bounded, microsecond-scale window.
  // Tombstoning the whole "<prefix>:" FAMILY instead would be wrong: the
  // replacement incarnation shares the prefix and joins milliseconds
  // later (hot-spare adoption), so it must not be blocked.
  std::lock_guard<std::mutex> lk(mu_);
  int dropped = 0;
  auto now = Clock::now();
  auto matches = [&](const std::string& id) {
    return id == prefix || id.rfind(prefix + ":", 0) == 0;
  };
  // Federation root: route the eviction DOWN to the owning region(s) as a
  // one-shot directive on their next digest response — the CHILD owns the
  // members' heartbeats, so dropping them only here would let the next
  // digest re-register the corpse.  Queued before the local drops erase
  // the region_of_ ownership rows; a prefix no region is known to own
  // broadcasts (the supervisor may be ahead of the first digest).
  if (!regions_.empty()) {
    std::set<std::string> targets;
    for (const auto& [id, region] : region_of_) {
      if (matches(id)) targets.insert(region);
    }
    if (targets.empty()) {
      for (const auto& [name, e] : regions_) targets.insert(name);
    }
    for (const auto& t : targets) {
      auto rit = regions_.find(t);
      if (rit != regions_.end()) rit->second.pending_evicts.push_back(prefix);
    }
  }
  for (auto it = state_.heartbeats.begin(); it != state_.heartbeats.end();) {
    if (matches(it->first)) {
      evicted_[it->first] = now;  // tombstone: no zombie re-registration
      it = state_.heartbeats.erase(it);
      ++dropped;
    } else {
      ++it;
    }
  }
  for (auto it = state_.participants.begin(); it != state_.participants.end();) {
    if (matches(it->first)) {
      evicted_[it->first] = now;
      it = state_.participants.erase(it);
    } else {
      ++it;
    }
  }
  for (auto it = last_fresh_.begin(); it != last_fresh_.end();) {
    if (matches(it->first)) {
      it = last_fresh_.erase(it);
    } else {
      ++it;
    }
  }
  auto erase_matching = [&](auto& m) {
    for (auto it = m.begin(); it != m.end();) {
      if (matches(it->first)) {
        it = m.erase(it);
      } else {
        ++it;
      }
    }
  };
  erase_matching(hb_step_);
  erase_matching(hb_state_);
  erase_matching(last_commit_ms_);
  erase_matching(allreduce_gbps_);
  erase_matching(ec_shards_);
  erase_matching(region_of_);
  // Evicted incarnations bank their ledger counters first (see
  // SweepLocked) — the work they accounted happened.  Not undoable: the
  // tombstone guarantees this id can never heartbeat again.
  for (auto it = ledger_.begin(); it != ledger_.end();) {
    if (matches(it->first)) {
      BankLedgerLocked(it->first, /*undoable=*/false);
      it = ledger_.erase(it);
    } else {
      ++it;
    }
  }
  erase_matching(health_);
  // An evicted incarnation's straggler alert resolves with it (the
  // supervisor already replaced the process; the alert described a corpse).
  for (auto& a : alerts_) {
    if (a.resolved_ms == 0 && matches(a.replica_id)) a.resolved_ms = NowEpochMs();
  }
  // Wake blocked quorum handlers: an evicted id's own handler must notice
  // its tombstone and abort instead of waiting out its deadline.
  quorum_cv_.notify_all();
  if (dropped > 0) {
    LOGI("lighthouse: evicted %d replica id(s) matching '%s' (supervisor "
         "reported dead)", dropped, prefix.c_str());
    flight_.RecordEvent(kFlightReplicaEvict,
                        "prefix=" + prefix +
                            " dropped=" + std::to_string(dropped));
    // A supervisor-reported death is the OTHER kill signature (scripted
    // kills evict before the heartbeat ever goes stale): trigger incident
    // auto-capture just like SweepLocked's stale transition.
    RecordIncidentLocked("replica_evicted", prefix,
                         static_cast<double>(dropped));
    TickLocked();  // a waiting quorum can now form without the straggler wait
  }
  return dropped;
}

int Lighthouse::DrainReplica(const std::string& prefix, int64_t deadline_ms) {
  std::lock_guard<std::mutex> lk(mu_);
  return DrainLocked(prefix, deadline_ms);
}

int Lighthouse::DrainLocked(const std::string& prefix, int64_t deadline_ms) {
  // Unlike EvictReplica, the heartbeat entries stay: the departing process
  // is ALIVE and finishing its step — the dashboard should keep showing it
  // (as draining) until it actually exits.  Exclusion from quorum comes
  // from QuorumCompute skipping draining ids entirely.  Ids are collected
  // from everything the lighthouse currently knows: heartbeats, pending
  // joins, and the previous quorum's membership (a member between rounds
  // has neither a heartbeat-map-only presence nor a pending join).
  auto matches = [&](const std::string& id) {
    return id == prefix || id.rfind(prefix + ":", 0) == 0;
  };
  std::set<std::string> ids;
  for (const auto& [id, _] : state_.heartbeats) {
    if (matches(id)) ids.insert(id);
  }
  for (const auto& [id, _] : state_.participants) {
    if (matches(id)) ids.insert(id);
  }
  if (state_.prev_quorum) {
    for (const auto& m : state_.prev_quorum->participants()) {
      if (matches(m.replica_id())) ids.insert(m.replica_id());
    }
  }
  // Federation root: drains propagate down the digest path like evictions
  // (the child's QuorumCompute is what must skip the draining members).
  if (!regions_.empty()) {
    std::set<std::string> targets;
    for (const auto& [id, region] : region_of_) {
      if (matches(id)) targets.insert(region);
    }
    if (targets.empty()) {
      for (const auto& [name, e] : regions_) targets.insert(name);
    }
    for (const auto& t : targets) {
      auto rit = regions_.find(t);
      if (rit == regions_.end()) continue;
      rit->second.pending_drains.push_back(prefix);
      if (deadline_ms > 0) rit->second.pending_drain_deadline_ms = deadline_ms;
    }
  }
  auto now = Clock::now();
  int marked = 0;
  for (const auto& id : ids) {
    if (state_.draining.emplace(id, now).second) ++marked;
    if (deadline_ms > 0) drain_deadline_ms_[id] = NowEpochMs() + deadline_ms;
  }
  // Wake blocked joins: a draining id's own pending handler must abort
  // (it will never be included again), and waiting survivors can form
  // their next quorum without the straggler wait right now.
  quorum_cv_.notify_all();
  if (marked > 0) {
    LOGI("lighthouse: draining %d replica id(s) matching '%s' (cooperative "
         "departure%s)", marked, prefix.c_str(),
         deadline_ms > 0
             ? (", deadline " + std::to_string(deadline_ms) + " ms").c_str()
             : "");
    flight_.RecordEvent(kFlightReplicaDrain,
                        "prefix=" + prefix + " marked=" +
                            std::to_string(marked) + " deadline_ms=" +
                            std::to_string(deadline_ms));
    TickLocked();
  }
  return marked;
}

bool Lighthouse::KillReplica(const std::string& replica_id, std::string* err) {
  std::string address;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (state_.prev_quorum) {
      for (const auto& m : state_.prev_quorum->participants()) {
        if (m.replica_id() == replica_id) address = m.address();
      }
    }
    for (const auto& [id, j] : state_.participants) {
      if (id == replica_id) address = j.member.address();
    }
  }
  if (address.empty()) {
    if (err) *err = "unknown replica " + replica_id;
    return false;
  }
  RpcClient client(address);
  KillRequest kreq;
  kreq.set_msg("killed from lighthouse dashboard");
  std::string payload, resp;
  kreq.SerializeToString(&payload);
  // The manager exits inside the handler, so the connection usually drops
  // before a response arrives; any outcome but a clean error is success.
  client.Call(kManagerKill, payload, 5000, &resp, err);
  return true;
}

namespace {
// Prometheus label-value escaping.  NOT the shared JsonEscape: the text
// exposition format defines exactly \\, \" and \n — JSON's \r/\t/\uXXXX
// escapes are undefined there and corrupt the series for parsers that
// take them literally.
std::string PromEscape(const std::string& s) {
  std::string out;
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      default: out += c;
    }
  }
  return out;
}
}  // namespace

std::string Lighthouse::MetricsText() {
  // Scale discipline: everything below is SNAPSHOT under mu_ into plain
  // vectors, then rendered AFTER the lock is released.  The render is the
  // expensive part (an ostringstream building ~10 series x N replicas of
  // formatted text), and holding the global mutex through it coupled
  // scrape cost directly into heartbeat/quorum handling latency — at
  // O(100) replicas x a 1 s scrape cadence that contention was the
  // dominant self-cost the scale sweep measures.  The histograms carry
  // their own locks and are read outside mu_ as well.
  struct Snap {
    int role = 0;
    int64_t leader_epoch = 0;
    int64_t quorum_size = 0;
    int64_t quorum_id = 0;
    double quorum_age_s = -1;
    int64_t healthy = 0, pending = 0, draining = 0, tombstoned = 0;
    int64_t healing = 0, donor_pool = 0, max_step = 0;
    int64_t stragglers = 0, alerts_active = 0;
    int64_t links_degraded = 0;
    std::vector<std::pair<std::string, int64_t>> steps;
    std::vector<std::pair<std::string, double>> hb_age_s;
    std::vector<std::pair<std::string, double>> commit_age_s;
    std::vector<std::pair<std::string, double>> step_time_s;
    std::vector<std::pair<std::string, double>> gbps;
    std::vector<std::pair<std::string, double>> ratio;
    std::vector<std::pair<std::string, int64_t>> sentinel_state;
    std::vector<std::pair<std::string, int64_t>> ec_held;
    int64_t ec_step = 0, ec_coverage = 0;
    std::vector<std::pair<std::string, double>> link_recv_gbps;
    std::vector<std::pair<std::string, double>> link_send_gbps;
    std::vector<std::pair<std::string, double>> link_rtt_ms;
    std::vector<std::pair<std::string, double>> link_ratio;
    std::vector<std::pair<std::string, int64_t>> link_state;
    // Goodput ledger (docs/wire.md "Goodput ledger").
    double ledger_compute = 0.0;
    double ledger_lost[kLedgerCauseCount] = {0};
    std::vector<std::pair<std::string, double>> goodput_ratio;
    double goodput_ewma = -1.0;
    int64_t incidents = 0;
    // SLO engine (docs/observability.md "SLO engine").
    double slo_target = 0.0;
    double slo_burn_fast = 0.0, slo_burn_slow = 0.0;
    double slo_budget_remaining = 1.0;
    double fleet_goodput = -1.0;
    // Federation (docs/wire.md "Federation").
    int fed_role = 0;  // 0 flat, 1 regional child, 2 root
    int64_t fed_digests = 0, fed_rejected = 0;
    struct RegionRow {
      std::string name;
      int64_t total = 0, fresh = 0, epoch = 0, seq = 0, alerts = 0;
      double age_s = 0.0, compute_s = 0.0, lost_s = 0.0, goodput = 0.0;
      bool stale = false;
    };
    std::vector<RegionRow> regions;
  } s;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto now = Clock::now();
    auto hb_timeout = std::chrono::milliseconds(opt_.heartbeat_timeout_ms);
    s.role = IsLeaderLocked() ? 1 : 0;
    s.leader_epoch = leader_epoch_;
    s.quorum_size = state_.prev_quorum ? state_.prev_quorum->participants_size() : 0;
    s.quorum_id = state_.quorum_id;
    if (state_.prev_quorum) {
      s.quorum_age_s = (NowEpochMs() - state_.prev_quorum->created_ms()) / 1000.0;
    }
    s.pending = state_.participants.size();
    s.draining = state_.draining.size();
    s.tombstoned = evicted_.size();
    for (const auto& [id, st] : hb_state_) {
      if (st == "heal") ++s.healing;
    }
    for (const auto& [id, last] : state_.heartbeats) {
      if (!state_.draining.count(id) && now - last < hb_timeout) ++s.healthy;
    }
    // Healthy replicas at the max live step = the donor pool striped
    // healing can draw on; recovery bandwidth scales with this count, so
    // it is the capacity gauge to alert on (donor_pool == 1 means heals
    // are pinned to a single donor link again).  The reference step is the
    // max over ELIGIBLE replicas only — a draining or heartbeat-stale
    // replica that reported a higher step cannot serve, and counting
    // against its step would read donor_pool=0 (a false capacity alarm)
    // during exactly the departure scenarios the gauge exists to monitor.
    int64_t max_eligible_step = -1;
    auto eligible = [&](const std::string& id) {
      auto hb = state_.heartbeats.find(id);
      return hb != state_.heartbeats.end() && !state_.draining.count(id) &&
             now - hb->second < hb_timeout;
    };
    for (const auto& [id, step] : hb_step_) {
      s.max_step = std::max(s.max_step, step);
      if (eligible(id)) max_eligible_step = std::max(max_eligible_step, step);
    }
    s.steps.reserve(hb_step_.size());
    for (const auto& [id, step] : hb_step_) {
      s.steps.emplace_back(id, step);
      if (eligible(id) && step == max_eligible_step) ++s.donor_pool;
    }
    s.hb_age_s.reserve(state_.heartbeats.size());
    for (const auto& [id, last] : state_.heartbeats) {
      s.hb_age_s.emplace_back(
          id, std::chrono::duration_cast<std::chrono::milliseconds>(now - last)
                      .count() /
                  1000.0);
    }
    int64_t epoch_now = NowEpochMs();
    s.commit_age_s.reserve(last_commit_ms_.size());
    for (const auto& [id, ms] : last_commit_ms_) {
      s.commit_age_s.emplace_back(id, (epoch_now - ms) / 1000.0);
    }
    s.step_time_s.reserve(health_.size());
    s.sentinel_state.reserve(health_.size());
    for (const auto& [id, h] : health_) {
      if (h.state == 2) ++s.stragglers;
      s.step_time_s.emplace_back(id, h.ewma_ms / 1000.0);
      s.sentinel_state.emplace_back(id, h.state);
      if (h.ratio > 0.0) s.ratio.emplace_back(id, h.ratio);
    }
    s.gbps.reserve(allreduce_gbps_.size());
    for (const auto& [id, g] : allreduce_gbps_) s.gbps.emplace_back(id, g);
    // Per-step shard coverage: shards held at the NEWEST reported encode
    // generation, summed over the replicas reporting that generation —
    // the redundancy a donor-free reconstruction at the max step can
    // actually draw on (needs >= k to succeed; alert below k + 1).
    s.ec_held.reserve(ec_shards_.size());
    auto hb_fresh = [&](const std::string& id) {
      return HeartbeatFreshLocked(id, now);
    };
    for (const auto& [id, sc] : ec_shards_) {
      s.ec_held.emplace_back(id, sc.second);
      // Coverage counts heartbeat-FRESH holders only (a dead holder's
      // inventory lingers until the graveyard prune but its shards are
      // unreachable) — the same rule the ec_coverage alert pages on, so
      // gauge and alert cannot disagree.
      if (hb_fresh(id)) s.ec_step = std::max(s.ec_step, sc.first);
    }
    for (const auto& [id, sc] : ec_shards_) {
      if (hb_fresh(id) && sc.first == s.ec_step) s.ec_coverage += sc.second;
    }
    for (const auto& a : alerts_) {
      if (a.resolved_ms == 0) ++s.alerts_active;
    }
    // Slow-link sentinel (docs/wire.md "Slow-link sentinel").
    s.link_recv_gbps.reserve(link_health_.size());
    for (const auto& [id, lh] : link_health_) {
      if (lh.state == 2) ++s.links_degraded;
      s.link_recv_gbps.emplace_back(id, lh.recv_gbps);
      s.link_send_gbps.emplace_back(id, lh.send_gbps);
      s.link_rtt_ms.emplace_back(id, lh.rtt_ms);
      s.link_state.emplace_back(id, lh.state);
      if (lh.ratio > 0.0) s.link_ratio.emplace_back(id, lh.ratio);
    }
    // Goodput ledger: cluster totals (bank + live) and per-replica ratios.
    ClusterLedgerLocked(&s.ledger_compute, s.ledger_lost);
    s.goodput_ratio.reserve(ledger_.size());
    for (const auto& [id, rl] : ledger_) {
      s.goodput_ratio.emplace_back(id, rl.goodput_ratio);
    }
    s.goodput_ewma = goodput_ewma_;
    s.incidents = incident_seq_;
    // SLO engine: target + live burn rates + cumulative budget remainder.
    s.slo_target = slo_target_;
    s.slo_burn_fast = slo_burn_fast_;
    s.slo_burn_slow = slo_burn_slow_;
    if (slo_target_ > 0.0) {
      double lt = 0.0;
      for (size_t i = 0; i < kLedgerCauseCount; ++i) lt += s.ledger_lost[i];
      double acc = s.ledger_compute + lt;
      if (acc > 0.0) {
        s.slo_budget_remaining = 1.0 - (lt / acc) / (1.0 - slo_target_);
      }
    }
    // Fleet goodput: digest-fed region rollups only (the root's O(R)
    // fleet view; -1 on flat/child instances with no regions).
    {
      double fc = 0.0, fl = 0.0;
      for (const auto& [name, e] : regions_) {
        fc += e.compute_s;
        for (size_t i = 0; i < kLedgerCauseCount; ++i) fl += e.lost_s[i];
      }
      if (fc + fl > 0.0) s.fleet_goodput = fc / (fc + fl);
    }
    // Federation: a root is whoever has accepted digests; a child counts
    // its own accepted pushes (roots keep fed_pushes_ok_ at 0, children
    // keep regions_ empty, so the sum below is whichever applies).
    s.fed_role = !regions_.empty() ? 2 : (fed_child_ ? 1 : 0);
    s.fed_digests = fed_pushes_ok_;
    s.fed_rejected = fed_pushes_rejected_;
    s.regions.reserve(regions_.size());
    for (const auto& [name, e] : regions_) {
      Snap::RegionRow row;
      row.name = name;
      row.total = e.replicas_total;
      row.fresh = e.replicas_fresh;
      row.epoch = e.child_epoch;
      row.seq = e.seq;
      row.alerts = e.alerts_active;
      row.age_s = std::chrono::duration<double>(now - e.last_push).count();
      row.compute_s = e.compute_s;
      for (size_t i = 0; i < kLedgerCauseCount; ++i) row.lost_s += e.lost_s[i];
      row.goodput = e.goodput_ratio;
      row.stale = e.stale;
      s.fed_digests += e.digests;
      s.regions.push_back(std::move(row));
    }
  }

  std::ostringstream o;
  auto gauge = [&](const char* name, const char* help) {
    o << "# HELP " << name << " " << help << "\n# TYPE " << name << " gauge\n";
  };
  // HA role first: scraped per instance (never redirected), this is the
  // gauge an operator alerts on — sum(tpuft_lighthouse_role) over the
  // replica set must be exactly 1.
  gauge("tpuft_lighthouse_role",
        "this lighthouse's role: 1 leader (live lease), 0 follower");
  o << "tpuft_lighthouse_role " << s.role << "\n";
  gauge("tpuft_lighthouse_leader_epoch",
        "lease epoch of the current leadership (bumps on every takeover)");
  o << "tpuft_lighthouse_leader_epoch " << s.leader_epoch << "\n";
  gauge("tpuft_quorum_size", "participants in the current quorum");
  o << "tpuft_quorum_size " << s.quorum_size << "\n";
  gauge("tpuft_quorum_id", "monotonically increasing quorum id (bumps on membership change)");
  o << "tpuft_quorum_id " << s.quorum_id << "\n";
  gauge("tpuft_quorum_age_seconds", "seconds since the current quorum formed");
  o << "tpuft_quorum_age_seconds " << s.quorum_age_s << "\n";
  gauge("tpuft_replicas_healthy", "replicas with a fresh heartbeat (draining excluded)");
  o << "tpuft_replicas_healthy " << s.healthy << "\n";
  gauge("tpuft_pending_joins", "replicas blocked in a quorum join this round");
  o << "tpuft_pending_joins " << s.pending << "\n";
  gauge("tpuft_replicas_draining", "replicas marked for cooperative departure");
  o << "tpuft_replicas_draining " << s.draining << "\n";
  gauge("tpuft_replicas_tombstoned", "evicted incarnations still tombstoned against zombies");
  o << "tpuft_replicas_tombstoned " << s.tombstoned << "\n";
  gauge("tpuft_heal_in_progress", "replicas currently fetching weights from a peer");
  o << "tpuft_heal_in_progress " << s.healing << "\n";
  gauge("tpuft_donor_pool",
        "healthy replicas at the max live step (striped-heal donor capacity)");
  o << "tpuft_donor_pool " << s.donor_pool << "\n";

  gauge("tpuft_replica_step", "live training step per replica (from heartbeats)");
  for (const auto& [id, step] : s.steps) {
    o << "tpuft_replica_step{replica=\"" << PromEscape(id) << "\"} " << step << "\n";
  }
  gauge("tpuft_replica_step_lag", "steps behind the most advanced replica");
  for (const auto& [id, step] : s.steps) {
    o << "tpuft_replica_step_lag{replica=\"" << PromEscape(id) << "\"} "
      << (s.max_step - step) << "\n";
  }
  gauge("tpuft_replica_heartbeat_age_seconds", "seconds since the last heartbeat");
  for (const auto& [id, age] : s.hb_age_s) {
    o << "tpuft_replica_heartbeat_age_seconds{replica=\"" << PromEscape(id)
      << "\"} " << age << "\n";
  }
  gauge("tpuft_replica_last_commit_age_seconds",
        "seconds since the replica's reported step last advanced");
  for (const auto& [id, age] : s.commit_age_s) {
    o << "tpuft_replica_last_commit_age_seconds{replica=\"" << PromEscape(id)
      << "\"} " << age << "\n";
  }

  // Straggler sentinel (docs/wire.md "Straggler sentinel").
  gauge("tpuft_replica_step_time_seconds",
        "rolling per-step busy-time EWMA reported on heartbeats");
  for (const auto& [id, v] : s.step_time_s) {
    o << "tpuft_replica_step_time_seconds{replica=\"" << PromEscape(id)
      << "\"} " << v << "\n";
  }
  gauge("tpuft_allreduce_gb_per_s",
        "per-replica allreduce payload GB/s (last committed step, from heartbeats)");
  for (const auto& [id, g] : s.gbps) {
    o << "tpuft_allreduce_gb_per_s{replica=\"" << PromEscape(id) << "\"} "
      << g << "\n";
  }

  // Erasure-coded peer state (docs/wire.md "Erasure shard endpoints").
  gauge("tpuft_ec_shards_held",
        "erasure shards held per replica at its newest encode generation");
  for (const auto& [id, n] : s.ec_held) {
    o << "tpuft_ec_shards_held{replica=\"" << PromEscape(id) << "\"} " << n
      << "\n";
  }
  gauge("tpuft_ec_shard_step",
        "newest erasure encode generation reported by any replica");
  o << "tpuft_ec_shard_step " << s.ec_step << "\n";
  gauge("tpuft_ec_shard_coverage",
        "shards held at the newest encode generation across replicas "
        "(donor-free reconstruction needs >= k of these reachable)");
  o << "tpuft_ec_shard_coverage " << s.ec_coverage << "\n";
  gauge("tpuft_replica_slowness_ratio",
        "replica step-time EWMA over the cluster median (1.0 = on pace)");
  for (const auto& [id, r] : s.ratio) {
    o << "tpuft_replica_slowness_ratio{replica=\"" << PromEscape(id)
      << "\"} " << r << "\n";
  }
  gauge("tpuft_straggler_state",
        "sentinel state per replica: 0 healthy, 1 suspect, 2 straggler");
  for (const auto& [id, st] : s.sentinel_state) {
    o << "tpuft_straggler_state{replica=\"" << PromEscape(id) << "\"} "
      << st << "\n";
  }
  gauge("tpuft_stragglers", "replicas currently in the straggler state");
  o << "tpuft_stragglers " << s.stragglers << "\n";

  // Slow-link sentinel (docs/wire.md "Slow-link sentinel"): per-replica
  // link health from heartbeat fields 11-13.  The replica label names the
  // REPORTER; its send gauge describes the outbound edge to its ring
  // successor, its recv gauge the inbound edge from its predecessor.
  gauge("tpuft_link_recv_gbps",
        "inbound ring-edge goodput EWMA per replica (payload GB/s per "
        "second of recv-wait, from heartbeats)");
  for (const auto& [id, v] : s.link_recv_gbps) {
    o << "tpuft_link_recv_gbps{replica=\"" << PromEscape(id) << "\"} " << v
      << "\n";
  }
  gauge("tpuft_link_send_gbps",
        "outbound ring-edge goodput EWMA per replica (payload GB/s per "
        "second of send-blocked time — the slow-link sentinel's signal)");
  for (const auto& [id, v] : s.link_send_gbps) {
    o << "tpuft_link_send_gbps{replica=\"" << PromEscape(id) << "\"} " << v
      << "\n";
  }
  gauge("tpuft_link_hop_rtt_ms", "mean per-hop recv-wait per replica, ms");
  for (const auto& [id, v] : s.link_rtt_ms) {
    o << "tpuft_link_hop_rtt_ms{replica=\"" << PromEscape(id) << "\"} " << v
      << "\n";
  }
  gauge("tpuft_link_slowness_ratio",
        "cluster median outbound goodput over the replica's (1.0 = on "
        "pace, >= TPUFT_LINK_RATIO = degraded candidate)");
  for (const auto& [id, v] : s.link_ratio) {
    o << "tpuft_link_slowness_ratio{replica=\"" << PromEscape(id) << "\"} "
      << v << "\n";
  }
  gauge("tpuft_link_state",
        "slow-link sentinel state per replica's outbound edge: 0 healthy, "
        "1 suspect, 2 degraded");
  for (const auto& [id, v] : s.link_state) {
    o << "tpuft_link_state{replica=\"" << PromEscape(id) << "\"} " << v
      << "\n";
  }
  gauge("tpuft_links_degraded", "replica outbound edges currently degraded");
  o << "tpuft_links_degraded " << s.links_degraded << "\n";
  gauge("tpuft_alerts_active", "unresolved sentinel alerts (see /alerts.json)");
  o << "tpuft_alerts_active " << s.alerts_active << "\n";

  // Goodput ledger (docs/wire.md "Goodput ledger"): cause-attributed
  // cluster accounting from heartbeat fields 14-16; /goodput.json carries
  // the per-replica breakdown.
  {
    double lost_total = 0.0;
    for (size_t i = 0; i < kLedgerCauseCount; ++i) lost_total += s.ledger_lost[i];
    double accounted = s.ledger_compute + lost_total;
    gauge("tpuft_goodput_ratio",
          "cluster productive fraction: compute seconds over accounted wall "
          "(bank + live incarnations; -1 before the first ledger report)");
    o << "tpuft_goodput_ratio "
      << (accounted > 0.0 ? s.ledger_compute / accounted : -1.0) << "\n";
    gauge("tpuft_replica_goodput_ratio",
          "per-replica cumulative productive fraction (heartbeat field 14)");
    for (const auto& [id, v] : s.goodput_ratio) {
      o << "tpuft_replica_goodput_ratio{replica=\"" << PromEscape(id)
        << "\"} " << v << "\n";
    }
    o << "# HELP tpuft_compute_seconds_total cluster productive seconds "
         "(goodput ledger; monotonic — departed incarnations are banked)\n"
         "# TYPE tpuft_compute_seconds_total counter\n";
    o << "tpuft_compute_seconds_total " << s.ledger_compute << "\n";
    o << "# HELP tpuft_lost_seconds_total cluster lost seconds per cause "
         "(goodput ledger's pinned taxonomy; monotonic)\n"
         "# TYPE tpuft_lost_seconds_total counter\n";
    for (size_t i = 0; i < kLedgerCauseCount; ++i) {
      o << "tpuft_lost_seconds_total{cause=\"" << kLedgerCauses[i] << "\"} "
        << s.ledger_lost[i] << "\n";
    }
    gauge("tpuft_goodput_ewma",
          "windowed cluster-goodput EWMA (the incident floor reference; -1 "
          "before the first observation)");
    o << "tpuft_goodput_ewma " << s.goodput_ewma << "\n";
    o << "# HELP tpuft_incidents_total incident-capture triggers recorded "
         "(see /incident.json)\n"
         "# TYPE tpuft_incidents_total counter\n";
    o << "tpuft_incidents_total " << s.incidents << "\n";
  }

  // SLO engine (docs/observability.md "SLO engine"): goodput SLO target +
  // multi-window burn rates.  Families are always declared; target reads 0
  // and burns read 0 while TPUFT_SLO_TARGET is unset, so dashboards need
  // no conditional queries.
  gauge("tpuft_slo_target",
        "configured goodput SLO target (TPUFT_SLO_TARGET; 0 = engine off)");
  o << "tpuft_slo_target " << s.slo_target << "\n";
  gauge("tpuft_slo_burn_rate_fast",
        "error-budget burn rate over the fast window (1.0 = burning exactly "
        "at the sustainable rate)");
  o << "tpuft_slo_burn_rate_fast " << s.slo_burn_fast << "\n";
  gauge("tpuft_slo_burn_rate_slow",
        "error-budget burn rate over the slow window");
  o << "tpuft_slo_burn_rate_slow " << s.slo_burn_slow << "\n";
  gauge("tpuft_slo_error_budget_remaining",
        "cumulative error budget remaining (1 = untouched, 0 = consumed, "
        "negative = SLO violated; 1 while the engine is off)");
  o << "tpuft_slo_error_budget_remaining " << s.slo_budget_remaining << "\n";

  // Federation (docs/wire.md "Federation"): per-instance role + push
  // counters, plus the root's per-region rollup (one series set per region
  // — region count is O(10), so the scrape stays bounded by REGION SIZE,
  // never global N; flat instances expose role 0 and empty region series).
  gauge("tpuft_federation_role",
        "federation role of this instance: 0 flat, 1 regional child, 2 root");
  o << "tpuft_federation_role " << s.fed_role << "\n";
  o << "# HELP tpuft_federation_digests_total region digest pushes accepted "
       "(child: accepted by the root; root: accepted from every region)\n"
       "# TYPE tpuft_federation_digests_total counter\n";
  o << "tpuft_federation_digests_total " << s.fed_digests << "\n";
  o << "# HELP tpuft_federation_digests_rejected_total digest pushes fenced "
       "or failed (stale child epoch, root unreachable)\n"
       "# TYPE tpuft_federation_digests_rejected_total counter\n";
  o << "tpuft_federation_digests_rejected_total " << s.fed_rejected << "\n";
  gauge("tpuft_regions", "regions known to this root (ever pushed a digest)");
  o << "tpuft_regions " << s.regions.size() << "\n";
  gauge("tpuft_fleet_goodput_ratio",
        "fleet productive fraction over every region's digest-fed ledger "
        "rollup (root tier; -1 when no region has pushed)");
  o << "tpuft_fleet_goodput_ratio " << s.fleet_goodput << "\n";
  gauge("tpuft_region_replicas",
        "replicas reported by the region's last digest");
  for (const auto& r : s.regions) {
    o << "tpuft_region_replicas{region=\"" << PromEscape(r.name) << "\"} "
      << r.total << "\n";
  }
  gauge("tpuft_region_replicas_fresh",
        "heartbeat-fresh replicas in the region's last digest");
  for (const auto& r : s.regions) {
    o << "tpuft_region_replicas_fresh{region=\"" << PromEscape(r.name)
      << "\"} " << r.fresh << "\n";
  }
  gauge("tpuft_region_digest_age_seconds",
        "seconds since the region's last accepted digest push");
  for (const auto& r : s.regions) {
    o << "tpuft_region_digest_age_seconds{region=\"" << PromEscape(r.name)
      << "\"} " << r.age_s << "\n";
  }
  gauge("tpuft_region_epoch",
        "child lease epoch of the region's last accepted digest (the "
        "per-region fencing token)");
  for (const auto& r : s.regions) {
    o << "tpuft_region_epoch{region=\"" << PromEscape(r.name) << "\"} "
      << r.epoch << "\n";
  }
  gauge("tpuft_region_stale",
        "1 when the region's digest pushes stopped for a heartbeat timeout "
        "(the cross-region kill signature)");
  for (const auto& r : s.regions) {
    o << "tpuft_region_stale{region=\"" << PromEscape(r.name) << "\"} "
      << (r.stale ? 1 : 0) << "\n";
  }
  gauge("tpuft_region_goodput_ratio",
        "region cumulative productive fraction from its ledger rollup");
  for (const auto& r : s.regions) {
    o << "tpuft_region_goodput_ratio{region=\"" << PromEscape(r.name)
      << "\"} " << r.goodput << "\n";
  }
  gauge("tpuft_region_alerts_active",
        "unresolved sentinel alerts inside the region (child-owned)");
  for (const auto& r : s.regions) {
    o << "tpuft_region_alerts_active{region=\"" << PromEscape(r.name)
      << "\"} " << r.alerts << "\n";
  }
  o << "# HELP tpuft_region_compute_seconds_total region productive seconds "
       "(goodput-ledger rollup from the region's digests; monotonic)\n"
       "# TYPE tpuft_region_compute_seconds_total counter\n";
  for (const auto& r : s.regions) {
    o << "tpuft_region_compute_seconds_total{region=\"" << PromEscape(r.name)
      << "\"} " << r.compute_s << "\n";
  }
  o << "# HELP tpuft_region_lost_seconds_total region lost seconds summed "
       "over the ledger's cause taxonomy (monotonic)\n"
       "# TYPE tpuft_region_lost_seconds_total counter\n";
  for (const auto& r : s.regions) {
    o << "tpuft_region_lost_seconds_total{region=\"" << PromEscape(r.name)
      << "\"} " << r.lost_s << "\n";
  }

  // Control-plane latency distributions (docs/wire.md "Latency
  // histograms") — the measurements ROADMAP item 2's scale sweep needs
  // before quorum/heartbeat/scrape paths can be optimized.
  ExposeHistogram(
      o, "tpuft_quorum_formation_seconds",
      "round first-joiner to quorum formation (server-side)",
      {{"", &quorum_formation_hist_}});
  std::vector<std::pair<std::string, const LatencyHistogram*>> rpc_series;
  for (const auto& [m, hist] : rpc_hist_) {
    rpc_series.emplace_back("method=\"" + MethodName(m) + "\"", &hist);
  }
  ExposeHistogram(
      o, "tpuft_rpc_latency_seconds",
      "server-side RPC handling latency per wire method (recv->send; "
      "includes blocking waits, so Quorum spans cover the formation wait)",
      rpc_series);
  ExposeHistogram(
      o, "tpuft_heartbeat_fanin_seconds",
      "summed heartbeat handling time per quorum tick (fan-in cost)",
      {{"", &heartbeat_fanin_hist_}});
  ExposeHistogram(
      o, "tpuft_metrics_scrape_seconds",
      "self-observed /metrics render duration (visible from the scrape "
      "after the one it measured)",
      {{"", &scrape_hist_}});
  return o.str();
}

int Lighthouse::StragglerState(const std::string& replica_id) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = health_.find(replica_id);
  return it == health_.end() ? 0 : it->second.state;
}

int Lighthouse::LinkState(const std::string& replica_id) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = link_health_.find(replica_id);
  return it == link_health_.end() ? 0 : it->second.state;
}

std::string Lighthouse::AlertsJson() {
  std::lock_guard<std::mutex> lk(mu_);
  std::ostringstream o;
  int64_t active = 0;
  for (const auto& a : alerts_) {
    if (a.resolved_ms == 0) ++active;
  }
  o << "{\"active\":" << active << ",\"alerts\":[";
  bool first = true;
  for (const auto& a : alerts_) {
    if (!first) o << ",";
    first = false;
    o << "{\"id\":" << a.id << ",\"kind\":\"" << JsonEscape(a.kind)
      << "\",\"replica_id\":\"" << JsonEscape(a.replica_id)
      << "\",\"raised_ms\":" << a.raised_ms
      << ",\"resolved_ms\":" << a.resolved_ms
      << ",\"ratio\":" << a.ratio
      << ",\"step_time_ms\":" << a.step_time_ms
      << ",\"auto_drained\":" << (a.auto_drained ? "true" : "false")
      << ",\"coverage\":" << a.coverage
      << ",\"threshold\":" << a.threshold
      << ",\"gbps\":" << a.gbps
      << ",\"src_replica_id\":\"" << JsonEscape(a.src_replica_id)
      << "\",\"burn_fast\":" << a.burn_fast
      << ",\"burn_slow\":" << a.burn_slow
      << ",\"dominant_cause\":\"" << JsonEscape(a.dominant_cause)
      << "\",\"charged_seconds\":" << a.charged_seconds
      << ",\"active\":" << (a.resolved_ms == 0 ? "true" : "false") << "}";
  }
  o << "]}";
  return o.str();
}

std::string Lighthouse::GoodputJson() {
  std::lock_guard<std::mutex> lk(mu_);
  double compute = 0.0, lost[kLedgerCauseCount];
  ClusterLedgerLocked(&compute, lost);
  double lost_total = 0.0;
  for (size_t i = 0; i < kLedgerCauseCount; ++i) lost_total += lost[i];
  double accounted = compute + lost_total;
  std::ostringstream o;
  auto causes_obj = [&](const double* v) {
    std::ostringstream c;
    c << "{";
    for (size_t i = 0; i < kLedgerCauseCount; ++i) {
      if (i) c << ",";
      c << "\"" << kLedgerCauses[i] << "\":" << v[i];
    }
    c << "}";
    return c.str();
  };
  o << "{\"goodput_ratio\":"
    << (accounted > 0.0 ? compute / accounted : -1.0)
    << ",\"goodput_ewma\":" << goodput_ewma_
    << ",\"compute_seconds\":" << compute
    << ",\"lost_seconds_total\":" << lost_total
    << ",\"lost_seconds\":" << causes_obj(lost)
    << ",\"banked_compute_seconds\":" << ledger_banked_compute_
    << ",\"incidents\":" << incident_seq_ << ",\"per_replica\":{";
  bool first = true;
  for (const auto& [id, rl] : ledger_) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\":{\"goodput_ratio\":" << rl.goodput_ratio
      << ",\"compute_seconds\":" << rl.compute_s
      << ",\"lost_seconds\":" << causes_obj(rl.lost_s) << "}";
  }
  o << "}";
  // Federation fleet rollup: the digest-fed region totals alone (distinct
  // from the cluster totals above, which also include this instance's own
  // members + bank).  Empty on a flat / child lighthouse.
  double fleet_compute = 0.0, fleet_lost = 0.0;
  for (const auto& [name, e] : regions_) {
    fleet_compute += e.compute_s;
    for (size_t i = 0; i < kLedgerCauseCount; ++i) fleet_lost += e.lost_s[i];
  }
  double fleet_acc = fleet_compute + fleet_lost;
  o << ",\"fleet\":{\"regions\":" << regions_.size()
    << ",\"goodput_ratio\":" << (fleet_acc > 0.0 ? fleet_compute / fleet_acc : -1.0)
    << ",\"compute_seconds\":" << fleet_compute
    << ",\"lost_seconds_total\":" << fleet_lost << ",\"per_region\":{";
  first = true;
  for (const auto& [name, e] : regions_) {
    double rl = 0.0;
    for (size_t i = 0; i < kLedgerCauseCount; ++i) rl += e.lost_s[i];
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(name) << "\":{\"goodput_ratio\":" << e.goodput_ratio
      << ",\"compute_seconds\":" << e.compute_s
      << ",\"lost_seconds_total\":" << rl
      << ",\"lost_seconds\":" << causes_obj(e.lost_s) << "}";
  }
  o << "}}}";
  return o.str();
}

std::string Lighthouse::SloJson() {
  std::lock_guard<std::mutex> lk(mu_);
  std::ostringstream o;
  if (slo_target_ <= 0.0) {
    o << "{\"enabled\":false}";
    return o.str();
  }
  double compute = 0.0, lost[kLedgerCauseCount];
  ClusterLedgerLocked(&compute, lost);
  double lost_total = 0.0;
  for (size_t i = 0; i < kLedgerCauseCount; ++i) lost_total += lost[i];
  double accounted = compute + lost_total;
  double budget = 1.0 - slo_target_;
  // Error budget remaining over the run to date: 1 at zero loss, 0 when
  // the cumulative lost fraction has consumed exactly (1 - target), and
  // negative once the SLO is violated outright.
  double budget_remaining =
      accounted > 0.0 ? 1.0 - (lost_total / accounted) / budget : 1.0;
  bool alert_active = false;
  for (const auto& a : alerts_) {
    if (a.kind == "slo_burn" && a.resolved_ms == 0) alert_active = true;
  }
  o << "{\"enabled\":true,\"target\":" << slo_target_
    << ",\"fast_window_s\":" << slo_fast_s_
    << ",\"slow_window_s\":" << slo_slow_s_
    << ",\"burn_rate_fast\":" << slo_burn_fast_
    << ",\"burn_rate_slow\":" << slo_burn_slow_
    << ",\"error_budget_remaining\":" << budget_remaining
    << ",\"goodput_ewma\":" << goodput_ewma_
    << ",\"windowed_goodput\":" << last_windowed_goodput_
    << ",\"alert_active\":" << (alert_active ? "true" : "false")
    << ",\"culprit\":{\"replica\":\"" << JsonEscape(last_attr_.replica)
    << "\",\"region\":\"" << JsonEscape(last_attr_.region)
    << "\",\"dominant_cause\":\"" << JsonEscape(last_attr_.cause)
    << "\",\"charged_seconds\":" << last_attr_.charged_s
    << ",\"delta_by_replica\":"
    << (last_attr_.delta_json.empty() ? "{}" : last_attr_.delta_json)
    << "},\"regions\":{";
  // Root tier: per-region cumulative burn over digest rollups — O(R), no
  // per-replica fan-in (the region's own child serves the windowed view).
  bool first = true;
  for (const auto& [name, e] : regions_) {
    double rl = 0.0;
    for (size_t i = 0; i < kLedgerCauseCount; ++i) rl += e.lost_s[i];
    double acc = e.compute_s + rl;
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(name) << "\":{\"goodput_ratio\":" << e.goodput_ratio
      << ",\"burn_rate\":" << (acc > 0.0 ? (rl / acc) / budget : 0.0) << "}";
  }
  o << "}}";
  return o.str();
}

std::string Lighthouse::IncidentJson() {
  std::lock_guard<std::mutex> lk(mu_);
  std::ostringstream o;
  o << "{\"count\":" << incident_seq_ << ",\"incidents\":[";
  bool first = true;
  for (const auto& rec : incidents_) {
    if (!first) o << ",";
    first = false;
    o << "{\"id\":" << rec.id << ",\"reason\":\"" << JsonEscape(rec.reason)
      << "\",\"replica_id\":\"" << JsonEscape(rec.replica_id)
      << "\",\"step\":" << rec.step << ",\"ts_ms\":" << rec.ts_ms
      << ",\"detail\":" << rec.detail
      << ",\"culprit_replica\":\"" << JsonEscape(rec.culprit_replica)
      << "\",\"culprit_region\":\"" << JsonEscape(rec.culprit_region)
      << "\",\"dominant_cause\":\"" << JsonEscape(rec.dominant_cause)
      << "\",\"charged_seconds\":" << rec.charged_seconds
      << ",\"delta_by_replica\":"
      << (rec.delta_by_replica_json.empty() ? "{}" : rec.delta_by_replica_json)
      << "}";
  }
  o << "]}";
  return o.str();
}

std::string Lighthouse::StatusJson() {
  LighthouseStatusResponse s;
  FillStatus(&s);
  std::string role;
  int64_t epoch;
  {
    std::lock_guard<std::mutex> lk(mu_);
    role = IsLeaderLocked() ? "leader" : "follower";
    epoch = leader_epoch_;
  }
  std::ostringstream o;
  o << "{\"role\":\"" << role << "\",\"leader_epoch\":" << epoch
    << ",\"quorum_id\":" << s.quorum_id() << ",\"participants\":[";
  bool first = true;
  for (const auto& m : s.prev_quorum().participants()) {
    if (!first) o << ",";
    first = false;
    o << "{\"replica_id\":\"" << JsonEscape(m.replica_id()) << "\",\"address\":\""
      << JsonEscape(m.address()) << "\",\"step\":" << m.step()
      << ",\"world_size\":" << m.world_size() << "}";
  }
  o << "],\"pending\":[";
  first = true;
  for (const auto& m : s.pending_participants()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(m.replica_id()) << "\"";
  }
  o << "],\"heartbeat_age_ms\":{";
  first = true;
  for (const auto& [id, age] : s.heartbeat_age_ms()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\":" << age;
  }
  o << "},\"draining\":[";
  first = true;
  for (const auto& id : s.draining()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\"";
  }
  // Live per-replica observability (heartbeat step/state fields): the
  // participants[].step above is the QUORUM-SNAPSHOT step; replica_step is
  // real-time, and last_commit_ts_ms is when it last advanced.
  o << "],\"replica_step\":{";
  first = true;
  for (const auto& [id, step] : s.replica_step()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\":" << step;
  }
  o << "},\"last_commit_ts_ms\":{";
  first = true;
  for (const auto& [id, ms] : s.last_commit_ts_ms()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\":" << ms;
  }
  o << "},\"replica_state\":{";
  first = true;
  for (const auto& [id, st] : s.replica_state()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\":\"" << JsonEscape(st) << "\"";
  }
  // Straggler sentinel: per-replica health state (0/1/2), rounded step-time
  // EWMA, and slowness ratio (permille scaled back to a float here).
  o << "},\"straggler_state\":{";
  first = true;
  for (const auto& [id, st] : s.straggler_state()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\":" << st;
  }
  o << "},\"replica_step_time_ms\":{";
  first = true;
  for (const auto& [id, ms] : s.replica_step_time_ms()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\":" << ms;
  }
  o << "},\"replica_slowness\":{";
  first = true;
  for (const auto& [id, pm] : s.replica_slowness_permille()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\":" << pm / 1000.0;
  }
  o << "}}";
  return o.str();
}

std::string Lighthouse::StatusHtml() {
  LighthouseStatusResponse s;
  FillStatus(&s);
  int64_t max_step = 0;
  for (const auto& m : s.prev_quorum().participants()) max_step = std::max(max_step, m.step());
  std::ostringstream o;
  o << "<!DOCTYPE html><html><head><title>tpu-ft lighthouse</title>"
       "<meta http-equiv=\"refresh\" content=\"1\">"
       "<style>body{font-family:monospace;background:#111;color:#eee;margin:2em}"
       ".card{border:1px solid #444;border-radius:6px;padding:1em;margin:.5em;display:inline-block;"
       "min-width:18em;vertical-align:top}"
       ".recovering{border-color:orange}.stale{color:#f66}"
       ".draining{border-color:#6af}"
       ".suspect{border-color:#fc6}.straggler{border-color:#f33}"
       ".slow{color:#fc6}.veryslow{color:#f33}"
       "button{background:#a33;color:#fff;border:0;padding:.3em .8em;border-radius:4px;"
       "cursor:pointer}</style></head><body>"
       "<h1>tpu-ft lighthouse</h1>";
  o << "<p>quorum_id: " << s.quorum_id() << " &mdash; " << s.prev_quorum().participants_size()
    << " participants, " << s.pending_participants_size() << " pending</p>";
  // Goodput-ledger card: cluster productive fraction + the dominant lost
  // cause (the full per-cause breakdown lives on /goodput.json).
  {
    double compute = 0.0, lost[kLedgerCauseCount];
    int64_t incidents = 0;
    {
      std::lock_guard<std::mutex> lk(mu_);
      ClusterLedgerLocked(&compute, lost);
      incidents = incident_seq_;
    }
    double lost_total = 0.0;
    size_t worst = 0;
    for (size_t i = 0; i < kLedgerCauseCount; ++i) {
      lost_total += lost[i];
      if (lost[i] > lost[worst]) worst = i;
    }
    double accounted = compute + lost_total;
    if (accounted > 0.0) {
      char buf[160];
      snprintf(buf, sizeof(buf),
               "<p>goodput: %.4f (lost %.1fs, top cause %s %.1fs; "
               "incidents %lld — <a href=\"/goodput.json\">/goodput.json</a>)"
               "</p>",
               compute / accounted, lost_total, kLedgerCauses[worst],
               lost[worst], static_cast<long long>(incidents));
      o << buf;
    }
  }
  std::set<std::string> draining(s.draining().begin(), s.draining().end());
  int64_t max_live = 0;
  for (const auto& [id, st] : s.replica_step()) max_live = std::max(max_live, st);
  for (const auto& m : s.prev_quorum().participants()) {
    bool recovering = m.step() != max_step;
    bool is_draining = draining.count(m.replica_id()) > 0;
    int64_t age = -1;
    auto it = s.heartbeat_age_ms().find(m.replica_id());
    if (it != s.heartbeat_age_ms().end()) age = it->second;
    // Live step/lag from heartbeats (the quorum-snapshot step can be a
    // whole round stale); lag > 0 is the step-lag alarm /metrics exposes.
    int64_t live = m.step();
    auto ls = s.replica_step().find(m.replica_id());
    if (ls != s.replica_step().end()) live = ls->second;
    int64_t lag = max_live - live;
    std::string state;
    auto st_it = s.replica_state().find(m.replica_id());
    if (st_it != s.replica_state().end()) state = st_it->second;
    // Sentinel badge: suspects amber, confirmed stragglers red — the
    // degraded-but-alive host no heartbeat timeout would ever flag.
    int64_t straggle = 0;
    auto sg_it = s.straggler_state().find(m.replica_id());
    if (sg_it != s.straggler_state().end()) straggle = sg_it->second;
    int64_t step_ms = 0;
    auto tm_it = s.replica_step_time_ms().find(m.replica_id());
    if (tm_it != s.replica_step_time_ms().end()) step_ms = tm_it->second;
    double slowness = 0.0;
    auto sl_it = s.replica_slowness_permille().find(m.replica_id());
    if (sl_it != s.replica_slowness_permille().end()) {
      slowness = sl_it->second / 1000.0;
    }
    const char* card_class = is_draining ? " draining"
                             : straggle == 2 ? " straggler"
                             : straggle == 1 ? " suspect"
                             : recovering    ? " recovering"
                                             : "";
    std::ostringstream pace;
    if (step_ms > 0) {
      pace << "<br><span class=\""
           << (straggle == 2 ? "veryslow" : straggle == 1 ? "slow" : "")
           << "\">step time: " << step_ms << " ms";
      if (slowness > 0.0) {
        char buf[32];
        snprintf(buf, sizeof(buf), " (%.2fx median)", slowness);
        pace << buf;
      }
      pace << (straggle == 2 ? " STRAGGLER" : straggle == 1 ? " suspect" : "")
           << "</span>";
    }
    o << "<div class=\"card" << card_class
      << "\"><b>" << m.replica_id() << "</b><br>step: " << live
      << " <span class=\"" << (lag > 0 ? "stale" : "") << "\">(lag " << lag << ")</span>"
      << (state.empty() ? "" : " [" + state + "]")
      << (is_draining ? " (draining)" : recovering ? " (recovering)" : "")
      << pace.str()
      << "<br>world_size: " << m.world_size() << "<br>manager: " << m.address()
      << "<br><span class=\"" << (age > 2500 ? "stale" : "") << "\">heartbeat: " << age
      << " ms ago</span><br><form method=\"post\" action=\"/replica/" << m.replica_id()
      << "/kill\"><button>Kill</button></form>"
      << "<form method=\"post\" action=\"/replica/" << m.replica_id()
      << "/drain\"><button style=\"background:#36a\">Drain</button></form></div>";
  }
  o << "</body></html>";
  return o.str();
}

}  // namespace tpuft
