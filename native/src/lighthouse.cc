#include "lighthouse.h"

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <set>
#include <sstream>

#include "http.h"
#include "log.h"

namespace tpuft {

int64_t NowEpochMs() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// Pure quorum math.  Reference parity: quorum_compute, src/lighthouse.rs:133-261.
// Semantics (in evaluation order):
//   0. draining replicas (cooperative departure announced) are invisible:
//      neither candidates nor counted healthy — the quorum forms without
//      them instantly instead of waiting out join/heartbeat timeouts;
//   1. only replicas with a fresh heartbeat are candidates;
//   2. if any candidate requests shrink_only, membership may not grow beyond
//      the previous quorum;
//   3. "fast quorum": if every member of the previous quorum has re-joined
//      and is healthy, form the quorum immediately (steady-state path);
//   4. otherwise require >= min_replicas, and a strict majority of all
//      currently-heartbeating replicas (split-brain guard);
//   5. wait join_timeout (measured from the round's first joiner) for healthy
//      stragglers that have not re-joined yet, unless all have joined.
// ---------------------------------------------------------------------------
std::optional<std::vector<QuorumMember>> QuorumCompute(TimePoint now, const QuorumState& state,
                                                       const LighthouseOpt& opt,
                                                       std::string* reason) {
  auto hb_timeout = std::chrono::milliseconds(opt.heartbeat_timeout_ms);

  std::set<std::string> healthy;
  for (const auto& [id, last] : state.heartbeats) {
    if (state.draining.count(id)) continue;
    if (now - last < hb_timeout) healthy.insert(id);
  }

  std::vector<QuorumMember> candidates;
  bool shrink_only = false;
  for (const auto& [id, j] : state.participants) {
    if (!healthy.count(id)) continue;
    candidates.push_back(j.member);
    if (j.member.shrink_only()) shrink_only = true;
  }

  std::set<std::string> prev_ids;
  if (state.prev_quorum) {
    for (const auto& m : state.prev_quorum->participants()) prev_ids.insert(m.replica_id());
  }

  if (shrink_only && state.prev_quorum) {
    std::vector<QuorumMember> shrunk;
    for (auto& m : candidates) {
      if (prev_ids.count(m.replica_id())) shrunk.push_back(m);
    }
    candidates = std::move(shrunk);
  }

  std::sort(candidates.begin(), candidates.end(),
            [](const QuorumMember& a, const QuorumMember& b) {
              return a.replica_id() < b.replica_id();
            });

  std::set<std::string> candidate_ids;
  for (const auto& m : candidates) candidate_ids.insert(m.replica_id());

  if (candidates.size() < opt.min_replicas) {
    if (reason) {
      *reason = "need at least " + std::to_string(opt.min_replicas) + " replicas, have " +
                std::to_string(candidates.size());
    }
    return std::nullopt;
  }

  // Fast quorum: every previous member is healthy and has re-joined.
  bool fast = state.prev_quorum && !prev_ids.empty() &&
              std::all_of(prev_ids.begin(), prev_ids.end(), [&](const std::string& id) {
                return candidate_ids.count(id) > 0;
              });
  if (fast) {
    if (reason) *reason = "fast quorum (all previous members present)";
    return candidates;
  }

  // Split-brain guard: require a strict majority of everything heartbeating.
  if (candidates.size() * 2 <= healthy.size()) {
    if (reason) {
      *reason = "potential split brain: only " + std::to_string(candidates.size()) + " of " +
                std::to_string(healthy.size()) + " healthy replicas joined";
    }
    return std::nullopt;
  }

  // All healthy replicas joined -> no reason to wait.
  bool all_joined = std::all_of(healthy.begin(), healthy.end(), [&](const std::string& id) {
    return state.participants.count(id) > 0 ||
           (shrink_only && !prev_ids.count(id));
  });
  if (all_joined) {
    if (reason) *reason = "quorum (all healthy replicas joined)";
    return candidates;
  }

  // Wait for stragglers up to join_timeout from the round's first joiner.
  TimePoint first_join = TimePoint::max();
  for (const auto& [id, j] : state.participants) {
    first_join = std::min(first_join, j.joined_at);
  }
  if (first_join != TimePoint::max() &&
      now - first_join >= std::chrono::milliseconds(opt.join_timeout_ms)) {
    if (reason) *reason = "quorum (join timeout elapsed, proceeding without stragglers)";
    return candidates;
  }
  if (reason) {
    *reason = "waiting for stragglers to join (" + std::to_string(candidates.size()) + "/" +
              std::to_string(healthy.size()) + " healthy joined)";
  }
  return std::nullopt;
}

// ---------------------------------------------------------------------------
// Lighthouse server
// ---------------------------------------------------------------------------

Lighthouse::Lighthouse(LighthouseOpt opt) : opt_(std::move(opt)) {}

Lighthouse::~Lighthouse() { Shutdown(); }

bool Lighthouse::AdminAllowed(const std::string& token, bool peer_loopback) const {
  if (!admin_token_.empty()) return token == admin_token_;
  return peer_loopback;
}

bool Lighthouse::Start(std::string* err) {
  if (const char* tok = std::getenv("TPUFT_ADMIN_TOKEN")) admin_token_ = tok;
  server_ = std::make_unique<RpcServer>(
      opt_.bind, [this](uint16_t method, const std::string& req, Deadline dl, std::string* resp) {
        return Dispatch(method, req, dl, resp);
      });
  if (!server_->Start(err)) return false;
  if (!opt_.http_bind.empty()) {
    http_ = std::make_unique<HttpServer>(
        opt_.http_bind,
        [this](const HttpRequestInfo& req) {
          const std::string& method = req.method;
          const std::string& path = req.path;
          HttpResponse r;
          bool is_mutation = method == "POST" && path.rfind("/replica/", 0) == 0;
          if (is_mutation && !AdminAllowed(req.token, req.peer_loopback)) {
            // Ops endpoints mutate cluster membership; see docs/wire.md
            // "Trust model" — remote callers must present the shared
            // secret when one is configured, and are refused outright
            // otherwise.
            r.code = 403;
            r.body = admin_token_.empty()
                         ? "forbidden: mutating endpoints are loopback-only "
                           "(set TPUFT_ADMIN_TOKEN to allow remote ops calls)"
                         : "forbidden: missing or wrong x-tpuft-token header";
            r.content_type = "text/plain";
            return r;
          }
          if (method == "GET" && (path == "/" || path == "/status")) {
            r.body = StatusHtml();
          } else if (method == "GET" && path == "/status.json") {
            r.content_type = "application/json";
            r.body = StatusJson();
          } else if (method == "GET" && path == "/metrics") {
            // Prometheus text exposition (read-only, ungated like
            // /status.json): cluster-level gauges a scraper can alert on.
            r.content_type = "text/plain; version=0.0.4; charset=utf-8";
            r.body = MetricsText();
          } else if (method == "POST" && path.rfind("/replica/", 0) == 0 &&
                     path.size() > 14 && path.substr(path.size() - 5) == "/kill") {
            std::string replica_id = path.substr(9, path.size() - 9 - 5);
            std::string kerr;
            if (KillReplica(replica_id, &kerr)) {
              r.body = "killed " + replica_id;
              r.content_type = "text/plain";
            } else {
              r.code = 500;
              r.body = kerr;
              r.content_type = "text/plain";
            }
          } else if (method == "POST" && path.rfind("/replica/", 0) == 0 &&
                     path.size() > 15 && path.substr(path.size() - 6) == "/evict") {
            std::string prefix = path.substr(9, path.size() - 9 - 6);
            int n = EvictReplica(prefix);
            r.body = "evicted " + std::to_string(n) + " id(s) for " + prefix;
            r.content_type = "text/plain";
          } else if (method == "POST" && path.rfind("/replica/", 0) == 0 &&
                     path.size() > 15 && path.substr(path.size() - 6) == "/drain") {
            std::string prefix = path.substr(9, path.size() - 9 - 6);
            int n = DrainReplica(prefix, 0);
            r.body = "draining " + std::to_string(n) + " id(s) for " + prefix;
            r.content_type = "text/plain";
          } else {
            r.code = 404;
            r.body = "not found";
            r.content_type = "text/plain";
          }
          return r;
        });
    if (!http_->Start(err)) return false;
  }
  tick_thread_ = std::thread([this] { TickLoop(); });
  LOGI("lighthouse listening on %s (dashboard %s)", server_->address().c_str(),
       http_ ? http_->address().c_str() : "disabled");
  return true;
}

void Lighthouse::Shutdown() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (shutdown_) return;
    shutdown_ = true;
    quorum_cv_.notify_all();
  }
  if (tick_thread_.joinable()) tick_thread_.join();
  if (server_) server_->Shutdown();
  if (http_) http_->Shutdown();
}

std::string Lighthouse::address() const { return server_ ? server_->address() : ""; }
std::string Lighthouse::http_address() const { return http_ ? http_->address() : ""; }

Status Lighthouse::Dispatch(uint16_t method, const std::string& req, Deadline dl,
                            std::string* resp) {
  switch (method) {
    case kLighthouseQuorum: {
      LighthouseQuorumRequest q;
      if (!q.ParseFromString(req)) return Status::kInvalidArgument;
      LighthouseQuorumResponse r;
      std::string err;
      Status st = HandleQuorum(q, dl, &r, &err);
      if (st != Status::kOk) {
        *resp = err;
        return st;
      }
      r.SerializeToString(resp);
      return Status::kOk;
    }
    case kLighthouseHeartbeat: {
      LighthouseHeartbeatRequest h;
      if (!h.ParseFromString(req)) return Status::kInvalidArgument;
      Status st = HandleHeartbeat(h);
      LighthouseHeartbeatResponse r;
      r.SerializeToString(resp);
      return st;
    }
    case kLighthouseStatus: {
      LighthouseStatusResponse r;
      FillStatus(&r);
      r.SerializeToString(resp);
      return Status::kOk;
    }
    case kLighthouseEvict: {
      LighthouseEvictRequest q;
      if (!q.ParseFromString(req)) return Status::kInvalidArgument;
      LighthouseEvictResponse r;
      r.set_evicted(EvictReplica(q.replica_prefix()));
      r.SerializeToString(resp);
      return Status::kOk;
    }
    case kLighthouseDrain: {
      LighthouseDrainRequest q;
      if (!q.ParseFromString(req)) return Status::kInvalidArgument;
      LighthouseDrainResponse r;
      r.set_drained(DrainReplica(q.replica_prefix(), q.deadline_ms()));
      r.SerializeToString(resp);
      return Status::kOk;
    }
    default:
      *resp = "unknown lighthouse method " + std::to_string(method);
      return Status::kUnknown;
  }
}

Status Lighthouse::HandleHeartbeat(const LighthouseHeartbeatRequest& req) {
  std::lock_guard<std::mutex> lk(mu_);
  if (evicted_.count(req.replica_id())) {
    return Status::kAborted;  // a zombie's in-flight heartbeat
  }
  state_.heartbeats[req.replica_id()] = Clock::now();
  // Live step/state (wire method 2 fields 2-3; 0/"" from pre-observability
  // peers).  A step ADVANCE is a commit: steps increment exactly when a
  // step commits (or a heal fast-forwards, which is progress too), so the
  // advance time is the lighthouse's last-commit timestamp for /metrics
  // and /status.json.
  auto it = hb_step_.find(req.replica_id());
  if (it == hb_step_.end() || req.step() > it->second) {
    if (it != hb_step_.end()) last_commit_ms_[req.replica_id()] = NowEpochMs();
    hb_step_[req.replica_id()] = req.step();
  }
  if (!req.state().empty()) hb_state_[req.replica_id()] = req.state();
  return Status::kOk;
}

Status Lighthouse::HandleQuorum(const LighthouseQuorumRequest& req, Deadline deadline,
                                LighthouseQuorumResponse* resp, std::string* err) {
  const std::string& id = req.requester().replica_id();
  if (id.empty()) {
    *err = "replica_id must be set";
    return Status::kInvalidArgument;
  }
  std::unique_lock<std::mutex> lk(mu_);
  if (evicted_.count(id)) {
    // The supervisor declared this exact incarnation dead; a late join
    // from it is a zombie (e.g. a request already in flight when the
    // process was reaped) and must not resurrect the corpse.
    *err = "replica " + id + " was evicted by its supervisor";
    return Status::kAborted;
  }
  if (state_.draining.count(id)) {
    // The incarnation announced a cooperative departure: it finishes its
    // in-flight step and exits — it must not start a NEW round.  (The
    // drain controller stops the train loop before the next quorum; this
    // guards the race where the notice lands mid-call.)
    *err = "replica " + id + " is draining; rejoin as a new incarnation";
    return Status::kAborted;
  }
  // Joining is an implicit heartbeat (reference: src/lighthouse.rs:480-491).
  state_.heartbeats[id] = Clock::now();
  // ...and carries the requester's step: keep the live view fresh for
  // clients whose heartbeat loop lags the join (raw wire clients).
  {
    auto step_it = hb_step_.find(id);
    if (step_it == hb_step_.end() || req.requester().step() > step_it->second) {
      if (step_it != hb_step_.end()) last_commit_ms_[id] = NowEpochMs();
      hb_step_[id] = req.requester().step();
    }
  }
  state_.participants[id] = QuorumState::Joined{req.requester(), Clock::now()};
  // Only quorums broadcast after this join count — a stale quorum from a
  // previous round must not satisfy this request.
  int64_t start_gen = quorum_gen_;
  TickLocked();

  // Wait for a quorum broadcast that includes the requester; a member may be
  // excluded from the quorum its own join triggered (e.g. shrink_only), in
  // which case it keeps waiting for a later round (src/lighthouse.rs:494-530).
  while (true) {
    if (evicted_.count(id)) {
      // Evicted while blocked here: abort instead of re-registering (the
      // re-register below would resurrect a corpse the supervisor already
      // replaced with a fresh incarnation).
      *err = "replica " + id + " was evicted by its supervisor";
      return Status::kAborted;
    }
    if (state_.draining.count(id)) {
      // Drain notice landed while this join was blocked: the quorum it is
      // waiting for will exclude it forever — unblock the caller so the
      // departing process can proceed to its drain exit.
      *err = "replica " + id + " is draining; rejoin as a new incarnation";
      return Status::kAborted;
    }
    if (latest_quorum_ && quorum_gen_ > start_gen) {
      for (const auto& m : latest_quorum_->participants()) {
        if (m.replica_id() == id) {
          *resp->mutable_quorum() = *latest_quorum_;
          return Status::kOk;
        }
      }
      // A quorum formed WITHOUT this requester (e.g. a shrink_only round
      // excluded a fresh joiner).  Formation cleared `participants`, so
      // re-register for the next round or this caller would never be
      // considered again (reference: the pending request stays queued,
      // src/lighthouse.rs:494-530 / test at src/lighthouse.rs:1078-1181).
      // Re-joining is an implicit heartbeat like the initial join above:
      // a raw wire client (docs/wire.md) with no heartbeat loop must not
      // age out of the healthy filter while it blocks here.
      state_.heartbeats[id] = Clock::now();
      state_.participants.emplace(id,
                                  QuorumState::Joined{req.requester(), Clock::now()});
    }
    int64_t gen = quorum_gen_;
    bool woke = quorum_cv_.wait_until(lk, deadline.at, [&] {
      return quorum_gen_ != gen || shutdown_ || evicted_.count(id) > 0 ||
             state_.draining.count(id) > 0;
    });
    if (shutdown_) {
      *err = "lighthouse shutting down";
      return Status::kUnavailable;
    }
    if (!woke && deadline.expired()) {
      *err = "timed out waiting for quorum";
      return Status::kDeadlineExceeded;
    }
  }
}

void Lighthouse::TickLoop() {
  while (true) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      if (shutdown_) return;
      TickLocked();
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(opt_.quorum_tick_ms));
  }
}

void Lighthouse::TickLocked() {
  // Log healthy<->stale transitions: when a replica is declared dead (or
  // comes back) the operator must be able to see it and its heartbeat age.
  auto tick_now = Clock::now();
  auto hb_timeout = std::chrono::milliseconds(opt_.heartbeat_timeout_ms);
  for (const auto& [id, last] : state_.heartbeats) {
    if (state_.draining.count(id)) continue;  // a drained donor's clean
    // exit makes its heartbeat stale by design — not a death to announce.
    bool fresh = tick_now - last < hb_timeout;
    auto it = last_fresh_.find(id);
    if (it == last_fresh_.end()) {
      last_fresh_[id] = fresh;
    } else if (it->second != fresh) {
      it->second = fresh;
      auto age_ms =
          std::chrono::duration_cast<std::chrono::milliseconds>(tick_now - last).count();
      if (fresh) {
        LOGI("lighthouse: replica %s heartbeat recovered", id.c_str());
      } else {
        LOGW("lighthouse: replica %s heartbeat stale (age %lld ms) — declaring dead",
             id.c_str(), static_cast<long long>(age_ms));
      }
    }
  }
  // Evict replicas dead for >10x the heartbeat timeout: they are invisible
  // to quorum already (the healthy filter uses age < timeout, so this cannot
  // change quorum or split-brain arithmetic) and under replica-id churn
  // (uuid-suffixed ids across restarts) the maps otherwise grow without
  // bound, with every tick iterating the graveyard.  Pending joiners are
  // exempt: a replica with a blocked Join RPC that stalls past the horizon
  // (e.g. JIT-compile starvation) and then recovers must still be counted
  // when the quorum finally forms — participants is cleared every quorum
  // round anyway, so this exemption cannot leak.
  for (auto it = state_.heartbeats.begin(); it != state_.heartbeats.end();) {
    if (tick_now - it->second > hb_timeout * 10 &&
        state_.participants.find(it->first) == state_.participants.end()) {
      it = state_.heartbeats.erase(it);
    } else {
      ++it;
    }
  }
  // Tombstones outlive any in-flight zombie RPC by far at 10x the
  // heartbeat timeout; prune so id churn cannot grow the map unboundedly.
  for (auto it = evicted_.begin(); it != evicted_.end();) {
    if (tick_now - it->second > hb_timeout * 10) {
      it = evicted_.erase(it);
    } else {
      ++it;
    }
  }
  // Drain marks age out on the same horizon — but never before the
  // ANNOUNCED deadline passes: a 5-minute Kubernetes grace period must
  // keep the donor excluded for all 5 minutes (it may legitimately keep
  // heartbeating while it serves a long final checkpoint), while
  // replacement incarnations carry fresh uuids so exact-id marks cannot
  // block a legitimate member either way.
  for (auto it = state_.draining.begin(); it != state_.draining.end();) {
    bool horizon_passed = tick_now - it->second > hb_timeout * 10;
    auto dl = drain_deadline_ms_.find(it->first);
    bool deadline_passed =
        dl == drain_deadline_ms_.end() || NowEpochMs() > dl->second;
    if (horizon_passed && deadline_passed) {
      drain_deadline_ms_.erase(it->first);
      it = state_.draining.erase(it);
    } else {
      ++it;
    }
  }
  for (auto it = last_fresh_.begin(); it != last_fresh_.end();) {
    if (state_.heartbeats.find(it->first) == state_.heartbeats.end()) {
      it = last_fresh_.erase(it);
    } else {
      ++it;
    }
  }
  // Live-status maps follow the heartbeat graveyard: under uuid-suffixed
  // id churn they would otherwise grow without bound.
  auto prune_with_heartbeats = [&](auto& m) {
    for (auto it = m.begin(); it != m.end();) {
      if (state_.heartbeats.find(it->first) == state_.heartbeats.end()) {
        it = m.erase(it);
      } else {
        ++it;
      }
    }
  };
  prune_with_heartbeats(hb_step_);
  prune_with_heartbeats(hb_state_);
  prune_with_heartbeats(last_commit_ms_);

  std::string reason;
  auto members = QuorumCompute(Clock::now(), state_, opt_, &reason);
  // Log each distinct reason ONCE per membership situation: during healthy
  // steady state the tick alternates between the waiting reason and the
  // formed reason every round, so last-value dedup printed both at O(steps).
  // The set resets whenever quorum membership changes (below), which is the
  // reference's ChangeLogger discipline (src/lighthouse.rs:68-84).
  if (!reason.empty() && logged_reasons_.insert(reason).second) {
    LOGI("lighthouse: %s", reason.c_str());
  }
  if (!members) return;

  // Bump the quorum id only when membership changed
  // (reference: src/lighthouse.rs:288-304).
  bool changed = true;
  if (state_.prev_quorum) {
    const auto& prev = state_.prev_quorum->participants();
    if (static_cast<size_t>(prev.size()) == members->size()) {
      changed = false;
      for (int i = 0; i < prev.size(); ++i) {
        if (prev[i].replica_id() != (*members)[i].replica_id()) {
          changed = true;
          break;
        }
      }
    }
  }
  if (changed) state_.quorum_id += 1;

  Quorum q;
  q.set_quorum_id(state_.quorum_id);
  q.set_created_ms(NowEpochMs());
  for (const auto& m : *members) *q.add_participants() = m;

  state_.prev_quorum = q;
  // Every replica must re-join for the next round (src/lighthouse.rs:314-319).
  state_.participants.clear();
  latest_quorum_ = q;
  quorum_gen_ += 1;
  quorum_cv_.notify_all();
  // Log formation only when membership actually changed: a healthy 2-group
  // job forms an identical quorum every training step, and logging each one
  // made the lighthouse log O(steps) (VERDICT r3 #5).
  if (changed) {
    std::string ids;
    for (const auto& m : q.participants()) {
      if (!ids.empty()) ids += ", ";
      ids += m.replica_id();
    }
    LOGI("lighthouse: formed quorum %lld with %d participants [%s]",
         static_cast<long long>(state_.quorum_id), q.participants_size(),
         ids.c_str());
    logged_reasons_.clear();
  }
}

void Lighthouse::FillStatus(LighthouseStatusResponse* resp) {
  std::lock_guard<std::mutex> lk(mu_);
  if (state_.prev_quorum) *resp->mutable_prev_quorum() = *state_.prev_quorum;
  for (const auto& [id, j] : state_.participants) *resp->add_pending_participants() = j.member;
  auto now = Clock::now();
  for (const auto& [id, last] : state_.heartbeats) {
    (*resp->mutable_heartbeat_age_ms())[id] =
        std::chrono::duration_cast<std::chrono::milliseconds>(now - last).count();
  }
  resp->set_quorum_id(state_.quorum_id);
  for (const auto& [id, _] : state_.draining) resp->add_draining(id);
  for (const auto& [id, step] : hb_step_) (*resp->mutable_replica_step())[id] = step;
  for (const auto& [id, ms] : last_commit_ms_) (*resp->mutable_last_commit_ts_ms())[id] = ms;
  for (const auto& [id, st] : hb_state_) (*resp->mutable_replica_state())[id] = st;
}

int Lighthouse::EvictReplica(const std::string& prefix) {
  // Tombstones cover IDS SEEN at evict time.  A first-contact join that
  // was serialized by the dying process but not yet dispatched here can
  // still register afterwards — that zombie self-heals within
  // heartbeat_timeout (it never commits or heartbeats again), which is the
  // pre-eviction behavior for a bounded, microsecond-scale window.
  // Tombstoning the whole "<prefix>:" FAMILY instead would be wrong: the
  // replacement incarnation shares the prefix and joins milliseconds
  // later (hot-spare adoption), so it must not be blocked.
  std::lock_guard<std::mutex> lk(mu_);
  int dropped = 0;
  auto now = Clock::now();
  auto matches = [&](const std::string& id) {
    return id == prefix || id.rfind(prefix + ":", 0) == 0;
  };
  for (auto it = state_.heartbeats.begin(); it != state_.heartbeats.end();) {
    if (matches(it->first)) {
      evicted_[it->first] = now;  // tombstone: no zombie re-registration
      it = state_.heartbeats.erase(it);
      ++dropped;
    } else {
      ++it;
    }
  }
  for (auto it = state_.participants.begin(); it != state_.participants.end();) {
    if (matches(it->first)) {
      evicted_[it->first] = now;
      it = state_.participants.erase(it);
    } else {
      ++it;
    }
  }
  for (auto it = last_fresh_.begin(); it != last_fresh_.end();) {
    if (matches(it->first)) {
      it = last_fresh_.erase(it);
    } else {
      ++it;
    }
  }
  auto erase_matching = [&](auto& m) {
    for (auto it = m.begin(); it != m.end();) {
      if (matches(it->first)) {
        it = m.erase(it);
      } else {
        ++it;
      }
    }
  };
  erase_matching(hb_step_);
  erase_matching(hb_state_);
  erase_matching(last_commit_ms_);
  // Wake blocked quorum handlers: an evicted id's own handler must notice
  // its tombstone and abort instead of waiting out its deadline.
  quorum_cv_.notify_all();
  if (dropped > 0) {
    LOGI("lighthouse: evicted %d replica id(s) matching '%s' (supervisor "
         "reported dead)", dropped, prefix.c_str());
    TickLocked();  // a waiting quorum can now form without the straggler wait
  }
  return dropped;
}

int Lighthouse::DrainReplica(const std::string& prefix, int64_t deadline_ms) {
  // Unlike EvictReplica, the heartbeat entries stay: the departing process
  // is ALIVE and finishing its step — the dashboard should keep showing it
  // (as draining) until it actually exits.  Exclusion from quorum comes
  // from QuorumCompute skipping draining ids entirely.  Ids are collected
  // from everything the lighthouse currently knows: heartbeats, pending
  // joins, and the previous quorum's membership (a member between rounds
  // has neither a heartbeat-map-only presence nor a pending join).
  std::lock_guard<std::mutex> lk(mu_);
  auto matches = [&](const std::string& id) {
    return id == prefix || id.rfind(prefix + ":", 0) == 0;
  };
  std::set<std::string> ids;
  for (const auto& [id, _] : state_.heartbeats) {
    if (matches(id)) ids.insert(id);
  }
  for (const auto& [id, _] : state_.participants) {
    if (matches(id)) ids.insert(id);
  }
  if (state_.prev_quorum) {
    for (const auto& m : state_.prev_quorum->participants()) {
      if (matches(m.replica_id())) ids.insert(m.replica_id());
    }
  }
  auto now = Clock::now();
  int marked = 0;
  for (const auto& id : ids) {
    if (state_.draining.emplace(id, now).second) ++marked;
    if (deadline_ms > 0) drain_deadline_ms_[id] = NowEpochMs() + deadline_ms;
  }
  // Wake blocked joins: a draining id's own pending handler must abort
  // (it will never be included again), and waiting survivors can form
  // their next quorum without the straggler wait right now.
  quorum_cv_.notify_all();
  if (marked > 0) {
    LOGI("lighthouse: draining %d replica id(s) matching '%s' (cooperative "
         "departure%s)", marked, prefix.c_str(),
         deadline_ms > 0
             ? (", deadline " + std::to_string(deadline_ms) + " ms").c_str()
             : "");
    TickLocked();
  }
  return marked;
}

bool Lighthouse::KillReplica(const std::string& replica_id, std::string* err) {
  std::string address;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (state_.prev_quorum) {
      for (const auto& m : state_.prev_quorum->participants()) {
        if (m.replica_id() == replica_id) address = m.address();
      }
    }
    for (const auto& [id, j] : state_.participants) {
      if (id == replica_id) address = j.member.address();
    }
  }
  if (address.empty()) {
    if (err) *err = "unknown replica " + replica_id;
    return false;
  }
  RpcClient client(address);
  KillRequest kreq;
  kreq.set_msg("killed from lighthouse dashboard");
  std::string payload, resp;
  kreq.SerializeToString(&payload);
  // The manager exits inside the handler, so the connection usually drops
  // before a response arrives; any outcome but a clean error is success.
  client.Call(kManagerKill, payload, 5000, &resp, err);
  return true;
}

namespace {
std::string JsonEscape(const std::string& s) {
  std::string out;
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      default: out += c;
    }
  }
  return out;
}

// Prometheus label-value escaping (same rules as JSON's subset: backslash,
// double quote, newline).
std::string PromEscape(const std::string& s) { return JsonEscape(s); }
}  // namespace

std::string Lighthouse::MetricsText() {
  std::ostringstream o;
  std::lock_guard<std::mutex> lk(mu_);
  auto now = Clock::now();
  auto hb_timeout = std::chrono::milliseconds(opt_.heartbeat_timeout_ms);

  int64_t max_step = 0;
  for (const auto& [id, step] : hb_step_) max_step = std::max(max_step, step);

  int64_t healing = 0;
  for (const auto& [id, st] : hb_state_) {
    if (st == "heal") ++healing;
  }
  int64_t healthy = 0;
  for (const auto& [id, last] : state_.heartbeats) {
    if (!state_.draining.count(id) && now - last < hb_timeout) ++healthy;
  }
  // Healthy replicas at the max live step = the donor pool striped healing
  // can draw on; recovery bandwidth scales with this count, so it is the
  // capacity gauge to alert on (donor_pool == 1 means heals are pinned to
  // a single donor link again).  The reference step is the max over
  // ELIGIBLE replicas only — a draining or heartbeat-stale replica that
  // reported a higher step cannot serve, and counting against its step
  // would read donor_pool=0 (a false capacity alarm) during exactly the
  // departure scenarios the gauge exists to monitor.
  int64_t donor_pool = 0;
  int64_t max_eligible_step = -1;
  auto eligible = [&](const std::string& id) {
    auto hb = state_.heartbeats.find(id);
    return hb != state_.heartbeats.end() && !state_.draining.count(id) &&
           now - hb->second < hb_timeout;
  };
  for (const auto& [id, step] : hb_step_) {
    if (eligible(id)) max_eligible_step = std::max(max_eligible_step, step);
  }
  for (const auto& [id, step] : hb_step_) {
    if (eligible(id) && step == max_eligible_step) ++donor_pool;
  }

  auto gauge = [&](const char* name, const char* help) {
    o << "# HELP " << name << " " << help << "\n# TYPE " << name << " gauge\n";
  };
  gauge("tpuft_quorum_size", "participants in the current quorum");
  o << "tpuft_quorum_size "
    << (state_.prev_quorum ? state_.prev_quorum->participants_size() : 0) << "\n";
  gauge("tpuft_quorum_id", "monotonically increasing quorum id (bumps on membership change)");
  o << "tpuft_quorum_id " << state_.quorum_id << "\n";
  gauge("tpuft_quorum_age_seconds", "seconds since the current quorum formed");
  if (state_.prev_quorum) {
    o << "tpuft_quorum_age_seconds "
      << (NowEpochMs() - state_.prev_quorum->created_ms()) / 1000.0 << "\n";
  } else {
    o << "tpuft_quorum_age_seconds -1\n";
  }
  gauge("tpuft_replicas_healthy", "replicas with a fresh heartbeat (draining excluded)");
  o << "tpuft_replicas_healthy " << healthy << "\n";
  gauge("tpuft_pending_joins", "replicas blocked in a quorum join this round");
  o << "tpuft_pending_joins " << state_.participants.size() << "\n";
  gauge("tpuft_replicas_draining", "replicas marked for cooperative departure");
  o << "tpuft_replicas_draining " << state_.draining.size() << "\n";
  gauge("tpuft_replicas_tombstoned", "evicted incarnations still tombstoned against zombies");
  o << "tpuft_replicas_tombstoned " << evicted_.size() << "\n";
  gauge("tpuft_heal_in_progress", "replicas currently fetching weights from a peer");
  o << "tpuft_heal_in_progress " << healing << "\n";
  gauge("tpuft_donor_pool",
        "healthy replicas at the max live step (striped-heal donor capacity)");
  o << "tpuft_donor_pool " << donor_pool << "\n";

  gauge("tpuft_replica_step", "live training step per replica (from heartbeats)");
  for (const auto& [id, step] : hb_step_) {
    o << "tpuft_replica_step{replica=\"" << PromEscape(id) << "\"} " << step << "\n";
  }
  gauge("tpuft_replica_step_lag", "steps behind the most advanced replica");
  for (const auto& [id, step] : hb_step_) {
    o << "tpuft_replica_step_lag{replica=\"" << PromEscape(id) << "\"} "
      << (max_step - step) << "\n";
  }
  gauge("tpuft_replica_heartbeat_age_seconds", "seconds since the last heartbeat");
  for (const auto& [id, last] : state_.heartbeats) {
    auto age_ms =
        std::chrono::duration_cast<std::chrono::milliseconds>(now - last).count();
    o << "tpuft_replica_heartbeat_age_seconds{replica=\"" << PromEscape(id)
      << "\"} " << age_ms / 1000.0 << "\n";
  }
  gauge("tpuft_replica_last_commit_age_seconds",
        "seconds since the replica's reported step last advanced");
  for (const auto& [id, ms] : last_commit_ms_) {
    o << "tpuft_replica_last_commit_age_seconds{replica=\"" << PromEscape(id)
      << "\"} " << (NowEpochMs() - ms) / 1000.0 << "\n";
  }
  return o.str();
}

std::string Lighthouse::StatusJson() {
  LighthouseStatusResponse s;
  FillStatus(&s);
  std::ostringstream o;
  o << "{\"quorum_id\":" << s.quorum_id() << ",\"participants\":[";
  bool first = true;
  for (const auto& m : s.prev_quorum().participants()) {
    if (!first) o << ",";
    first = false;
    o << "{\"replica_id\":\"" << JsonEscape(m.replica_id()) << "\",\"address\":\""
      << JsonEscape(m.address()) << "\",\"step\":" << m.step()
      << ",\"world_size\":" << m.world_size() << "}";
  }
  o << "],\"pending\":[";
  first = true;
  for (const auto& m : s.pending_participants()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(m.replica_id()) << "\"";
  }
  o << "],\"heartbeat_age_ms\":{";
  first = true;
  for (const auto& [id, age] : s.heartbeat_age_ms()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\":" << age;
  }
  o << "},\"draining\":[";
  first = true;
  for (const auto& id : s.draining()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\"";
  }
  // Live per-replica observability (heartbeat step/state fields): the
  // participants[].step above is the QUORUM-SNAPSHOT step; replica_step is
  // real-time, and last_commit_ts_ms is when it last advanced.
  o << "],\"replica_step\":{";
  first = true;
  for (const auto& [id, step] : s.replica_step()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\":" << step;
  }
  o << "},\"last_commit_ts_ms\":{";
  first = true;
  for (const auto& [id, ms] : s.last_commit_ts_ms()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\":" << ms;
  }
  o << "},\"replica_state\":{";
  first = true;
  for (const auto& [id, st] : s.replica_state()) {
    if (!first) o << ",";
    first = false;
    o << "\"" << JsonEscape(id) << "\":\"" << JsonEscape(st) << "\"";
  }
  o << "}}";
  return o.str();
}

std::string Lighthouse::StatusHtml() {
  LighthouseStatusResponse s;
  FillStatus(&s);
  int64_t max_step = 0;
  for (const auto& m : s.prev_quorum().participants()) max_step = std::max(max_step, m.step());
  std::ostringstream o;
  o << "<!DOCTYPE html><html><head><title>tpu-ft lighthouse</title>"
       "<meta http-equiv=\"refresh\" content=\"1\">"
       "<style>body{font-family:monospace;background:#111;color:#eee;margin:2em}"
       ".card{border:1px solid #444;border-radius:6px;padding:1em;margin:.5em;display:inline-block;"
       "min-width:18em;vertical-align:top}"
       ".recovering{border-color:orange}.stale{color:#f66}"
       ".draining{border-color:#6af}"
       "button{background:#a33;color:#fff;border:0;padding:.3em .8em;border-radius:4px;"
       "cursor:pointer}</style></head><body>"
       "<h1>tpu-ft lighthouse</h1>";
  o << "<p>quorum_id: " << s.quorum_id() << " &mdash; " << s.prev_quorum().participants_size()
    << " participants, " << s.pending_participants_size() << " pending</p>";
  std::set<std::string> draining(s.draining().begin(), s.draining().end());
  int64_t max_live = 0;
  for (const auto& [id, st] : s.replica_step()) max_live = std::max(max_live, st);
  for (const auto& m : s.prev_quorum().participants()) {
    bool recovering = m.step() != max_step;
    bool is_draining = draining.count(m.replica_id()) > 0;
    int64_t age = -1;
    auto it = s.heartbeat_age_ms().find(m.replica_id());
    if (it != s.heartbeat_age_ms().end()) age = it->second;
    // Live step/lag from heartbeats (the quorum-snapshot step can be a
    // whole round stale); lag > 0 is the step-lag alarm /metrics exposes.
    int64_t live = m.step();
    auto ls = s.replica_step().find(m.replica_id());
    if (ls != s.replica_step().end()) live = ls->second;
    int64_t lag = max_live - live;
    std::string state;
    auto st_it = s.replica_state().find(m.replica_id());
    if (st_it != s.replica_state().end()) state = st_it->second;
    o << "<div class=\"card" << (is_draining ? " draining" : recovering ? " recovering" : "")
      << "\"><b>" << m.replica_id() << "</b><br>step: " << live
      << " <span class=\"" << (lag > 0 ? "stale" : "") << "\">(lag " << lag << ")</span>"
      << (state.empty() ? "" : " [" + state + "]")
      << (is_draining ? " (draining)" : recovering ? " (recovering)" : "")
      << "<br>world_size: " << m.world_size() << "<br>manager: " << m.address()
      << "<br><span class=\"" << (age > 2500 ? "stale" : "") << "\">heartbeat: " << age
      << " ms ago</span><br><form method=\"post\" action=\"/replica/" << m.replica_id()
      << "/kill\"><button>Kill</button></form>"
      << "<form method=\"post\" action=\"/replica/" << m.replica_id()
      << "/drain\"><button style=\"background:#36a\">Drain</button></form></div>";
  }
  o << "</body></html>";
  return o.str();
}

}  // namespace tpuft
