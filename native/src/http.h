// Minimal HTTP/1.1 server for the Lighthouse dashboard and ops endpoints.
// Reference parity: the axum routes in src/lighthouse.rs:349-367.
#pragma once

#include <atomic>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace tpuft {

struct HttpResponse {
  int code = 200;
  std::string content_type = "text/html; charset=utf-8";
  std::string body;
  // When non-empty, emitted as a Location header (redirect-to-leader on
  // HA standbys; pair with code 307 so POSTs re-POST).
  std::string location;
};

// One parsed request plus the connection facts the ops-endpoint trust
// model needs (docs/wire.md "Trust model"): the shared-secret header and
// whether the peer is loopback.
struct HttpRequestInfo {
  std::string method;
  std::string path;
  std::string body;
  // Value of the "x-tpuft-token" header, empty when absent.
  std::string token;
  // True when the TCP peer is 127.0.0.0/8, ::1, or a v4-mapped loopback.
  bool peer_loopback = false;
};

using HttpHandler = std::function<HttpResponse(const HttpRequestInfo& req)>;

class HttpServer {
 public:
  HttpServer(std::string bind, HttpHandler handler);
  ~HttpServer();
  bool Start(std::string* err);
  void Shutdown();
  std::string address() const { return address_; }

 private:
  void AcceptLoop();
  void Serve(int fd);
  using FinishedConn = std::pair<int, std::shared_ptr<std::thread>>;
  void ReapFinishedLocked(std::vector<FinishedConn>* out);

  std::string bind_;
  HttpHandler handler_;
  int listen_fd_ = -1;
  std::string address_;
  std::atomic<bool> shutdown_{false};
  std::thread accept_thread_;
  std::mutex conns_mu_;
  std::map<int, std::shared_ptr<std::thread>> conns_;
  // Finished connection threads awaiting join-then-close (see
  // RpcServer::finished_: detaching raced static destruction at process
  // exit, and closing before the join raced fd-number reuse).
  std::vector<FinishedConn> finished_;
};

}  // namespace tpuft
