// Minimal HTTP/1.1 server for the Lighthouse dashboard and ops endpoints.
// Reference parity: the axum routes in src/lighthouse.rs:349-367.
#pragma once

#include <atomic>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>

namespace tpuft {

struct HttpResponse {
  int code = 200;
  std::string content_type = "text/html; charset=utf-8";
  std::string body;
};

// (method, path, body) -> response.
using HttpHandler = std::function<HttpResponse(const std::string& method, const std::string& path,
                                               const std::string& body)>;

class HttpServer {
 public:
  HttpServer(std::string bind, HttpHandler handler);
  ~HttpServer();
  bool Start(std::string* err);
  void Shutdown();
  std::string address() const { return address_; }

 private:
  void AcceptLoop();
  void Serve(int fd);

  std::string bind_;
  HttpHandler handler_;
  int listen_fd_ = -1;
  std::string address_;
  std::atomic<bool> shutdown_{false};
  std::thread accept_thread_;
  std::mutex conns_mu_;
  std::map<int, std::shared_ptr<std::thread>> conns_;
};

}  // namespace tpuft
