// Framed-TCP RPC transport for the tpu-ft coordination plane.
//
// Plays the role of tonic/gRPC in the reference (src/net.rs:8-34): a client
// connects with retry + keep-alive, sends one protobuf-serialized request per
// frame, and blocks for the response.  The frame header carries a
// client-chosen deadline which the server honors on blocking calls — the
// analogue of the reference's `grpc-timeout` header parsing (src/timeout.rs:18-61).
#pragma once

#include <atomic>
#include <chrono>
#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace tpuft {

using Clock = std::chrono::steady_clock;
using TimePoint = Clock::time_point;

// Wire protocol version (see docs/wire.md).  Carried in every frame
// header; a peer speaking a different version is rejected loudly with
// FAILED_PRECONDITION rather than misparsed.  Pre-versioning builds
// wrote 0 in this slot, so they are rejected too.
constexpr uint8_t kWireVersion = 1;

// The on-the-wire frame header (32 bytes, little-endian, packed).  This
// IS the wire contract — see docs/wire.md for the field semantics.
#pragma pack(push, 1)
struct FrameHeader {
  uint32_t magic;        // kFrameMagic
  uint16_t method;       // Method enum below (requests); echoed in responses
  uint16_t status;       // Status enum below; 0 (OK) in requests
  uint64_t req_id;       // client-chosen, echoed in the response
  uint64_t deadline_ms;  // relative deadline budget chosen by the client; 0 = none
  uint32_t len;          // payload byte length (protobuf-serialized message)
  uint8_t version;       // kWireVersion
  uint8_t flags;         // reserved, must be 0
  uint16_t reserved;     // reserved, must be 0
};
#pragma pack(pop)
static_assert(sizeof(FrameHeader) == 32, "frame header must be 32 bytes");

constexpr uint32_t kFrameMagic = 0x7f7a55aa;

// gRPC-compatible status codes so the Python layer can map
// CANCELLED/DEADLINE_EXCEEDED -> TimeoutError like the reference
// (src/lib.rs:644-668).
enum class Status : uint16_t {
  kOk = 0,
  kCancelled = 1,
  kUnknown = 2,
  kInvalidArgument = 3,
  kDeadlineExceeded = 4,
  kNotFound = 5,
  kFailedPrecondition = 9,
  kAborted = 10,
  kInternal = 13,
  kUnavailable = 14,
};

// Method ids (stable wire contract; see proto/tpuft.proto section comments).
enum Method : uint16_t {
  kLighthouseQuorum = 1,
  kLighthouseHeartbeat = 2,
  kLighthouseStatus = 3,
  kLighthouseEvict = 4,
  kLighthouseDrain = 5,
  // HA lighthouse (docs/wire.md "HA lighthouse"): leader->standby state
  // replication push, and read-only leader discovery answered by every
  // replica regardless of role.
  kLighthouseReplicate = 6,
  kLighthouseLeaderInfo = 7,
  // Federation (docs/wire.md "Federation"): regional child -> root digest
  // push, and the read-only per-region rollup listing answered by every
  // instance regardless of federation role.
  kLighthouseRegionDigest = 8,
  kLighthouseRegions = 9,
  kManagerQuorum = 10,
  kManagerCheckpointMetadata = 11,
  kManagerShouldCommit = 12,
  kManagerKill = 13,
  kStoreSet = 20,
  kStoreGet = 21,
  kStoreAdd = 22,
  kStoreDelete = 23,
};

struct Deadline {
  // Absolute steady-clock deadline; TimePoint::max() means "none".
  TimePoint at = TimePoint::max();

  static Deadline FromMillis(uint64_t ms) {
    Deadline d;
    if (ms > 0) d.at = Clock::now() + std::chrono::milliseconds(ms);
    return d;
  }
  bool expired() const { return Clock::now() >= at; }
  // Remaining time in ms, clamped to >= 0; large value when unset.
  int64_t remaining_ms() const {
    if (at == TimePoint::max()) return INT64_MAX;
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(at - Clock::now()).count();
    return left < 0 ? 0 : left;
  }
};

// A parsed "host:port" / "[v6]:port" address.
struct SockAddr {
  std::string host;
  uint16_t port = 0;
};
bool ParseAddress(const std::string& addr, SockAddr* out, std::string* err);

// ---------------------------------------------------------------------------
// Server
// ---------------------------------------------------------------------------

// Handler: (method, request payload, deadline, peer) -> status + response
// payload.  `peer` is the remote "host:port" of the connection the frame
// arrived on — what the flight recorder stamps into server-side RPC spans.
using RpcHandler =
    std::function<Status(uint16_t method, const std::string& req, Deadline deadline,
                         const std::string& peer, std::string* resp)>;

class RpcServer {
 public:
  // bind: "host:port", port 0 for ephemeral.  The handler runs on a
  // per-connection thread and may block (subject to the frame deadline).
  RpcServer(std::string bind, RpcHandler handler);
  ~RpcServer();

  // Starts the accept loop.  Returns false and fills err on bind failure.
  bool Start(std::string* err);
  // Address actually bound, "host:port" with the resolved port.
  std::string address() const { return address_; }
  uint16_t port() const { return port_; }
  void Shutdown();

 private:
  void AcceptLoop();
  void Serve(int fd);

  using FinishedConn = std::pair<int, std::shared_ptr<std::thread>>;
  void ReapFinishedLocked(std::vector<FinishedConn>* out);

  std::string bind_;
  RpcHandler handler_;
  int listen_fd_ = -1;
  std::string address_;
  uint16_t port_ = 0;
  std::atomic<bool> shutdown_{false};
  std::thread accept_thread_;
  std::mutex conns_mu_;
  std::map<int, std::shared_ptr<std::thread>> conns_;
  // Connection threads that finished serving move their own handle (and
  // fd) here — a thread cannot join itself; the accept loop and Shutdown
  // join them and only THEN close the fd, so no fd is ever closed while
  // another thread could still ::shutdown() it (a closed number can be
  // reused by an unrelated descriptor).  Detaching instead raced process
  // exit: a detached thread's epilogue during static destruction aborted
  // ~1/30 runs.
  std::vector<FinishedConn> finished_;
};

// ---------------------------------------------------------------------------
// Client
// ---------------------------------------------------------------------------

class RpcClient {
 public:
  explicit RpcClient(std::string addr) : addr_(std::move(addr)) {}
  ~RpcClient();

  // Establish the connection, retrying with exponential backoff until
  // connect_timeout_ms elapses (reference: src/net.rs:22-34 + retry.rs).
  Status Connect(uint64_t connect_timeout_ms, std::string* err);

  // One blocking RPC.  timeout_ms==0 means no deadline.  Thread-safe; calls
  // are serialized on the single connection.
  Status Call(uint16_t method, const std::string& req, uint64_t timeout_ms,
              std::string* resp, std::string* err);

  const std::string& addr() const { return addr_; }
  void Close();

 private:
  Status CallLocked(uint16_t method, const std::string& req, uint64_t timeout_ms,
                    std::string* resp, std::string* err);

  std::string addr_;
  std::mutex mu_;
  int fd_ = -1;
  uint64_t next_req_id_ = 1;
};

// Dials a TCP connection; returns fd or -1 (err filled).
int DialTcp(const std::string& addr, uint64_t timeout_ms, std::string* err);

std::string StatusName(Status s);

// Human-readable wire method name ("Quorum", "ManagerQuorum", "StoreGet",
// ...; "Method<N>" for unknown ids) — the flight recorder's and the
// tpuft_rpc_latency_seconds histogram's `method` label.
std::string MethodName(uint16_t method);

// Remote "host:port" of a connected socket ("" on failure).
std::string PeerAddress(int fd);

// ---------------------------------------------------------------------------
// Failover client (HA lighthouse, docs/wire.md)
// ---------------------------------------------------------------------------

// The standby-rejection contract: a lighthouse that is not the current
// lease holder answers every mutating method with kUnavailable and an
// error string starting with this prefix, optionally naming the leader:
//   "not the leader; leader=<rpc_addr> http=<http_addr> epoch=<N>"
// (the framed-TCP wire carries status + message only — no structured
// error payload — so the address rides in the message like the Python
// Manager's "is draining" contract).  ParseNotLeader extracts the
// leader's RPC address ("" when unknown / not a redirect).
extern const char kNotLeaderPrefix[];
bool ParseNotLeader(const std::string& err, std::string* leader_addr);

// Multi-address RPC client for a replicated service: Call() tries the
// current address and, on transport failure or an UNAVAILABLE rejection,
// fails over — a "not the leader; leader=<addr>" rejection jumps straight
// to the named leader, anything else rotates to the next address — and
// keeps retrying with decorrelated-jitter backoff until the call deadline
// expires.  The jitter matters at fleet scale: N replica groups failing
// over simultaneously must not stampede the new leader with synchronized
// retries.  One live RpcClient per address is kept for connection reuse.
// Thread-safe like RpcClient (calls serialize on an internal mutex).
class FailoverRpcClient {
 public:
  // addrs: comma-separated "host:port" list (single address = plain
  // client with retry).
  explicit FailoverRpcClient(const std::string& addrs);
  ~FailoverRpcClient();

  Status Call(uint16_t method, const std::string& req, uint64_t timeout_ms,
              std::string* resp, std::string* err);

  // Probes reachability: succeeds as soon as ANY address accepts a TCP
  // connection, fails with an error naming every address once
  // connect_timeout_ms elapses.  Used at Manager startup so a dead
  // address list raises a clean, actionable error instead of the first
  // quorum hanging out its full deadline.
  Status Connect(uint64_t connect_timeout_ms, std::string* err);

  const std::vector<std::string>& addrs() const { return addrs_; }
  // Address the last successful (or currently preferred) call targets.
  std::string current();
  void Close();

 private:
  RpcClient* ClientForLocked(const std::string& addr);

  std::vector<std::string> addrs_;
  std::mutex mu_;
  size_t cur_ = 0;
  // Leader learned from a redirect; tried first while set.  May name an
  // address outside addrs_ (a replica set that moved).
  std::string leader_override_;
  std::map<std::string, std::unique_ptr<RpcClient>> clients_;
};

// Splits a comma-separated address list, trimming blanks.
std::vector<std::string> SplitAddressList(const std::string& addrs);

}  // namespace tpuft
