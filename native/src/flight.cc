#include "flight.h"

#include <cctype>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <sstream>

namespace tpuft {

std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

namespace {

int64_t EpochMsNow() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

int64_t MonoUsNow() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void AppendEventJson(std::ostringstream& o, const FlightEvent& ev) {
  o << "{\"seq\":" << ev.seq << ",\"ts_ms\":" << ev.ts_ms
    << ",\"mono_us\":" << ev.mono_us << ",\"kind\":\"" << JsonEscape(ev.kind)
    << "\"";
  if (!ev.method.empty()) o << ",\"method\":\"" << JsonEscape(ev.method) << "\"";
  if (!ev.peer.empty()) o << ",\"peer\":\"" << JsonEscape(ev.peer) << "\"";
  if (ev.kind == kFlightRpc) {
    o << ",\"status\":" << ev.status << ",\"dur_us\":" << ev.dur_us;
  }
  if (!ev.trace_id.empty()) {
    o << ",\"trace_id\":\"" << JsonEscape(ev.trace_id) << "\"";
  }
  if (!ev.detail.empty()) {
    o << ",\"detail\":\"" << JsonEscape(ev.detail) << "\"";
  }
  o << "}";
}

}  // namespace

FlightRecorder::FlightRecorder(size_t capacity, size_t transition_capacity)
    : capacity_(capacity == 0 ? 1 : capacity),
      trans_capacity_(transition_capacity == 0 ? 1 : transition_capacity) {
  ring_.resize(capacity_);
  trans_ring_.resize(trans_capacity_);
}

void FlightRecorder::SetIdentity(const std::string& server, const std::string& id) {
  std::lock_guard<std::mutex> lk(mu_);
  server_ = server;
  id_ = id;
}

void FlightRecorder::Record(FlightEvent ev) {
  ev.ts_ms = EpochMsNow();
  ev.mono_us = MonoUsNow();
  bool is_span = ev.kind == kFlightRpc;
  std::lock_guard<std::mutex> lk(mu_);
  ev.seq = ++seq_;
  // Spans and transitions retain separately: heartbeat-span volume at
  // O(dozens) replicas must not evict the (rare) membership history.
  if (is_span) {
    ++span_count_;
    ring_[next_] = std::move(ev);
    next_ = (next_ + 1) % capacity_;
  } else {
    ++trans_count_;
    trans_ring_[trans_next_] = std::move(ev);
    trans_next_ = (trans_next_ + 1) % trans_capacity_;
  }
}

void FlightRecorder::RecordEvent(const char* kind, std::string detail,
                                 std::string trace_id) {
  FlightEvent ev;
  ev.kind = kind;
  ev.detail = std::move(detail);
  ev.trace_id = std::move(trace_id);
  Record(std::move(ev));
}

void FlightRecorder::RecordRpc(const char* method, std::string peer,
                               uint16_t status, int64_t dur_us,
                               std::string trace_id) {
  FlightEvent ev;
  ev.kind = kFlightRpc;
  ev.method = method;
  ev.peer = std::move(peer);
  ev.status = status;
  ev.dur_us = dur_us;
  ev.trace_id = std::move(trace_id);
  Record(std::move(ev));
}

int64_t FlightRecorder::recorded() const {
  std::lock_guard<std::mutex> lk(mu_);
  return seq_;
}

std::string FlightRecorder::Json(size_t limit) const {
  std::ostringstream o;
  std::lock_guard<std::mutex> lk(mu_);
  size_t span_ret = span_count_ < static_cast<int64_t>(capacity_)
                        ? static_cast<size_t>(span_count_)
                        : capacity_;
  size_t trans_ret = trans_count_ < static_cast<int64_t>(trans_capacity_)
                         ? static_cast<size_t>(trans_count_)
                         : trans_capacity_;
  size_t retained = span_ret + trans_ret;
  size_t emit = (limit == 0 || limit > retained) ? retained : limit;
  o << "{\"server\":\"" << JsonEscape(server_) << "\",\"id\":\""
    << JsonEscape(id_) << "\",\"capacity\":" << (capacity_ + trans_capacity_)
    << ",\"recorded\":" << seq_
    << ",\"dropped\":" << (seq_ - static_cast<int64_t>(retained))
    << ",\"dumped_ts_ms\":" << EpochMsNow() << ",\"events\":[";
  // Newest first, merged across the two rings by seq: walk each ring
  // backwards from its newest slot and emit the larger seq at each step.
  size_t i = 0, j = 0, written = 0;
  while (written < emit && (i < span_ret || j < trans_ret)) {
    const FlightEvent* span =
        i < span_ret ? &ring_[(next_ + capacity_ - 1 - i) % capacity_] : nullptr;
    const FlightEvent* trans =
        j < trans_ret
            ? &trans_ring_[(trans_next_ + trans_capacity_ - 1 - j) % trans_capacity_]
            : nullptr;
    const FlightEvent* pick;
    if (span && (!trans || span->seq > trans->seq)) {
      pick = span;
      ++i;
    } else {
      pick = trans;
      ++j;
    }
    if (written) o << ",";
    AppendEventJson(o, *pick);
    ++written;
  }
  o << "]}";
  return o.str();
}

bool FlightRecorder::DumpToFile(const std::string& path) const {
  if (path.empty()) return false;
  std::string body = Json(0);
  std::string tmp = path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (!f) return false;
  size_t n = fwrite(body.data(), 1, body.size(), f);
  bool ok = n == body.size();
  ok = fclose(f) == 0 && ok;
  if (ok) ok = rename(tmp.c_str(), path.c_str()) == 0;
  if (!ok) remove(tmp.c_str());
  return ok;
}

std::string FlightRecorder::DumpPathFromEnv() const {
  const char* dir = std::getenv("TPUFT_FLIGHT_DIR");
  if (!dir || !dir[0]) return "";
  std::string server, id;
  {
    std::lock_guard<std::mutex> lk(mu_);
    server = server_;
    id = id_;
  }
  std::string safe;
  for (char c : id) {
    safe += (isalnum(static_cast<unsigned char>(c)) || c == '-' || c == '.')
                ? c
                : '_';
  }
  return std::string(dir) + "/flight_" + server + (safe.empty() ? "" : "_" + safe) +
         ".json";
}

// ---------------------------------------------------------------------------
// LatencyHistogram
// ---------------------------------------------------------------------------

const std::vector<double>& LatencyHistogram::Bounds() {
  static const std::vector<double> kBounds = {
      0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
      0.05,   0.1,     0.25,   0.5,  1.0,    2.5,   5.0,  10.0};
  return kBounds;
}

LatencyHistogram::LatencyHistogram() : counts_(Bounds().size() + 1, 0) {}

void LatencyHistogram::Observe(double seconds) {
  const auto& bounds = Bounds();
  size_t idx = bounds.size();  // +Inf slot
  for (size_t i = 0; i < bounds.size(); ++i) {
    if (seconds <= bounds[i]) {
      idx = i;
      break;
    }
  }
  std::lock_guard<std::mutex> lk(mu_);
  counts_[idx] += 1;
  sum_ += seconds;
  count_ += 1;
}

uint64_t LatencyHistogram::count() const {
  std::lock_guard<std::mutex> lk(mu_);
  return count_;
}

std::vector<uint64_t> LatencyHistogram::Snapshot(double* sum, uint64_t* count) const {
  std::lock_guard<std::mutex> lk(mu_);
  if (sum) *sum = sum_;
  if (count) *count = count_;
  return counts_;
}

void ExposeHistogram(
    std::ostream& o, const std::string& name, const std::string& help,
    const std::vector<std::pair<std::string, const LatencyHistogram*>>& series) {
  o << "# HELP " << name << " " << help << "\n# TYPE " << name << " histogram\n";
  const auto& bounds = LatencyHistogram::Bounds();
  char le[32];
  for (const auto& [label, hist] : series) {
    double sum = 0.0;
    uint64_t count = 0;
    std::vector<uint64_t> counts = hist->Snapshot(&sum, &count);
    uint64_t cum = 0;
    for (size_t i = 0; i < bounds.size(); ++i) {
      cum += counts[i];
      snprintf(le, sizeof(le), "%g", bounds[i]);
      o << name << "_bucket{" << label << (label.empty() ? "" : ",")
        << "le=\"" << le << "\"} " << cum << "\n";
    }
    o << name << "_bucket{" << label << (label.empty() ? "" : ",")
      << "le=\"+Inf\"} " << count << "\n";
    o << name << "_sum" << (label.empty() ? "" : "{" + label + "}") << " "
      << sum << "\n";
    o << name << "_count" << (label.empty() ? "" : "{" + label + "}") << " "
      << count << "\n";
  }
}

}  // namespace tpuft
