// GIL-free ring data plane: the hot loop of TCPCollective's striped
// multi-lane ring allreduce, moved out of Python threads.
//
// The Python layer keeps everything slow-path and contractual — rendezvous,
// the 12-byte connection preamble, tag allocation, topology resolution,
// stripe/chunk boundary math (np.array_split), abort/reconfigure semantics —
// and hands this engine the established lane sockets (dup'd fds) plus, per
// op, the chunk views of a contiguous float32 working buffer.  Everything
// per-hop runs here without the interpreter: scatter-gather writev/readv-
// style socket I/O over the caller's buffers, the leader/follower tag-demux
// reader, the per-direction virtual-time link pacing (LinkShaper's model),
// and the bf16 / int8 wire codecs.
//
// Wire format is IDENTICAL to the Python engine (same `<IQ` frame header,
// same per-hop codec bytes, same combine order), so the two engines are
// bitwise-interoperable: a native rank and a Python rank on one ring decode
// the same results, and the parity tests pin native == python bit for bit.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace tpuft {

// Error classes surfaced to Python (mapped to TimeoutError /
// ConnectionError / RuntimeError by the bindings).
enum class RingStatus : int {
  kOk = 0,
  kTimeout = 1,
  kClosed = 2,   // peer gone / engine closed mid-op
  kError = 3,    // anything else (bad args, syscall failure)
};

// Ring tiers, matching TCPCollective's channel layout: the flat ring plus
// the 2D topology's row/column tier rings.
enum RingTier : int { kTierFlat = 0, kTierRow = 1, kTierCol = 2, kNumTiers = 3 };

enum RingDir : int { kDirNext = 0, kDirPrev = 1 };

// Ring-pass modes: a full reduce-scatter + allgather pass, or one phase
// (the hierarchical pass runs row-RS, column-FULL, row-AG as three calls).
enum RingPassMode : int { kPassFull = 0, kPassReduceScatter = 1, kPassAllgather = 2 };

// Reduce ops ("avg" divides in Python after the pass, so it is kOpSum here).
enum RingOp : int { kOpSum = 0, kOpMax = 1, kOpMin = 2 };

// Wire encodings per hop.  kWireRaw frames the f32 chunk bytes unchanged;
// kWireBf16 casts f32 -> bfloat16 (round-to-nearest-even, ml_dtypes
// bit-compatible) per hop with f32 accumulation; kWireInt8 frames a 4-byte
// f32 scale followed by symmetric int8 values (scale = amax/127), matching
// collectives.quantize_int8 bit for bit; kWireInt4 frames the 4-byte f32
// scale (amax/7) followed by two's-complement nibble pairs (even element in
// the low nibble), matching collectives.quantize_int4 bit for bit.
enum RingWire : int { kWireRaw = 0, kWireBf16 = 1, kWireInt8 = 2, kWireInt4 = 3 };

// Shared virtual-time pacer for one tier-direction (LinkShaper's model):
// concurrent lanes queue on the modeled link, so lanes can only win by
// overlapping propagation and host work with serialization.
struct RingShaper {
  // Atomic: OnSend's early-out reads it from the lane sender threads
  // while SetRate (mid-run re-shaping) writes it from the caller.
  std::atomic<bool> enabled{false};
  double bytes_per_s = 0;
  double half_rtt_s = 0;
  // Engine-wide close flag: the pacer sleeps in short slices against it so
  // Close()'s drain never waits out a multi-second modeled serialization.
  const std::atomic<bool>* closed = nullptr;
  std::mutex mu;
  double busy_until_s = 0;  // steady-clock seconds
  std::atomic<uint64_t> bytes_sent{0};
  std::atomic<uint64_t> frames_sent{0};
  // Time actually slept waiting out the modeled serialization + propagation
  // (the "shaping" bucket of the data-plane attribution split): microseconds
  // so the counter read is one atomic load, like the byte counters.
  std::atomic<uint64_t> wait_us{0};

  void OnSend(size_t nbytes);
  // Mid-run re-shaping (the slow-link bench degrades ONE peer direction
  // 10x without a reconfigure).  mbps <= 0 disables pacing.
  void SetRate(double mbps, double rtt_ms);
};

// One recorded ring hop — the data-plane flight recorder's unit.  The
// FIELD SET AND ORDER are the cross-engine schema contract: the Python
// engine's HopRecorder (collectives.HOP_RECORD_FIELDS) emits dicts with
// exactly these keys, and tf_ring_hop_records marshals each record as 8
// doubles in exactly this order.  tests/test_link.py pins both.
struct RingHopRecord {
  double ts = 0;        // wall-clock (epoch) seconds at hop start
  int32_t tier = 0;     // kTierFlat / kTierRow / kTierCol
  int32_t lane = 0;
  uint32_t tag = 0;     // frame tag (encodes op seq / stripe / rs-vs-ag)
  double send_s = 0;    // blocked joining the lane sender (incl. pacing)
  double recv_s = 0;    // blocked waiting for the matching inbound frame
  double comb_s = 0;    // decode + combine of the received chunk (RS hops)
  uint64_t nbytes = 0;  // frame payload bytes sent (header excluded)
};

struct RingSendJob;

// One lane socket of one tier-direction.  `next` links own a sender thread
// draining a FIFO job queue (the per-lane single-worker sender pool,
// natively); `prev` links own the leader/follower demux state.
struct RingLink {
  int fd = -1;
  RingShaper* shaper = nullptr;
  std::atomic<uint64_t> bytes{0};  // wire bytes incl. headers (out for next, in for prev)
  std::atomic<bool> dead{false};
  // Written exactly once, under dead_mu, BEFORE dead's release-store flips
  // true (PoisonLink) — so any thread that observes dead == true may read
  // it lock-free.  Concurrent failure paths (op thread, sender, Close)
  // race to poison; dead_mu picks one winner.
  std::mutex dead_mu;
  std::string dead_reason;

  // Sender (next links).
  std::thread sender;
  std::mutex qmu;
  std::condition_variable qcv;
  std::deque<std::shared_ptr<RingSendJob>> queue;
  bool stop = false;

  // Demux (prev links): exactly one reader on the socket at a time; it
  // publishes non-matching frames to the stash under the condition and
  // notifies, so a follower whose frame already landed takes it without
  // queuing behind the leader's blocking read.
  std::mutex rmu;
  std::condition_variable rcv;
  bool reading = false;
  std::map<uint32_t, std::deque<std::string>> stash;

  // Same-host shared-memory transport (TPUFT_RING_TRANSPORT): when a
  // segment is attached, frame bytes move through its lock-free SPSC byte
  // ring instead of the socket.  The socket stays open as the liveness /
  // abort channel — the shm wait loops poll it, so a dead peer or a local
  // shutdown() wakes a blocked op exactly like the tcp path.
  uint8_t* shm = nullptr;  // mapped segment base (64-byte header + data)
  size_t shm_cap = 0;      // data capacity (mapping length - header)
  size_t shm_len = 0;      // full mapping length (for munmap)
};

class RingEngine {
 public:
  // lanes: lane count every registered tier uses.  mbps <= 0 disables the
  // shaped-link pacer (counters still tick).
  RingEngine(int lanes, double shaper_mbps, double shaper_rtt_ms);
  ~RingEngine();

  // Registers one tier's lane sockets.  The fds are dup()'d — the Python
  // side keeps (and closes) its own socket objects; Close() closes the
  // dups.  Must be called before any op on that tier.
  bool SetTier(int tier, int nlanes, const int32_t* next_fds,
               const int32_t* prev_fds, std::string* err);

  // Shuts down + closes every dup'd fd and joins the sender threads.
  // Idempotent; safe to call while ops are in flight (they fail with
  // kClosed).  This is what abort()/_fail_ring latch onto.
  void Close();

  // Quiescent teardown for INCREMENTAL reconfiguration: releases every
  // dup'd fd with plain close() — never shutdown(), so the underlying
  // sockets the Python side still owns stay connected and the next
  // engine generation can re-adopt them — joins the sender/multi-pool
  // threads and unmaps shm segments (the segment files persist; the new
  // generation re-attaches by path + token).  Refuses (returns false,
  // engine untouched) when any op is in flight: a mid-op detach would
  // leave the reused socket mid-frame.  The engine is closed afterwards.
  bool Detach(std::string* err);

  // Close()/Detach() already ran (a detached engine stays safely inert
  // until freed).
  bool Closed() const { return closed_.load(); }

  // Dup'd fds still open (the fd-leak sweep's native counterpart).
  int OpenFds() const;

  // Full-duplex whole-frame exchange on (tier, lane): sends `len` bytes
  // under `tag` to the next neighbor while receiving the same tag from the
  // previous one.  The received payload is returned in *out.  This is what
  // the Python-orchestrated ops (allgather/broadcast/alltoall/barrier and
  // non-f32 payload fallbacks) ride, so ALL reads of a lane socket go
  // through one demux.
  RingStatus Exchange(int tier, int lane, uint32_t tag, const uint8_t* buf,
                      size_t len, std::string* out, double timeout_s,
                      std::string* err);

  // One ring pass over `n` chunk views of the caller's f32 working buffer,
  // in place: mode selects reduce-scatter / allgather / both, `op` the
  // combine, `wire` the per-hop codec.  rank is this rank's position on
  // the tier ring; tags are tag_base + rs_sub / + ag_sub (the caller's
  // stripe block).  Hop order, combine order, and codec arithmetic are
  // bit-exact mirrors of the Python engine.
  RingStatus RingPass(int tier, int lane, int n, int rank, uint32_t tag_base,
                      uint32_t rs_sub, uint32_t ag_sub, int mode, int op,
                      int wire, float* const* chunk_ptrs,
                      const uint64_t* chunk_elems, double timeout_s,
                      std::string* err);

  // Attaches a negotiated same-host shared-memory segment to one lane link
  // (direction 0 = next/producer, 1 = prev/consumer).  `path` is the
  // filesystem path of the segment (under /dev/shm); `token` must match
  // the segment's generation header or the attach is refused — a dead
  // peer's stale segment is never re-attached.  The link's socket remains
  // open as the liveness channel.
  bool SetShm(int tier, int direction, int lane, const char* path,
              uint64_t token, std::string* err);

  // Batched ring passes: the whole stripe set of one op in a single call
  // (one capi crossing instead of one per stripe), fanned out to the
  // engine's persistent internal workers.  Per stripe s: lane lanes[s],
  // tag base tag_bases[s], chunk views chunk_ptrs/chunk_elems[s*n..].
  // The first failing stripe's status is returned, and the tier's links
  // are poisoned on first failure so sibling stripes fail fast — the same
  // fate _run_striped's _fail_ring imposes.
  RingStatus RingPassMulti(int tier, int nstripes, int n, int rank,
                           const int32_t* lanes, const uint32_t* tag_bases,
                           uint32_t rs_sub, uint32_t ag_sub, int mode, int op,
                           int wire, const uint64_t* chunk_ptrs,
                           const uint64_t* chunk_elems, double timeout_s,
                           std::string* err);

  // Per-lane wire-byte counters of one tier (lane_stats' feed).  Returns
  // the lane count written (0 for an unregistered tier).
  int Counters(int tier, uint64_t* sent, uint64_t* recv, int cap);

  // Shared shaper counters of one tier-direction (LinkShaper.bytes_sent /
  // frames_sent parity for shaped-link byte accounting tests).
  void ShaperCounters(int tier, int direction, uint64_t* bytes, uint64_t* frames);

  // Seconds one tier-direction's pacer actually slept (the "shaping"
  // bucket of obs.report's link_attribution split).
  double ShaperWaitS(int tier, int direction);

  // Mid-run re-shaping of one tier-direction's pacer (the slow-link bench
  // degrades ONE peer link 10x without a reconfigure).  mbps <= 0 disables.
  void SetShaper(int tier, int direction, double mbps, double rtt_ms);

  // Wire bytes moved on one lane link (direction 0 = next/out, 1 = prev/in).
  uint64_t LinkBytes(int tier, int direction, int lane);

  // -- data-plane flight recorder (docs/architecture.md "Data-plane
  // observability") ------------------------------------------------------
  // Bounded per-hop timeline + always-on per-tier stall aggregates.  The
  // aggregates are a handful of atomic adds per hop (microsecond cost
  // against millisecond hops); the timeline ring records every
  // ``sample``-th hop (0 disables the timeline, aggregates stay on) into a
  // fixed ``cap``-slot ring — the bench's healthy control cell pins the
  // recorder's throughput impact under its budget.
  void SetHopRecorder(int sample, int cap);
  // out4 = {hops, send_block_s, recv_wait_s, combine_s} for one tier.
  // Returns 1 when the tier is registered, 0 otherwise (out zeroed).
  int HopStats(int tier, double* out4);
  // Copies up to ``cap_records`` retained hop records, oldest first, as 8
  // doubles each in RingHopRecord field order.  Returns the record count.
  int HopRecords(double* out, int cap_records);

 private:
  struct Tier {
    bool present = false;
    std::vector<std::unique_ptr<RingLink>> next;
    std::vector<std::unique_ptr<RingLink>> prev;
    RingShaper next_shaper;
    RingShaper prev_shaper;
  };

  RingLink* link(int tier, int direction, int lane);
  bool CheckOpEntry(int tier, int lane, std::string* err);
  void SenderLoop(RingLink* l);
  std::shared_ptr<RingSendJob> EnqueueSend(RingLink* l, uint32_t tag,
                                           const uint8_t* a, size_t alen,
                                           const uint8_t* b, size_t blen,
                                           double timeout_s);
  RingStatus WaitSend(const std::shared_ptr<RingSendJob>& job, double timeout_s,
                      std::string* err);
  // Failure-path cleanup: poisons the send link (so the job fails fast)
  // and blocks until the job has released its caller-owned buffers.
  void AbandonSend(RingLink* nl, const std::shared_ptr<RingSendJob>& job,
                   const std::string& why);
  // Receives the frame for `tag` on prev-link `l`.  If dst != nullptr the
  // payload must be exactly dst_len bytes and lands straight in dst (the
  // zero-copy path); otherwise it is returned in *out.
  RingStatus RecvFrame(RingLink* l, uint32_t tag, uint8_t* dst, size_t dst_len,
                       std::string* out, double timeout_s, std::string* err);
  RingStatus ReadPayload(RingLink* l, uint64_t nbytes, uint32_t tag,
                         uint32_t expect_tag, uint8_t* dst, size_t dst_len,
                         std::string* out, double timeout_s, std::string* err);
  // One hop: enqueue the send, receive the same tag, join the send.
  // ``rec`` (optional) is filled with the hop's send/recv wait split and
  // byte count on success — the caller stamps tier/lane/tag/combine and
  // commits it via RecordHop.
  RingStatus Hop(Tier* t, int lane, uint32_t tag, const uint8_t* a, size_t alen,
                 const uint8_t* b, size_t blen, uint8_t* rdst, size_t rlen,
                 double timeout_s, std::string* err,
                 RingHopRecord* rec = nullptr);
  // Folds one completed hop into the per-tier aggregates and (sampled)
  // the bounded timeline ring.
  void RecordHop(const RingHopRecord& rec);

  // Persistent multi-stripe worker pool (RingPassMulti's fan-out).  Long
  // lived so the per-thread codec scratch (thread_local in RingPass)
  // amortizes across ops, like the Python engine's lane executor threads.
  struct MultiBatch;
  void EnsureMultiPool();
  void MultiWorkerLoop();
  void RunBatchClaims(const std::shared_ptr<MultiBatch>& batch);
  std::mutex mw_mu_;
  std::condition_variable mw_cv_;
  std::deque<std::shared_ptr<MultiBatch>> mw_queue_;
  std::vector<std::thread> mw_threads_;
  bool mw_stop_ = false;

  int lanes_;
  double mbps_, rtt_ms_;
  Tier tiers_[kNumTiers];
  // Per-tier stall aggregates (always on; lane_stats' "hops" feed).
  std::atomic<uint64_t> agg_hops_[kNumTiers] = {};
  std::atomic<uint64_t> agg_send_us_[kNumTiers] = {};
  std::atomic<uint64_t> agg_recv_us_[kNumTiers] = {};
  std::atomic<uint64_t> agg_comb_us_[kNumTiers] = {};
  // Sampled bounded hop timeline (lock-light: one short mutex'd append per
  // SAMPLED hop; the hot path pays an atomic increment when sampled out).
  std::atomic<uint64_t> hop_counter_{0};
  std::atomic<int> hop_sample_{1};
  std::mutex hop_mu_;
  std::vector<RingHopRecord> hop_ring_;
  size_t hop_cap_ = 2048;
  size_t hop_next_ = 0;
  std::atomic<bool> closed_{false};
  mutable std::mutex close_mu_;
  // In-flight op count: Close() shuts the sockets down (waking every
  // blocked op), then briefly waits for ops to drain before close()ing the
  // fd numbers, so a racing reader can never touch a recycled fd.
  std::atomic<int> active_ops_{0};
};

}  // namespace tpuft
