// C ABI exposing the coordination core to Python via ctypes.
//
// The role of the reference's PyO3 binding layer (src/lib.rs:710-726), minus
// codegen: requests/responses cross the boundary as serialized protobuf bytes
// which the Python side builds/parses with the generated tpuft_pb2 module.
// ctypes releases the GIL for the duration of every call, matching the
// reference's `py.allow_threads` usage (src/lib.rs:186-200).
#include <cstdlib>
#include <cstring>
#include <string>

#include "lighthouse.h"
#include "manager.h"
#include "ring.h"
#include "store.h"
#include "wire.h"

using namespace tpuft;

namespace {

char* CopyString(const std::string& s) {
  char* out = static_cast<char*>(malloc(s.size() + 1));
  memcpy(out, s.data(), s.size());
  out[s.size()] = '\0';
  return out;
}

void SetErr(char** err, const std::string& msg) {
  if (err) *err = CopyString(msg);
}

}  // namespace

extern "C" {

void tf_free(void* p) { free(p); }

// ---------------------------------------------------------------------------
// Lighthouse server
// ---------------------------------------------------------------------------

void* tf_lighthouse_new(const char* bind, const char* http_bind, uint64_t min_replicas,
                        uint64_t join_timeout_ms, uint64_t quorum_tick_ms,
                        uint64_t heartbeat_timeout_ms, char** err) {
  LighthouseOpt opt;
  opt.bind = bind;
  opt.http_bind = http_bind ? http_bind : "";
  opt.min_replicas = min_replicas;
  opt.join_timeout_ms = join_timeout_ms;
  opt.quorum_tick_ms = quorum_tick_ms;
  opt.heartbeat_timeout_ms = heartbeat_timeout_ms;
  auto* lh = new Lighthouse(opt);
  std::string e;
  if (!lh->Start(&e)) {
    SetErr(err, e);
    delete lh;
    return nullptr;
  }
  return lh;
}

char* tf_lighthouse_address(void* p) { return CopyString(static_cast<Lighthouse*>(p)->address()); }

char* tf_lighthouse_http_address(void* p) {
  return CopyString(static_cast<Lighthouse*>(p)->http_address());
}

int tf_lighthouse_evict(void* p, const char* prefix) {
  return static_cast<Lighthouse*>(p)->EvictReplica(prefix ? prefix : "");
}

int tf_lighthouse_drain(void* p, const char* prefix, int64_t deadline_ms) {
  return static_cast<Lighthouse*>(p)->DrainReplica(prefix ? prefix : "", deadline_ms);
}

// HA role control (docs/wire.md "HA lighthouse"): the Python election
// driver (torchft_tpu/ha) flips the role on every lease transition; the
// serve-time expiry guard lives native-side so a stalled Python thread
// cannot leave an expired leader answering Quorum.
void tf_lighthouse_set_role(void* p, int is_leader, const char* leader_addr,
                            const char* leader_http, int64_t epoch,
                            int64_t lease_expires_ms) {
  static_cast<Lighthouse*>(p)->SetRole(is_leader != 0, leader_addr ? leader_addr : "",
                                       leader_http ? leader_http : "", epoch,
                                       lease_expires_ms);
}

int tf_lighthouse_role(void* p) { return static_cast<Lighthouse*>(p)->Role(); }

int64_t tf_lighthouse_leader_epoch(void* p) {
  return static_cast<Lighthouse*>(p)->LeaderEpoch();
}

// Serialized LighthouseReplicateRequest of the full replicable state; the
// election driver pushes these bytes to each standby (wire method 6).
void tf_lighthouse_snapshot(void* p, uint8_t** buf, size_t* len) {
  std::string s = static_cast<Lighthouse*>(p)->SnapshotState();
  *buf = static_cast<uint8_t*>(malloc(s.size() ? s.size() : 1));
  memcpy(*buf, s.data(), s.size());
  *len = s.size();
}

// Flight-recorder snapshot (newest-first JSON document; limit 0 = all
// retained events).  Same payload as GET /debug/flight.json.
// Slow-link sentinel introspection (in-process tests; the wire surfaces
// are /metrics and /alerts.json).
int tf_lighthouse_link_state(void* p, const char* replica_id) {
  return static_cast<Lighthouse*>(p)->LinkState(replica_id ? replica_id : "");
}

char* tf_lighthouse_flight_json(void* p, uint64_t limit) {
  return CopyString(static_cast<Lighthouse*>(p)->FlightJson(limit));
}

// Federation (docs/wire.md "Federation"): makes this lighthouse a regional
// CHILD reporting digests to the root's address list.  This symbol doubles
// as the Python side's capability probe: a stale libtpuft.so without it
// predates the federation surface and the bindings raise a clear error
// instead of silently running flat.
void tf_lighthouse_set_federation(void* p, const char* region,
                                  const char* root_addrs,
                                  int64_t push_interval_ms) {
  static_cast<Lighthouse*>(p)->SetFederation(region ? region : "",
                                             root_addrs ? root_addrs : "",
                                             push_interval_ms);
}

// Per-instance federation rollup (same payload as GET /regions.json).
char* tf_lighthouse_regions_json(void* p) {
  return CopyString(static_cast<Lighthouse*>(p)->RegionsJson());
}

void tf_lighthouse_shutdown(void* p) { static_cast<Lighthouse*>(p)->Shutdown(); }

void tf_lighthouse_free(void* p) { delete static_cast<Lighthouse*>(p); }

// ---------------------------------------------------------------------------
// Manager server
// ---------------------------------------------------------------------------

void* tf_manager_new(const char* replica_id, const char* lighthouse_addr, const char* bind,
                     const char* store_addr, uint64_t world_size, uint64_t heartbeat_interval_ms,
                     uint64_t connect_timeout_ms, char** err) {
  ManagerOpt opt;
  opt.replica_id = replica_id;
  opt.lighthouse_addr = lighthouse_addr;
  opt.bind = bind;
  opt.store_addr = store_addr ? store_addr : "";
  opt.world_size = world_size;
  opt.heartbeat_interval_ms = heartbeat_interval_ms;
  opt.connect_timeout_ms = connect_timeout_ms;
  auto* m = new ManagerServer(opt);
  std::string e;
  if (!m->Start(&e)) {
    SetErr(err, e);
    delete m;
    return nullptr;
  }
  return m;
}

char* tf_manager_address(void* p) { return CopyString(static_cast<ManagerServer*>(p)->address()); }

void tf_manager_set_status(void* p, int64_t step, const char* state,
                           double step_time_ms_ewma, double step_time_ms_last,
                           double allreduce_gb_per_s, int64_t ec_shards_held,
                           int64_t ec_shard_step, int64_t ec_k,
                           double link_recv_gbps, double link_send_gbps,
                           double link_hop_rtt_ms) {
  static_cast<ManagerServer*>(p)->SetStatus(
      step, state ? state : "", step_time_ms_ewma, step_time_ms_last,
      allreduce_gb_per_s, ec_shards_held, ec_shard_step, ec_k, link_recv_gbps,
      link_send_gbps, link_hop_rtt_ms);
}

// Goodput-ledger push (heartbeat fields 14-16, docs/wire.md "Goodput
// ledger").  This symbol doubles as the Python side's capability probe: a
// stale libtpuft.so without it degrades to status-only heartbeats.
void tf_manager_set_ledger(void* p, double goodput_ratio, double compute_seconds,
                           const double* lost_seconds, int32_t n_causes) {
  static_cast<ManagerServer*>(p)->SetLedger(goodput_ratio, compute_seconds,
                                            lost_seconds, n_causes);
}

// Manager-side flight recorder (no HTTP server on managers — this is the
// only live read path besides the shutdown dump).
char* tf_manager_flight_json(void* p, uint64_t limit) {
  return CopyString(static_cast<ManagerServer*>(p)->FlightJson(limit));
}

void tf_manager_shutdown(void* p) { static_cast<ManagerServer*>(p)->Shutdown(); }

void tf_manager_free(void* p) { delete static_cast<ManagerServer*>(p); }

// ---------------------------------------------------------------------------
// Store server
// ---------------------------------------------------------------------------

void* tf_store_new(const char* bind, char** err) {
  auto* s = new StoreServer(bind);
  std::string e;
  if (!s->Start(&e)) {
    SetErr(err, e);
    delete s;
    return nullptr;
  }
  return s;
}

char* tf_store_address(void* p) { return CopyString(static_cast<StoreServer*>(p)->address()); }

void tf_store_shutdown(void* p) { static_cast<StoreServer*>(p)->Shutdown(); }

void tf_store_free(void* p) { delete static_cast<StoreServer*>(p); }

// ---------------------------------------------------------------------------
// Generic RPC client (lighthouse / manager / store methods alike)
// ---------------------------------------------------------------------------

void* tf_client_new(const char* addr, uint64_t connect_timeout_ms, char** err) {
  auto* c = new RpcClient(addr);
  std::string e;
  if (c->Connect(connect_timeout_ms, &e) != Status::kOk) {
    SetErr(err, e);
    delete c;
    return nullptr;
  }
  return c;
}

// Returns the wire status code; on kOk fills resp/resp_len (malloc'd), else err.
int tf_client_call(void* p, uint16_t method, const uint8_t* req, size_t req_len,
                   uint64_t timeout_ms, uint8_t** resp, size_t* resp_len, char** err) {
  auto* c = static_cast<RpcClient*>(p);
  std::string request(reinterpret_cast<const char*>(req), req_len);
  std::string response, e;
  Status st = c->Call(method, request, timeout_ms, &response, &e);
  if (st == Status::kOk) {
    *resp = static_cast<uint8_t*>(malloc(response.size() ? response.size() : 1));
    memcpy(*resp, response.data(), response.size());
    *resp_len = response.size();
  } else {
    SetErr(err, e.empty() ? StatusName(st) : e);
  }
  return static_cast<int>(st);
}

void tf_client_free(void* p) { delete static_cast<RpcClient*>(p); }

// ---------------------------------------------------------------------------
// Ring engine (GIL-free data plane, native/src/ring.h)
//
// Status codes mirror RingStatus: 0 ok, 1 timeout, 2 peer/engine closed,
// 3 other error — the bindings map them to TimeoutError / ConnectionError /
// RuntimeError.  These symbols double as the Python side's capability
// probe: a libtpuft.so missing tf_ring_new is a stale build and the
// collective logs one warning and runs the Python engine instead.
// ---------------------------------------------------------------------------

void* tf_ring_new(int32_t lanes, double shaper_mbps, double shaper_rtt_ms) {
  return new RingEngine(lanes, shaper_mbps, shaper_rtt_ms);
}

int tf_ring_set_tier(void* p, int32_t tier, int32_t nlanes, const int32_t* next_fds,
                     const int32_t* prev_fds, char** err) {
  std::string e;
  if (!static_cast<RingEngine*>(p)->SetTier(tier, nlanes, next_fds, prev_fds, &e)) {
    SetErr(err, e);
    return 3;
  }
  return 0;
}

void tf_ring_close(void* p) { static_cast<RingEngine*>(p)->Close(); }

int tf_ring_detach(void* p, char** err) {
  std::string e;
  if (!static_cast<RingEngine*>(p)->Detach(&e)) {
    SetErr(err, e);
    return 3;
  }
  return 0;
}

void tf_ring_free(void* p) { delete static_cast<RingEngine*>(p); }

int tf_ring_open_fds(void* p) { return static_cast<RingEngine*>(p)->OpenFds(); }

int tf_ring_exchange(void* p, int32_t tier, int32_t lane, uint32_t tag,
                     const uint8_t* buf, size_t len, uint8_t** out, size_t* out_len,
                     double timeout_s, char** err) {
  std::string recv, e;
  RingStatus st = static_cast<RingEngine*>(p)->Exchange(tier, lane, tag, buf, len,
                                                        &recv, timeout_s, &e);
  if (st != RingStatus::kOk) {
    SetErr(err, e);
    return static_cast<int>(st);
  }
  *out = static_cast<uint8_t*>(malloc(recv.size() ? recv.size() : 1));
  memcpy(*out, recv.data(), recv.size());
  *out_len = recv.size();
  return 0;
}

int tf_ring_pass(void* p, int32_t tier, int32_t lane, int32_t n, int32_t rank,
                 uint32_t tag_base, uint32_t rs_sub, uint32_t ag_sub, int32_t mode,
                 int32_t op, int32_t wire, const uint64_t* chunk_ptrs,
                 const uint64_t* chunk_elems, double timeout_s, char** err) {
  std::string e;
  RingStatus st = static_cast<RingEngine*>(p)->RingPass(
      tier, lane, n, rank, tag_base, rs_sub, ag_sub, mode, op, wire,
      reinterpret_cast<float* const*>(const_cast<uint64_t*>(chunk_ptrs)),
      chunk_elems, timeout_s, &e);
  if (st != RingStatus::kOk) SetErr(err, e);
  return static_cast<int>(st);
}

int tf_ring_pass_multi(void* p, int32_t tier, int32_t nstripes, int32_t n,
                       int32_t rank, const int32_t* lanes,
                       const uint32_t* tag_bases, uint32_t rs_sub,
                       uint32_t ag_sub, int32_t mode, int32_t op, int32_t wire,
                       const uint64_t* chunk_ptrs, const uint64_t* chunk_elems,
                       double timeout_s, char** err) {
  std::string e;
  RingStatus st = static_cast<RingEngine*>(p)->RingPassMulti(
      tier, nstripes, n, rank, lanes, tag_bases, rs_sub, ag_sub, mode, op,
      wire, chunk_ptrs, chunk_elems, timeout_s, &e);
  if (st != RingStatus::kOk) SetErr(err, e);
  return static_cast<int>(st);
}

int tf_ring_set_shm(void* p, int32_t tier, int32_t direction, int32_t lane,
                    const char* path, uint64_t token, char** err) {
  std::string e;
  if (!static_cast<RingEngine*>(p)->SetShm(tier, direction, lane, path, token,
                                           &e)) {
    SetErr(err, e);
    return 3;
  }
  return 0;
}

int tf_ring_counters(void* p, int32_t tier, uint64_t* sent, uint64_t* recv,
                     int32_t cap) {
  return static_cast<RingEngine*>(p)->Counters(tier, sent, recv, cap);
}

void tf_ring_shaper_counters(void* p, int32_t tier, int32_t direction,
                             uint64_t* bytes, uint64_t* frames) {
  static_cast<RingEngine*>(p)->ShaperCounters(tier, direction, bytes, frames);
}

uint64_t tf_ring_link_bytes(void* p, int32_t tier, int32_t direction, int32_t lane) {
  return static_cast<RingEngine*>(p)->LinkBytes(tier, direction, lane);
}

// -- data-plane flight recorder (hop telemetry) -----------------------------
// These symbols double as the Python side's capability probe for the hop
// API: a libtpuft.so missing tf_ring_hop_stats predates the recorder and
// the bindings degrade to Python-side-only hop telemetry.

void tf_ring_set_hop(void* p, int32_t sample, int32_t cap) {
  static_cast<RingEngine*>(p)->SetHopRecorder(sample, cap);
}

int tf_ring_hop_stats(void* p, int32_t tier, double* out4) {
  return static_cast<RingEngine*>(p)->HopStats(tier, out4);
}

int tf_ring_hop_records(void* p, double* out, int32_t cap_records) {
  return static_cast<RingEngine*>(p)->HopRecords(out, cap_records);
}

double tf_ring_shaper_wait_s(void* p, int32_t tier, int32_t direction) {
  return static_cast<RingEngine*>(p)->ShaperWaitS(tier, direction);
}

void tf_ring_set_shaper(void* p, int32_t tier, int32_t direction, double mbps,
                        double rtt_ms) {
  static_cast<RingEngine*>(p)->SetShaper(tier, direction, mbps, rtt_ms);
}

}  // extern "C"
