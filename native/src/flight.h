// Control-plane flight recorder + native latency histograms.
//
// The "always-on, low-overhead, dump-on-demand" black box for the native
// coordination servers (docs/architecture.md "Control-plane observability"):
// a bounded ring buffer of RPC spans and state-transition events that every
// Lighthouse and ManagerServer keeps in memory at all times, readable live
// (GET /debug/flight.json on the lighthouse, a capi accessor everywhere) and
// dumped to a JSON file on server shutdown so a crashed run leaves a
// replayable record of why each quorum formed when it did.
//
// Recording is mutex-light by design: one short lock per event around a
// fixed-slot ring write (strings are moved in, nothing allocates while the
// lock is held beyond the slot's own strings).  Readers serialize the whole
// ring under the same lock — reads are rare (debug endpoint, shutdown dump),
// writes are the hot path.
#pragma once

#include <cstdint>
#include <mutex>
#include <ostream>
#include <string>
#include <utility>
#include <vector>

namespace tpuft {

// Flight-recorder event kinds.  EVERY kind recorded anywhere in the native
// servers must be declared here — tests/test_flight.py greps these
// constants against the Python-side registry
// (torchft_tpu/obs/flight.py FLIGHT_EVENTS), the same grep-pinning
// discipline as the metrics.EVENTS registry.
constexpr char kFlightRpc[] = "rpc";
constexpr char kFlightQuorumFormed[] = "quorum_formed";
constexpr char kFlightReplicaJoin[] = "replica_join";
constexpr char kFlightReplicaEvict[] = "replica_evict";
constexpr char kFlightReplicaDrain[] = "replica_drain";
constexpr char kFlightSentinelTransition[] = "sentinel_transition";
constexpr char kFlightRoleChange[] = "role_change";
constexpr char kFlightQuorumResult[] = "quorum_result";
constexpr char kFlightIncident[] = "incident";
constexpr char kFlightShutdown[] = "shutdown";

// One recorded event.  RPC spans fill method/peer/status/dur_us; state
// events leave them defaulted (dur_us -1 = not a span).
struct FlightEvent {
  int64_t seq = 0;      // monotonically increasing per recorder
  int64_t ts_ms = 0;    // epoch ms at record (= send) time
  int64_t mono_us = 0;  // steady-clock µs at record time (same origin as
                        // dur_us arithmetic; NTP-immune ordering)
  std::string kind;     // one of the kFlight* constants above
  std::string method;   // rpc: wire method name (MethodName, wire.h)
  std::string peer;     // rpc: remote "host:port"
  uint16_t status = 0;  // rpc: wire Status the response carried
  int64_t dur_us = -1;  // rpc: recv -> send handling time in µs
  std::string trace_id; // causal trace id carried by the request, if any
  std::string detail;   // state events: "k=v k=[a,b]" tokens (obs/flight.py
                        // parses these back into dicts)
};

// Bounded, process-lifetime event recorder.  Thread-safe.
//
// TWO rings, not one: RPC spans (kind "rpc") and state transitions
// (everything else) are retained separately.  At O(dozens) of replicas the
// heartbeat span volume alone is hundreds of events per second — a single
// shared ring overwrote every quorum transition within seconds of it
// happening, which destroyed exactly the membership history a
// preemption-wave post-mortem reconstructs (found by the scale sweep's
// 32-group wave cell).  Transitions are rare (membership changes, role
// changes, sentinel moves), so a small dedicated ring holds the full story
// of a long run regardless of RPC traffic.
class FlightRecorder {
 public:
  explicit FlightRecorder(size_t capacity = 2048,
                          size_t transition_capacity = 512);

  // Identity stamped into Json()/dumps ("lighthouse" / "manager") plus a
  // stable instance id (port / replica id).  Set once at server Start.
  void SetIdentity(const std::string& server, const std::string& id);

  void Record(FlightEvent ev);
  // State-transition event.
  void RecordEvent(const char* kind, std::string detail,
                   std::string trace_id = "");
  // Server-side RPC span (kind "rpc").
  void RecordRpc(const char* method, std::string peer, uint16_t status,
                 int64_t dur_us, std::string trace_id);

  // JSON document: {"server","id","capacity","recorded","dropped",
  // "dumped_ts_ms","events":[...]} with events NEWEST-FIRST (spans and
  // transitions merged by seq), at most `limit` of them (0 = all
  // retained).  "capacity" is the combined ring capacity.
  std::string Json(size_t limit = 0) const;

  // Writes Json() to `path` atomically (tmp + rename).  Best-effort:
  // returns false on any I/O failure, never throws — the black box must
  // not be able to fail a shutdown.
  bool DumpToFile(const std::string& path) const;

  // $TPUFT_FLIGHT_DIR/flight_<server>_<sanitized id>.json, or "" when the
  // env knob is unset (dump disabled).
  std::string DumpPathFromEnv() const;

  int64_t recorded() const;

 private:
  mutable std::mutex mu_;
  std::vector<FlightEvent> ring_;        // RPC spans
  std::vector<FlightEvent> trans_ring_;  // state transitions
  size_t capacity_;
  size_t trans_capacity_;
  size_t next_ = 0;        // next span write slot
  size_t trans_next_ = 0;  // next transition write slot
  int64_t seq_ = 0;        // total recorded across both rings
  int64_t span_count_ = 0;
  int64_t trans_count_ = 0;
  std::string server_ = "server";
  std::string id_;
};

// ---------------------------------------------------------------------------
// Fixed-bucket latency histogram (Prometheus exposition)
// ---------------------------------------------------------------------------

// Cumulative-bucket histogram over a fixed bound set (100 µs .. 10 s —
// covers a /metrics render at the bottom and a join_timeout quorum wait at
// the top).  Observe() is lock-cheap (one mutex, index + two adds).
class LatencyHistogram {
 public:
  LatencyHistogram();
  void Observe(double seconds);
  uint64_t count() const;
  // Per-bucket (non-cumulative) counts + sum + count, atomically.
  std::vector<uint64_t> Snapshot(double* sum, uint64_t* count) const;
  // Shared upper bounds in seconds (last implicit bucket is +Inf).
  static const std::vector<double>& Bounds();

 private:
  mutable std::mutex mu_;
  std::vector<uint64_t> counts_;  // Bounds().size() + 1 slots (+Inf last)
  double sum_ = 0.0;
  uint64_t count_ = 0;
};

// Writes one Prometheus histogram family: HELP/TYPE once, then cumulative
// _bucket{...,le="..."} / _sum / _count series per (label, histogram) pair.
// `label` is the inner label text without braces ("method=\"Quorum\"") or
// "" for an unlabelled family.
void ExposeHistogram(
    std::ostream& o, const std::string& name, const std::string& help,
    const std::vector<std::pair<std::string, const LatencyHistogram*>>& series);

// JSON string-value escaping (quotes, backslash, control characters).  The
// ONE escaper for every hand-rolled JSON surface in the native servers
// (/status.json, /alerts.json, /debug/flight.json, dumps) — two private
// copies silently diverging is how one endpoint ships broken JSON for an
// input its sibling handles.
std::string JsonEscape(const std::string& s);

}  // namespace tpuft
