#include "wire.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <random>

#include "retry.h"

namespace tpuft {

namespace {

constexpr uint32_t kMagic = kFrameMagic;

// Read exactly n bytes; honors an absolute poll deadline. Returns false on
// EOF/error/timeout (timed_out set on timeout).
bool ReadFull(int fd, char* buf, size_t n, TimePoint deadline, bool* timed_out) {
  size_t got = 0;
  while (got < n) {
    int timeout = -1;
    if (deadline != TimePoint::max()) {
      auto left = std::chrono::duration_cast<std::chrono::milliseconds>(deadline - Clock::now())
                      .count();
      if (left <= 0) {
        if (timed_out) *timed_out = true;
        return false;
      }
      timeout = static_cast<int>(std::min<int64_t>(left, INT32_MAX));
    }
    struct pollfd pfd = {fd, POLLIN, 0};
    int pr = poll(&pfd, 1, timeout);
    if (pr == 0) continue;  // re-check deadline
    if (pr < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    ssize_t r = recv(fd, buf + got, n - got, 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR || errno == EAGAIN)) continue;
      return false;
    }
    got += static_cast<size_t>(r);
  }
  return true;
}

bool WriteFull(int fd, const char* buf, size_t n) {
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = send(fd, buf + sent, n - sent, MSG_NOSIGNAL);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR || errno == EAGAIN)) continue;
      return false;
    }
    sent += static_cast<size_t>(r);
  }
  return true;
}

bool WriteFrame(int fd, uint16_t method, Status status, uint64_t req_id,
                uint64_t deadline_ms, const std::string& payload) {
  FrameHeader h;
  h.magic = kMagic;
  h.method = method;
  h.status = static_cast<uint16_t>(status);
  h.req_id = req_id;
  h.deadline_ms = deadline_ms;
  h.len = static_cast<uint32_t>(payload.size());
  h.version = kWireVersion;
  h.flags = 0;
  h.reserved = 0;
  std::string buf;
  buf.reserve(sizeof(h) + payload.size());
  buf.append(reinterpret_cast<const char*>(&h), sizeof(h));
  buf.append(payload);
  return WriteFull(fd, buf.data(), buf.size());
}

bool ReadFrame(int fd, FrameHeader* h, std::string* payload, TimePoint deadline,
               bool* timed_out) {
  if (!ReadFull(fd, reinterpret_cast<char*>(h), sizeof(*h), deadline, timed_out)) return false;
  if (h->magic != kMagic) return false;
  if (h->len > (1u << 30)) return false;  // 1 GiB sanity cap
  // Version mismatch: the header itself parsed (the 32-byte layout is
  // frozen across versions), but the payload encoding may not have —
  // DRAIN the payload without interpreting it (leaving it unread would
  // make close() send RST and destroy the rejection reply in flight),
  // then hand the caller an empty payload to reject loudly.
  if (h->version != kWireVersion) {
    char scratch[4096];
    uint64_t left = h->len;
    while (left > 0) {
      size_t chunk = left < sizeof(scratch) ? static_cast<size_t>(left) : sizeof(scratch);
      if (!ReadFull(fd, scratch, chunk, deadline, timed_out)) return false;
      left -= chunk;
    }
    payload->clear();
    return true;
  }
  payload->resize(h->len);
  if (h->len > 0 &&
      !ReadFull(fd, payload->empty() ? nullptr : &(*payload)[0], h->len, deadline, timed_out))
    return false;
  return true;
}

void SetKeepAlive(int fd) {
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
  int idle = 60, intvl = 20, cnt = 3;
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &idle, sizeof(idle));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &intvl, sizeof(intvl));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPCNT, &cnt, sizeof(cnt));
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

}  // namespace

bool ParseAddress(const std::string& addr, SockAddr* out, std::string* err) {
  if (addr.empty()) {
    if (err) *err = "empty address";
    return false;
  }
  if (addr[0] == '[') {
    auto close = addr.find(']');
    if (close == std::string::npos || close + 1 >= addr.size() || addr[close + 1] != ':') {
      if (err) *err = "bad [v6]:port address: " + addr;
      return false;
    }
    out->host = addr.substr(1, close - 1);
    out->port = static_cast<uint16_t>(atoi(addr.c_str() + close + 2));
    return true;
  }
  auto colon = addr.rfind(':');
  if (colon == std::string::npos) {
    if (err) *err = "missing port in address: " + addr;
    return false;
  }
  out->host = addr.substr(0, colon);
  out->port = static_cast<uint16_t>(atoi(addr.c_str() + colon + 1));
  return true;
}

std::string MethodName(uint16_t method) {
  switch (method) {
    case kLighthouseQuorum: return "Quorum";
    case kLighthouseHeartbeat: return "Heartbeat";
    case kLighthouseStatus: return "Status";
    case kLighthouseEvict: return "Evict";
    case kLighthouseDrain: return "Drain";
    case kLighthouseReplicate: return "Replicate";
    case kLighthouseLeaderInfo: return "LeaderInfo";
    case kLighthouseRegionDigest: return "RegionDigest";
    case kLighthouseRegions: return "Regions";
    case kManagerQuorum: return "ManagerQuorum";
    case kManagerCheckpointMetadata: return "CheckpointMetadata";
    case kManagerShouldCommit: return "ShouldCommit";
    case kManagerKill: return "Kill";
    case kStoreSet: return "StoreSet";
    case kStoreGet: return "StoreGet";
    case kStoreAdd: return "StoreAdd";
    case kStoreDelete: return "StoreDelete";
  }
  return "Method" + std::to_string(method);
}

std::string PeerAddress(int fd) {
  struct sockaddr_storage peer = {};
  socklen_t plen = sizeof(peer);
  if (getpeername(fd, reinterpret_cast<struct sockaddr*>(&peer), &plen) != 0) {
    return "";
  }
  char host[NI_MAXHOST], port[NI_MAXSERV];
  if (getnameinfo(reinterpret_cast<struct sockaddr*>(&peer), plen, host,
                  sizeof(host), port, sizeof(port),
                  NI_NUMERICHOST | NI_NUMERICSERV) != 0) {
    return "";
  }
  std::string h(host);
  return (h.find(':') != std::string::npos ? "[" + h + "]" : h) + ":" + port;
}

std::string StatusName(Status s) {
  switch (s) {
    case Status::kOk: return "OK";
    case Status::kCancelled: return "CANCELLED";
    case Status::kUnknown: return "UNKNOWN";
    case Status::kInvalidArgument: return "INVALID_ARGUMENT";
    case Status::kDeadlineExceeded: return "DEADLINE_EXCEEDED";
    case Status::kNotFound: return "NOT_FOUND";
    case Status::kFailedPrecondition: return "FAILED_PRECONDITION";
    case Status::kAborted: return "ABORTED";
    case Status::kInternal: return "INTERNAL";
    case Status::kUnavailable: return "UNAVAILABLE";
  }
  return "UNKNOWN";
}

// ---------------------------------------------------------------------------
// Server
// ---------------------------------------------------------------------------

RpcServer::RpcServer(std::string bind, RpcHandler handler)
    : bind_(std::move(bind)), handler_(std::move(handler)) {}

RpcServer::~RpcServer() { Shutdown(); }

bool RpcServer::Start(std::string* err) {
  SockAddr sa;
  if (!ParseAddress(bind_, &sa, err)) return false;

  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  hints.ai_flags = AI_PASSIVE;
  struct addrinfo* res = nullptr;
  std::string port_str = std::to_string(sa.port);
  const char* node = sa.host.empty() || sa.host == "::" || sa.host == "0.0.0.0"
                         ? nullptr
                         : sa.host.c_str();
  int rc = getaddrinfo(node, port_str.c_str(), &hints, &res);
  if (rc != 0) {
    if (err) *err = std::string("getaddrinfo: ") + gai_strerror(rc);
    return false;
  }
  int fd = -1;
  for (auto* ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    if (ai->ai_family == AF_INET6) {
      int zero = 0;  // dual-stack
      setsockopt(fd, IPPROTO_IPV6, IPV6_V6ONLY, &zero, sizeof(zero));
    }
    if (bind(fd, ai->ai_addr, ai->ai_addrlen) == 0 && listen(fd, 1024) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) {
    if (err) *err = "failed to bind " + bind_ + ": " + strerror(errno);
    return false;
  }
  listen_fd_ = fd;

  struct sockaddr_storage bound = {};
  socklen_t blen = sizeof(bound);
  getsockname(fd, reinterpret_cast<struct sockaddr*>(&bound), &blen);
  if (bound.ss_family == AF_INET6) {
    port_ = ntohs(reinterpret_cast<struct sockaddr_in6*>(&bound)->sin6_port);
  } else {
    port_ = ntohs(reinterpret_cast<struct sockaddr_in*>(&bound)->sin_port);
  }
  // Advertise a connectable host: keep the requested host unless it was a
  // wildcard, in which case use localhost (single-host tests) or the FQDN.
  std::string host = sa.host;
  if (host.empty() || host == "::" || host == "0.0.0.0") {
    char name[256];
    if (gethostname(name, sizeof(name)) == 0) {
      host = name;
    } else {
      host = "localhost";
    }
  }
  address_ = (host.find(':') != std::string::npos ? "[" + host + "]" : host) + ":" +
             std::to_string(port_);

  accept_thread_ = std::thread([this] { AcceptLoop(); });
  return true;
}

void RpcServer::ReapFinishedLocked(std::vector<FinishedConn>* out) {
  out->insert(out->end(), finished_.begin(), finished_.end());
  finished_.clear();
}

void RpcServer::AcceptLoop() {
  while (!shutdown_.load()) {
    // Join connection threads that finished on their own so the set of
    // unjoined threads stays bounded by the live connections.  The fd is
    // closed only AFTER the join — the thread is the fd's user.
    std::vector<FinishedConn> done;
    {
      std::lock_guard<std::mutex> lk(conns_mu_);
      ReapFinishedLocked(&done);
    }
    for (auto& [fd, th] : done) {
      if (th->joinable()) th->join();
      close(fd);
    }
    struct pollfd pfd = {listen_fd_, POLLIN, 0};
    int pr = poll(&pfd, 1, 100);
    if (pr <= 0) continue;
    int cfd = accept(listen_fd_, nullptr, nullptr);
    if (cfd < 0) continue;
    SetKeepAlive(cfd);
    std::lock_guard<std::mutex> lk(conns_mu_);
    if (shutdown_.load()) {
      close(cfd);
      break;
    }
    auto th = std::make_shared<std::thread>([this, cfd] { Serve(cfd); });
    conns_[cfd] = th;
  }
}

void RpcServer::Serve(int fd) {
  // Resolved once per connection (it cannot change mid-stream) and handed
  // to every dispatched frame for the flight recorder's RPC spans.
  const std::string peer = PeerAddress(fd);
  while (!shutdown_.load()) {
    FrameHeader h;
    std::string payload;
    bool timed_out = false;
    if (!ReadFrame(fd, &h, &payload, TimePoint::max(), &timed_out)) break;
    if (h.version != kWireVersion) {
      std::string msg = "wire version mismatch: client v" + std::to_string(h.version) +
                        ", server v" + std::to_string(kWireVersion) + " (see docs/wire.md)";
      WriteFrame(fd, h.method, Status::kFailedPrecondition, h.req_id, 0, msg);
      break;  // close: the payload encoding cannot be trusted
    }
    Deadline dl = Deadline::FromMillis(h.deadline_ms);
    std::string resp;
    Status st;
    try {
      st = handler_(h.method, payload, dl, peer, &resp);
    } catch (const std::exception& e) {
      st = Status::kInternal;
      resp = e.what();
    }
    if (!WriteFrame(fd, h.method, st, h.req_id, 0, resp)) break;
  }
  // The serving thread does NOT close its fd: the reaper that joins this
  // thread (accept loop or Shutdown) closes it afterwards, so no fd
  // number can be recycled while another thread still holds it for a
  // ::shutdown().  Handing the handle over (instead of detaching) is
  // what makes process exit race-free: a detached thread still running
  // this epilogue during static destruction is a crash.  Under shutdown
  // the entry stays in conns_ — Shutdown's snapshot joins and closes it.
  std::lock_guard<std::mutex> lk(conns_mu_);
  if (shutdown_.load()) return;
  auto it = conns_.find(fd);
  if (it != conns_.end()) {
    finished_.emplace_back(fd, it->second);
    conns_.erase(it);
  }
}

void RpcServer::Shutdown() {
  {
    // The flag flip and the map snapshot are one atomic step relative to
    // Serve's epilogue, so every connection thread ends up in exactly one
    // of {conns snapshot, finished_} and gets joined + closed once.
    std::lock_guard<std::mutex> lk(conns_mu_);
    if (shutdown_.exchange(true)) return;
  }
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    close(listen_fd_);
  }
  if (accept_thread_.joinable()) accept_thread_.join();
  std::map<int, std::shared_ptr<std::thread>> conns;
  std::vector<FinishedConn> done;
  {
    std::lock_guard<std::mutex> lk(conns_mu_);
    conns.swap(conns_);
    ReapFinishedLocked(&done);
  }
  for (auto& [fd, th] : conns) {
    ::shutdown(fd, SHUT_RDWR);  // wakes the thread; fd is still open
  }
  for (auto& [fd, th] : conns) {
    if (th->joinable()) th->join();
    close(fd);
  }
  for (auto& [fd, th] : done) {
    if (th->joinable()) th->join();
    close(fd);
  }
}

// ---------------------------------------------------------------------------
// Client
// ---------------------------------------------------------------------------

int DialTcp(const std::string& addr, uint64_t timeout_ms, std::string* err) {
  SockAddr sa;
  if (!ParseAddress(addr, &sa, err)) return -1;
  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_str = std::to_string(sa.port);
  int rc = getaddrinfo(sa.host.c_str(), port_str.c_str(), &hints, &res);
  if (rc != 0) {
    if (err) *err = std::string("getaddrinfo(") + sa.host + "): " + gai_strerror(rc);
    return -1;
  }
  int fd = -1;
  for (auto* ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    // Non-blocking connect with poll so we can honor timeout_ms.
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    int cr = connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (cr != 0 && errno == EINPROGRESS) {
      struct pollfd pfd = {fd, POLLOUT, 0};
      int timeout = timeout_ms == 0 ? -1 : static_cast<int>(timeout_ms);
      if (poll(&pfd, 1, timeout) == 1) {
        int serr = 0;
        socklen_t slen = sizeof(serr);
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &serr, &slen);
        if (serr == 0) cr = 0;
      }
    }
    if (cr == 0) {
      fcntl(fd, F_SETFL, flags);
      SetKeepAlive(fd);
      break;
    }
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0 && err) *err = "failed to connect to " + addr;
  return fd;
}

// ---------------------------------------------------------------------------
// Failover client (HA lighthouse)
// ---------------------------------------------------------------------------

const char kNotLeaderPrefix[] = "not the leader";

bool ParseNotLeader(const std::string& err, std::string* leader_addr) {
  if (err.rfind(kNotLeaderPrefix, 0) != 0) return false;
  if (leader_addr) {
    leader_addr->clear();
    auto pos = err.find("leader=");
    if (pos != std::string::npos) {
      pos += 7;
      auto end = err.find(' ', pos);
      *leader_addr = err.substr(pos, end == std::string::npos ? std::string::npos
                                                              : end - pos);
    }
  }
  return true;
}

std::vector<std::string> SplitAddressList(const std::string& addrs) {
  std::vector<std::string> out;
  size_t start = 0;
  while (start <= addrs.size()) {
    size_t comma = addrs.find(',', start);
    std::string part = addrs.substr(
        start, comma == std::string::npos ? std::string::npos : comma - start);
    // Trim surrounding whitespace.
    size_t b = part.find_first_not_of(" \t");
    size_t e = part.find_last_not_of(" \t");
    if (b != std::string::npos) out.push_back(part.substr(b, e - b + 1));
    if (comma == std::string::npos) break;
    start = comma + 1;
  }
  return out;
}

FailoverRpcClient::FailoverRpcClient(const std::string& addrs)
    : addrs_(SplitAddressList(addrs)) {}

FailoverRpcClient::~FailoverRpcClient() { Close(); }

void FailoverRpcClient::Close() {
  std::lock_guard<std::mutex> lk(mu_);
  for (auto& [addr, c] : clients_) c->Close();
  clients_.clear();
}

std::string FailoverRpcClient::current() {
  std::lock_guard<std::mutex> lk(mu_);
  if (!leader_override_.empty()) return leader_override_;
  return addrs_.empty() ? "" : addrs_[cur_ % addrs_.size()];
}

RpcClient* FailoverRpcClient::ClientForLocked(const std::string& addr) {
  auto it = clients_.find(addr);
  if (it == clients_.end()) {
    it = clients_.emplace(addr, std::make_unique<RpcClient>(addr)).first;
  }
  return it->second.get();
}

Status FailoverRpcClient::Connect(uint64_t connect_timeout_ms, std::string* err) {
  if (addrs_.empty()) {
    if (err) *err = "no lighthouse address configured";
    return Status::kInvalidArgument;
  }
  Deadline dl = Deadline::FromMillis(connect_timeout_ms);
  ExponentialBackoff backoff(50, 1.5, 1000);
  std::string last_err;
  do {
    for (const auto& addr : addrs_) {
      // Short per-address budget so one black-holing address cannot eat
      // the whole window before its siblings are ever tried.
      uint64_t per = std::max<uint64_t>(
          250, std::min<int64_t>(dl.remaining_ms(),
                                 static_cast<int64_t>(connect_timeout_ms /
                                                      (2 * addrs_.size()) + 1)));
      int fd = DialTcp(addr, per, &last_err);
      if (fd >= 0) {
        close(fd);  // reachability probe only; Call() dials its own
        return Status::kOk;
      }
      if (dl.expired()) break;
    }
  } while (backoff.Sleep(dl));
  if (err) {
    std::string joined;
    for (const auto& a : addrs_) {
      if (!joined.empty()) joined += ", ";
      joined += a;
    }
    *err = "no lighthouse reachable at any of [" + joined + "] within " +
           std::to_string(connect_timeout_ms) +
           " ms — check TPUFT_LIGHTHOUSE and that the lighthouse processes "
           "are running (last error: " + last_err + ")";
  }
  return Status::kDeadlineExceeded;
}

Status FailoverRpcClient::Call(uint16_t method, const std::string& req,
                               uint64_t timeout_ms, std::string* resp,
                               std::string* err) {
  if (addrs_.empty()) {
    if (err) *err = "no lighthouse address configured";
    return Status::kInvalidArgument;
  }
  Deadline dl = Deadline::FromMillis(timeout_ms);
  // Cap well under a lease period: during a leader election every address
  // answers "no leader yet", and a sleep that outgrows the election itself
  // (not the rejection round-trips) becomes the failover latency floor.
  // 500 ms of decorrelated jitter still smears an N-group stampede.
  ExponentialBackoff backoff(50, 1.5, 500);
  Status last = Status::kUnavailable;
  std::string last_err;
  bool first_attempt = true;
  int attempts = 0;
  // With no deadline a redirect ping-pong (two confused followers naming
  // each other) must still terminate: bound the sweep instead.
  const int max_attempts_no_deadline = static_cast<int>(2 * addrs_.size() + 4);
  while (first_attempt || !dl.expired()) {
    first_attempt = false;
    if (timeout_ms == 0 && ++attempts > max_attempts_no_deadline) break;
    std::string target;
    RpcClient* client;
    {
      std::lock_guard<std::mutex> lk(mu_);
      target = !leader_override_.empty() ? leader_override_
                                         : addrs_[cur_ % addrs_.size()];
      client = ClientForLocked(target);
    }
    uint64_t attempt_ms = timeout_ms;
    if (timeout_ms > 0) {
      int64_t left = dl.remaining_ms();
      if (left <= 0) break;
      attempt_ms = static_cast<uint64_t>(left);
    }
    std::string e;
    Status st = client->Call(method, req, attempt_ms, resp, &e);
    if (st == Status::kOk) return st;
    last = st;
    last_err = e;
    std::string leader;
    if (st == Status::kUnavailable && ParseNotLeader(e, &leader)) {
      std::lock_guard<std::mutex> lk(mu_);
      if (!leader.empty() && leader != target) {
        // Redirect: jump straight to the named leader (no backoff — the
        // rejection itself proves the service is up and answering).
        leader_override_ = leader;
        continue;
      }
      // A standby that knows no leader yet (election in progress), or the
      // named leader is the one that just rejected us: rotate + back off.
      leader_override_.clear();
      cur_ = (cur_ + 1) % addrs_.size();
    } else if (st == Status::kUnavailable) {
      // Transport-level failure: rotate to the next address.
      std::lock_guard<std::mutex> lk(mu_);
      if (!leader_override_.empty()) {
        leader_override_.clear();  // the learned leader died; re-discover
      } else {
        cur_ = (cur_ + 1) % addrs_.size();
      }
    } else {
      // Application-level statuses (ABORTED "is draining", NOT_FOUND,
      // DEADLINE_EXCEEDED from the server, ...) are not failover events.
      if (err) *err = e;
      return st;
    }
    if (timeout_ms == 0) {
      // No deadline given: a single failover sweep, not an infinite loop.
      bool wrapped;
      {
        std::lock_guard<std::mutex> lk(mu_);
        wrapped = cur_ == 0 && leader_override_.empty();
      }
      if (wrapped) break;
      continue;
    }
    if (!backoff.Sleep(dl)) break;
  }
  if (err) *err = last_err.empty() ? StatusName(last) : last_err;
  return last == Status::kOk ? Status::kUnavailable : last;
}

RpcClient::~RpcClient() { Close(); }

void RpcClient::Close() {
  std::lock_guard<std::mutex> lk(mu_);
  if (fd_ >= 0) {
    ::shutdown(fd_, SHUT_RDWR);
    close(fd_);
    fd_ = -1;
  }
}

Status RpcClient::Connect(uint64_t connect_timeout_ms, std::string* err) {
  std::lock_guard<std::mutex> lk(mu_);
  if (fd_ >= 0) return Status::kOk;
  Deadline dl = Deadline::FromMillis(connect_timeout_ms);
  ExponentialBackoff backoff;
  std::string last_err;
  do {
    int64_t left = dl.remaining_ms();
    int fd = DialTcp(addr_, static_cast<uint64_t>(std::min<int64_t>(left, 10000)), &last_err);
    if (fd >= 0) {
      fd_ = fd;
      return Status::kOk;
    }
  } while (backoff.Sleep(dl));
  if (err) *err = "connect to " + addr_ + " timed out: " + last_err;
  return Status::kDeadlineExceeded;
}

Status RpcClient::Call(uint16_t method, const std::string& req, uint64_t timeout_ms,
                       std::string* resp, std::string* err) {
  std::lock_guard<std::mutex> lk(mu_);
  return CallLocked(method, req, timeout_ms, resp, err);
}

Status RpcClient::CallLocked(uint16_t method, const std::string& req, uint64_t timeout_ms,
                             std::string* resp, std::string* err) {
  if (fd_ < 0) {
    // Lazy reconnect (e.g. after a Close or a broken pipe).
    std::string cerr;
    int fd = DialTcp(addr_, timeout_ms == 0 ? 10000 : timeout_ms, &cerr);
    if (fd < 0) {
      if (err) *err = cerr;
      return Status::kUnavailable;
    }
    fd_ = fd;
  }
  uint64_t req_id = next_req_id_++;
  if (!WriteFrame(fd_, method, Status::kOk, req_id, timeout_ms, req)) {
    close(fd_);
    fd_ = -1;
    if (err) *err = "send failed to " + addr_ + ": " + strerror(errno);
    return Status::kUnavailable;
  }
  TimePoint dl = timeout_ms == 0 ? TimePoint::max()
                                 : Clock::now() + std::chrono::milliseconds(timeout_ms);
  FrameHeader h;
  bool timed_out = false;
  if (!ReadFrame(fd_, &h, resp, dl, &timed_out)) {
    close(fd_);
    fd_ = -1;
    if (timed_out) {
      if (err) *err = "rpc to " + addr_ + " timed out after " + std::to_string(timeout_ms) + "ms";
      return Status::kDeadlineExceeded;
    }
    if (err) *err = "connection to " + addr_ + " lost";
    return Status::kUnavailable;
  }
  if (h.version != kWireVersion) {
    close(fd_);
    fd_ = -1;
    if (err)
      *err = "wire version mismatch: server " + addr_ + " speaks v" +
             std::to_string(h.version) + ", client v" + std::to_string(kWireVersion) +
             " (see docs/wire.md)";
    return Status::kFailedPrecondition;
  }
  Status st = static_cast<Status>(h.status);
  if (st != Status::kOk && err) *err = *resp;
  return st;
}

}  // namespace tpuft
