#include "store.h"

#include "log.h"
#include "tpuft.pb.h"

namespace tpuft {

StoreServer::~StoreServer() { Shutdown(); }

bool StoreServer::Start(std::string* err) {
  server_ = std::make_unique<RpcServer>(
      bind_, [this](uint16_t method, const std::string& req, Deadline dl,
                    const std::string& peer, std::string* resp) {
        (void)peer;  // the store keeps no flight recorder (pure KV hot path)
        return Dispatch(method, req, dl, resp);
      });
  if (!server_->Start(err)) return false;
  LOGD("store listening on %s", server_->address().c_str());
  return true;
}

void StoreServer::Shutdown() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (shutdown_) return;
    shutdown_ = true;
    cv_.notify_all();
  }
  if (server_) server_->Shutdown();
}

std::string StoreServer::address() const { return server_ ? server_->address() : ""; }

Status StoreServer::Dispatch(uint16_t method, const std::string& req, Deadline deadline,
                             std::string* resp) {
  switch (method) {
    case kStoreSet: {
      StoreSetRequest r;
      if (!r.ParseFromString(req)) return Status::kInvalidArgument;
      {
        std::lock_guard<std::mutex> lk(mu_);
        kv_[r.key()] = r.value();
        cv_.notify_all();
      }
      StoreSetResponse out;
      out.SerializeToString(resp);
      return Status::kOk;
    }
    case kStoreGet: {
      StoreGetRequest r;
      if (!r.ParseFromString(req)) return Status::kInvalidArgument;
      StoreGetResponse out;
      std::unique_lock<std::mutex> lk(mu_);
      if (r.wait()) {
        bool ok = cv_.wait_until(lk, deadline.at, [&] {
          return kv_.count(r.key()) > 0 || shutdown_;
        });
        if (shutdown_) {
          *resp = "store shutting down";
          return Status::kUnavailable;
        }
        if (!ok) {
          *resp = "timed out waiting for key " + r.key();
          return Status::kDeadlineExceeded;
        }
      }
      auto it = kv_.find(r.key());
      out.set_found(it != kv_.end());
      if (it != kv_.end()) out.set_value(it->second);
      lk.unlock();
      out.SerializeToString(resp);
      return Status::kOk;
    }
    case kStoreAdd: {
      StoreAddRequest r;
      if (!r.ParseFromString(req)) return Status::kInvalidArgument;
      StoreAddResponse out;
      {
        std::lock_guard<std::mutex> lk(mu_);
        int64_t cur = 0;
        auto it = kv_.find(r.key());
        if (it != kv_.end()) cur = atoll(it->second.c_str());
        cur += r.delta();
        kv_[r.key()] = std::to_string(cur);
        out.set_value(cur);
        cv_.notify_all();
      }
      out.SerializeToString(resp);
      return Status::kOk;
    }
    case kStoreDelete: {
      StoreDeleteRequest r;
      if (!r.ParseFromString(req)) return Status::kInvalidArgument;
      {
        std::lock_guard<std::mutex> lk(mu_);
        kv_.erase(r.key());
      }
      StoreDeleteResponse out;
      out.SerializeToString(resp);
      return Status::kOk;
    }
    default:
      *resp = "unknown store method " + std::to_string(method);
      return Status::kUnknown;
  }
}

}  // namespace tpuft
