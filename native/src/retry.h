// Exponential backoff with jitter, bounded by an overall deadline.
// Reference parity: retry_backoff / ExponentialBackoff, src/retry.rs:6-41.
#pragma once

#include <chrono>
#include <cstdint>
#include <random>
#include <thread>

namespace tpuft {

struct Deadline;  // wire.h

class ExponentialBackoff {
 public:
  ExponentialBackoff(uint64_t initial_ms = 100, double multiplier = 1.5,
                     uint64_t max_ms = 10000, uint64_t jitter_ms = 100)
      : next_ms_(initial_ms), multiplier_(multiplier), max_ms_(max_ms), jitter_ms_(jitter_ms) {}

  // Sleeps for the next backoff interval unless the deadline would be crossed.
  // Returns false when the deadline has fewer ms left than the sleep needs.
  template <typename DeadlineT>
  bool Sleep(const DeadlineT& deadline) {
    uint64_t jitter = jitter_ms_ ? (rng_() % jitter_ms_) : 0;
    uint64_t sleep_ms = next_ms_ + jitter;
    if (static_cast<int64_t>(sleep_ms) >= deadline.remaining_ms()) return false;
    std::this_thread::sleep_for(std::chrono::milliseconds(sleep_ms));
    next_ms_ = static_cast<uint64_t>(next_ms_ * multiplier_);
    if (next_ms_ > max_ms_) next_ms_ = max_ms_;
    return true;
  }

  uint64_t next_ms() const { return next_ms_; }

 private:
  uint64_t next_ms_;
  double multiplier_;
  uint64_t max_ms_;
  uint64_t jitter_ms_;
  std::minstd_rand rng_{std::random_device{}()};
};

}  // namespace tpuft
