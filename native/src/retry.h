// Exponential backoff with DECORRELATED jitter, bounded by an overall
// deadline.  Reference parity: retry_backoff / ExponentialBackoff,
// src/retry.rs:6-41 — extended with the decorrelated-jitter scheme
// (sleep_{k+1} = uniform(initial, 3 * sleep_k), capped): when N replica
// groups lose the same lighthouse at the same instant (a leader SIGKILL),
// plain exponential backoff keeps their retries phase-locked and every
// round slams the new leader simultaneously; decorrelating the sleeps
// spreads the reconnect wave across the whole interval.  The Python
// analogue is torchft_tpu/ha/backoff.py — keep the algorithms in sync.
#pragma once

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <random>
#include <thread>

namespace tpuft {

struct Deadline;  // wire.h

class ExponentialBackoff {
 public:
  ExponentialBackoff(uint64_t initial_ms = 100, double multiplier = 1.5,
                     uint64_t max_ms = 10000, uint64_t jitter_ms = 100)
      : initial_ms_(initial_ms ? initial_ms : 1),
        prev_ms_(initial_ms ? initial_ms : 1),
        next_ms_(initial_ms ? initial_ms : 1),
        multiplier_(multiplier),
        max_ms_(max_ms),
        jitter_(jitter_ms > 0) {}

  // Computes the next decorrelated sleep without sleeping (for callers
  // that wait on a condition variable instead of a bare sleep).
  uint64_t NextSleepMs() {
    uint64_t sleep_ms;
    if (jitter_) {
      // Decorrelated jitter: uniform in [initial, 3 * previous sleep].
      uint64_t hi = std::max<uint64_t>(initial_ms_ + 1, prev_ms_ * 3);
      sleep_ms = initial_ms_ + rng_() % (hi - initial_ms_);
    } else {
      // Jitter disabled: plain bounded exponential (deterministic tests).
      sleep_ms = next_ms_;
    }
    sleep_ms = std::min(sleep_ms, max_ms_);
    prev_ms_ = std::max<uint64_t>(1, sleep_ms);
    next_ms_ = std::min<uint64_t>(max_ms_, static_cast<uint64_t>(next_ms_ * multiplier_));
    return sleep_ms;
  }

  // Sleeps for the next backoff interval unless the deadline would be crossed.
  // Returns false when the deadline has fewer ms left than the sleep needs.
  template <typename DeadlineT>
  bool Sleep(const DeadlineT& deadline) {
    uint64_t sleep_ms = NextSleepMs();
    if (static_cast<int64_t>(sleep_ms) >= deadline.remaining_ms()) return false;
    std::this_thread::sleep_for(std::chrono::milliseconds(sleep_ms));
    return true;
  }

  uint64_t next_ms() const { return next_ms_; }

 private:
  uint64_t initial_ms_;
  uint64_t prev_ms_;
  uint64_t next_ms_;
  double multiplier_;
  uint64_t max_ms_;
  bool jitter_;
  std::minstd_rand rng_{std::random_device{}()};
};

}  // namespace tpuft
