#include "http.h"

#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include "wire.h"

namespace tpuft {

namespace {

// Trims ASCII whitespace from both ends.
std::string Trim(const std::string& s) {
  size_t a = s.find_first_not_of(" \t\r\n");
  if (a == std::string::npos) return "";
  size_t b = s.find_last_not_of(" \t\r\n");
  return s.substr(a, b - a + 1);
}

// Reads until "\r\n\r\n" plus Content-Length body. Very small requests only.
bool ReadRequest(int fd, HttpRequestInfo* req) {
  std::string buf;
  char tmp[4096];
  size_t header_end = std::string::npos;
  while (header_end == std::string::npos) {
    struct pollfd pfd = {fd, POLLIN, 0};
    if (poll(&pfd, 1, 10000) <= 0) return false;
    ssize_t r = recv(fd, tmp, sizeof(tmp), 0);
    if (r <= 0) return false;
    buf.append(tmp, static_cast<size_t>(r));
    if (buf.size() > (1u << 20)) return false;
    header_end = buf.find("\r\n\r\n");
  }
  auto line_end = buf.find("\r\n");
  std::string request_line = buf.substr(0, line_end);
  auto sp1 = request_line.find(' ');
  auto sp2 = request_line.rfind(' ');
  if (sp1 == std::string::npos || sp2 == std::string::npos || sp2 <= sp1) return false;
  req->method = request_line.substr(0, sp1);
  req->path = request_line.substr(sp1 + 1, sp2 - sp1 - 1);

  size_t content_length = 0;
  std::string raw_headers = buf.substr(0, header_end);
  std::string headers = raw_headers;  // lowercased copy for name lookups
  for (char& c : headers) c = static_cast<char>(tolower(c));
  auto cl = headers.find("content-length:");
  if (cl != std::string::npos) {
    content_length = static_cast<size_t>(atoll(headers.c_str() + cl + 15));
    if (content_length > (1u << 20)) return false;
  }
  auto tok = headers.find("x-tpuft-token:");
  if (tok != std::string::npos) {
    auto eol = headers.find("\r\n", tok);
    // Value sliced from the ORIGINAL bytes (same offsets): header NAMES
    // are case-insensitive, but the shared secret's case must survive.
    req->token = Trim(raw_headers.substr(tok + 14, eol - tok - 14));
  }
  std::string have = buf.substr(header_end + 4);
  while (have.size() < content_length) {
    struct pollfd pfd = {fd, POLLIN, 0};
    if (poll(&pfd, 1, 10000) <= 0) return false;
    ssize_t r = recv(fd, tmp, sizeof(tmp), 0);
    if (r <= 0) return false;
    have.append(tmp, static_cast<size_t>(r));
  }
  req->body = have.substr(0, content_length);
  return true;
}

bool PeerIsLoopback(int fd) {
  struct sockaddr_storage peer = {};
  socklen_t plen = sizeof(peer);
  if (getpeername(fd, reinterpret_cast<struct sockaddr*>(&peer), &plen) != 0) return false;
  if (peer.ss_family == AF_INET) {
    auto* a = reinterpret_cast<struct sockaddr_in*>(&peer);
    return (ntohl(a->sin_addr.s_addr) >> 24) == 127;
  }
  if (peer.ss_family == AF_INET6) {
    auto* a = reinterpret_cast<struct sockaddr_in6*>(&peer);
    if (IN6_IS_ADDR_LOOPBACK(&a->sin6_addr)) return true;
    if (IN6_IS_ADDR_V4MAPPED(&a->sin6_addr)) {
      const uint8_t* b = a->sin6_addr.s6_addr;
      return b[12] == 127;
    }
  }
  return false;
}

void WriteResponse(int fd, const HttpResponse& resp) {
  const char* reason = resp.code == 200   ? "OK"
                       : resp.code == 404 ? "Not Found"
                       : resp.code == 307 ? "Temporary Redirect"
                                          : "Error";
  std::string out = "HTTP/1.1 " + std::to_string(resp.code) + " " + reason +
                    "\r\nContent-Type: " + resp.content_type +
                    "\r\nContent-Length: " + std::to_string(resp.body.size()) +
                    (resp.location.empty() ? "" : "\r\nLocation: " + resp.location) +
                    "\r\nConnection: close\r\n\r\n" + resp.body;
  size_t sent = 0;
  while (sent < out.size()) {
    ssize_t r = send(fd, out.data() + sent, out.size() - sent, MSG_NOSIGNAL);
    if (r <= 0) return;
    sent += static_cast<size_t>(r);
  }
}

}  // namespace

HttpServer::HttpServer(std::string bind, HttpHandler handler)
    : bind_(std::move(bind)), handler_(std::move(handler)) {}

HttpServer::~HttpServer() { Shutdown(); }

bool HttpServer::Start(std::string* err) {
  SockAddr sa;
  if (!ParseAddress(bind_, &sa, err)) return false;
  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  hints.ai_flags = AI_PASSIVE;
  struct addrinfo* res = nullptr;
  std::string port_str = std::to_string(sa.port);
  const char* node = sa.host.empty() || sa.host == "::" || sa.host == "0.0.0.0"
                         ? nullptr
                         : sa.host.c_str();
  int rc = getaddrinfo(node, port_str.c_str(), &hints, &res);
  if (rc != 0) {
    if (err) *err = std::string("getaddrinfo: ") + gai_strerror(rc);
    return false;
  }
  int fd = -1;
  for (auto* ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    if (ai->ai_family == AF_INET6) {
      int zero = 0;
      setsockopt(fd, IPPROTO_IPV6, IPV6_V6ONLY, &zero, sizeof(zero));
    }
    if (bind(fd, ai->ai_addr, ai->ai_addrlen) == 0 && listen(fd, 1024) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) {
    if (err) *err = "failed to bind http " + bind_ + ": " + strerror(errno);
    return false;
  }
  listen_fd_ = fd;
  struct sockaddr_storage bound = {};
  socklen_t blen = sizeof(bound);
  getsockname(fd, reinterpret_cast<struct sockaddr*>(&bound), &blen);
  uint16_t port = bound.ss_family == AF_INET6
                      ? ntohs(reinterpret_cast<struct sockaddr_in6*>(&bound)->sin6_port)
                      : ntohs(reinterpret_cast<struct sockaddr_in*>(&bound)->sin_port);
  std::string host = sa.host;
  if (host.empty() || host == "::" || host == "0.0.0.0") {
    char name[256];
    host = gethostname(name, sizeof(name)) == 0 ? name : "localhost";
  }
  address_ = "http://" + (host.find(':') != std::string::npos ? "[" + host + "]" : host) + ":" +
             std::to_string(port);
  accept_thread_ = std::thread([this] { AcceptLoop(); });
  return true;
}

void HttpServer::ReapFinishedLocked(std::vector<FinishedConn>* out) {
  out->insert(out->end(), finished_.begin(), finished_.end());
  finished_.clear();
}

void HttpServer::AcceptLoop() {
  while (!shutdown_.load()) {
    std::vector<FinishedConn> done;
    {
      std::lock_guard<std::mutex> lk(conns_mu_);
      ReapFinishedLocked(&done);
    }
    for (auto& [fd, th] : done) {
      if (th->joinable()) th->join();
      close(fd);
    }
    struct pollfd pfd = {listen_fd_, POLLIN, 0};
    if (poll(&pfd, 1, 100) <= 0) continue;
    int cfd = accept(listen_fd_, nullptr, nullptr);
    if (cfd < 0) continue;
    std::lock_guard<std::mutex> lk(conns_mu_);
    if (shutdown_.load()) {
      close(cfd);
      break;
    }
    conns_[cfd] = std::make_shared<std::thread>([this, cfd] { Serve(cfd); });
  }
}

void HttpServer::Serve(int fd) {
  HttpRequestInfo req;
  req.peer_loopback = PeerIsLoopback(fd);
  if (ReadRequest(fd, &req)) {
    HttpResponse resp;
    try {
      resp = handler_(req);
    } catch (const std::exception& e) {
      resp.code = 500;
      resp.body = e.what();
      resp.content_type = "text/plain";
    }
    WriteResponse(fd, resp);
  }
  // See RpcServer::Serve: the reaper that joins this thread closes the
  // fd afterwards — never the serving thread itself.
  std::lock_guard<std::mutex> lk(conns_mu_);
  if (shutdown_.load()) return;
  auto it = conns_.find(fd);
  if (it != conns_.end()) {
    finished_.emplace_back(fd, it->second);
    conns_.erase(it);
  }
}

void HttpServer::Shutdown() {
  {
    std::lock_guard<std::mutex> lk(conns_mu_);
    if (shutdown_.exchange(true)) return;
  }
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    close(listen_fd_);
  }
  if (accept_thread_.joinable()) accept_thread_.join();
  std::map<int, std::shared_ptr<std::thread>> conns;
  std::vector<FinishedConn> done;
  {
    std::lock_guard<std::mutex> lk(conns_mu_);
    conns.swap(conns_);
    ReapFinishedLocked(&done);
  }
  for (auto& [fd, th] : conns) ::shutdown(fd, SHUT_RDWR);
  for (auto& [fd, th] : conns) {
    if (th->joinable()) th->join();
    close(fd);
  }
  for (auto& [fd, th] : done) {
    if (th->joinable()) th->join();
    close(fd);
  }
}

}  // namespace tpuft
