// Colored stderr logging with TPUFT_LOG level filtering.
// Reference parity: fern logging configured at import, src/lib.rs:670-713.
#pragma once

#include <unistd.h>

#include <chrono>
#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>

namespace tpuft {
namespace logging {

enum Level { kDebug = 0, kInfo = 1, kWarn = 2, kError = 3 };

inline Level MinLevel() {
  static Level lvl = [] {
    const char* e = getenv("TPUFT_LOG");
    if (!e) return kInfo;
    if (!strcasecmp(e, "debug")) return kDebug;
    if (!strcasecmp(e, "warn")) return kWarn;
    if (!strcasecmp(e, "error")) return kError;
    return kInfo;
  }();
  return lvl;
}

inline void Log(Level lvl, const char* fmt, ...) {
  if (lvl < MinLevel()) return;
  static const char* kNames[] = {"DEBUG", "INFO", "WARN", "ERROR"};
  static const char* kColors[] = {"\x1b[90m", "\x1b[32m", "\x1b[33m", "\x1b[31m"};
  auto now = std::chrono::system_clock::now();
  std::time_t t = std::chrono::system_clock::to_time_t(now);
  auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(now.time_since_epoch()).count() %
            1000;
  struct tm tmv;
  localtime_r(&t, &tmv);
  char ts[32];
  strftime(ts, sizeof(ts), "%H:%M:%S", &tmv);
  bool color = isatty(fileno(stderr));
  char body[2048];
  va_list ap;
  va_start(ap, fmt);
  vsnprintf(body, sizeof(body), fmt, ap);
  va_end(ap);
  if (color) {
    fprintf(stderr, "%s[%s.%03d %s tpuft]\x1b[0m %s\n", kColors[lvl], ts, (int)ms, kNames[lvl],
            body);
  } else {
    fprintf(stderr, "[%s.%03d %s tpuft] %s\n", ts, (int)ms, kNames[lvl], body);
  }
}

}  // namespace logging
}  // namespace tpuft

#define LOGD(...) ::tpuft::logging::Log(::tpuft::logging::kDebug, __VA_ARGS__)
#define LOGI(...) ::tpuft::logging::Log(::tpuft::logging::kInfo, __VA_ARGS__)
#define LOGW(...) ::tpuft::logging::Log(::tpuft::logging::kWarn, __VA_ARGS__)
#define LOGE(...) ::tpuft::logging::Log(::tpuft::logging::kError, __VA_ARGS__)
