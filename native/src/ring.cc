// GIL-free ring engine implementation.  See ring.h for the contract; the
// guiding invariant throughout is BITWISE parity with the Python engine in
// collectives.py — identical frame bytes, identical hop order, identical
// codec arithmetic — so the two engines interoperate on one ring and every
// existing parity/commit-protocol test pins this code for free.
#include "ring.h"

#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <unistd.h>

#ifdef __linux__
#include <linux/futex.h>
#include <sys/syscall.h>
#endif

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cfloat>
#include <cstring>
#include <thread>

#if defined(__SSE2__)
#include <emmintrin.h>
#endif

#include "log.h"

namespace tpuft {

namespace {

constexpr size_t kHdrSize = 12;  // struct.Struct("<IQ"): u32 tag, u64 nbytes

double NowS() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Wall-clock (epoch) seconds — hop-record timestamps must time-align with
// the Python side's time.time()-based span/event stream so the Perfetto
// export can put both planes on one timeline.
double NowWallS() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

int ModN(int a, int n) { return ((a % n) + n) % n; }

// Same-host SPSC segment layout (mirrored by collectives._ShmRing; the
// cross-engine contract like the `<IQ` frame header): u64 magic, u64
// generation token, u64 head (producer byte cursor), u64 tail (consumer
// byte cursor), u32 poisoned, u32 consumer-parked flag, u32
// producer-parked flag, then data at kShmHdr.  Cursors are monotonic
// byte counts; the ring is a plain byte stream, so the 12-byte frame
// header + tag demux above it are unchanged between transports.
constexpr uint64_t kShmMagic = 0x746675745f736d68ULL;  // "hms_tuft" LE
constexpr size_t kShmHdr = 64;
constexpr size_t kShmTokenOff = 8;
constexpr size_t kShmHeadOff = 16;
constexpr size_t kShmTailOff = 24;
constexpr size_t kShmPoisonOff = 32;
constexpr size_t kShmConsWaitOff = 40;
constexpr size_t kShmProdWaitOff = 44;

inline std::atomic<uint64_t>* ShmU64(uint8_t* base, size_t off) {
  return reinterpret_cast<std::atomic<uint64_t>*>(base + off);
}

inline std::atomic<uint32_t>* ShmU32(uint8_t* base, size_t off) {
  return reinterpret_cast<std::atomic<uint32_t>*>(base + off);
}

#ifdef __linux__
// Shared (cross-process) futex on the LOW 32 bits of a cursor: any
// advance changes the low word (increments are < the segment capacity,
// far below 2^32), so waiting for "head moved" is FUTEX_WAIT on
// head's low half.  Little-endian only — which is every TPU/x86/arm64
// host this runs on.
inline uint32_t* ShmFutexWord(uint8_t* base, size_t off) {
  return reinterpret_cast<uint32_t*>(base + off);
}

inline void ShmFutexWaitLow(uint8_t* base, size_t off, uint32_t seen,
                            long timeout_ns) {
  struct timespec ts = {0, timeout_ns};
  ::syscall(SYS_futex, ShmFutexWord(base, off), FUTEX_WAIT, seen, &ts,
            nullptr, 0);
}

inline void ShmFutexWake(uint8_t* base, size_t off) {
  ::syscall(SYS_futex, ShmFutexWord(base, off), FUTEX_WAKE, 1, nullptr,
            nullptr, 0);
}
#endif

// Producer/consumer side of the cursor-advance wakeup: after publishing a
// cursor move, wake the peer IF (and only if) it declared itself parked —
// the flag check keeps the fast path syscall-free.  seq_cst fence pairs
// with the waiter's flag-store/cursor-recheck ordering so a wake cannot
// be missed between "peer checked cursor" and "peer parked".
inline void ShmWakePeer(uint8_t* base, size_t cursor_off, size_t flag_off) {
#ifdef __linux__
  std::atomic_thread_fence(std::memory_order_seq_cst);
  // exchange (not load) makes the wake one-shot: a burst of cursor
  // advances while the peer is still coming out of futex_wait fires a
  // single syscall, not one per advance.
  if (ShmU32(base, flag_off)->exchange(0, std::memory_order_seq_cst) != 0) {
    ShmFutexWake(base, cursor_off);
  }
#else
  (void)base; (void)cursor_off; (void)flag_off;
#endif
}

// The one sanctioned writer of RingLink::{dead, dead_reason}: the reason
// lands under dead_mu before dead's release-store, so readers that observe
// dead == true read the (now immutable) reason without a lock.
void PoisonLink(RingLink* l, const std::string& why) {
  std::lock_guard<std::mutex> lk(l->dead_mu);
  if (l->dead.load(std::memory_order_relaxed)) return;
  l->dead_reason = why;
  l->dead.store(true, std::memory_order_release);
  // Cross-process fail-fast: a poisoned shm lane flips the segment flag so
  // the PEER's wait loop bails now instead of waiting out the socket FIN.
  if (l->shm != nullptr) {
    ShmU32(l->shm, kShmPoisonOff)->store(1, std::memory_order_release);
    // A parked peer is waiting on a cursor futex; kick both so the abort
    // is seen now rather than after the 2 ms park timeout.
    ShmWakePeer(l->shm, kShmHeadOff, kShmConsWaitOff);
    ShmWakePeer(l->shm, kShmTailOff, kShmProdWaitOff);
  }
}

void PutHdr(uint8_t* hdr, uint32_t tag, uint64_t nbytes) {
  memcpy(hdr, &tag, 4);
  memcpy(hdr + 4, &nbytes, 8);
}

// f32 -> bfloat16, round-to-nearest-even — the exact ml_dtypes/Eigen RTNE
// cast the Python engine's `.astype(ml_dtypes.bfloat16)` performs, so wire
// bytes match bit for bit.  Branchless (ternary, not early-return) so the
// encode loop auto-vectorizes — the scalar branchy form made the bf16
// wire SLOWER than raw f32 despite moving half the bytes.
inline uint16_t F32ToBf16(float f) {
  uint32_t input;
  memcpy(&input, &f, 4);
  // NaN: quiet, sign preserved (ml_dtypes keeps the sign bit).
  uint16_t nan_out = static_cast<uint16_t>(((input >> 16) & 0x8000u) | 0x7fc0u);
  uint32_t lsb = (input >> 16) & 1u;
  uint16_t rtne = static_cast<uint16_t>((input + 0x7fffu + lsb) >> 16);
  return ((input & 0x7fffffffu) > 0x7f800000u) ? nan_out : rtne;
}

inline float Bf16ToF32(uint16_t h) {
  uint32_t bits = static_cast<uint32_t>(h) << 16;
  float f;
  memcpy(&f, &bits, 4);
  return f;
}

inline float CombineOne(int op, float a, float b) {
  // np.add / np.maximum / np.minimum semantics (NaN-propagating min/max).
  switch (op) {
    case kOpMax:
      if (a != a) return a;
      if (b != b) return b;
      return a > b ? a : b;
    case kOpMin:
      if (a != a) return a;
      if (b != b) return b;
      return a < b ? a : b;
    default:
      return a + b;
  }
}

// collectives.quantize_int8, bit for bit: scale = amax/127 computed in
// double then narrowed to f32 (both the frame header pack and numpy's weak
// scalar promotion narrow the same way); round-to-nearest-even; NaN -> 0,
// inf saturates via the nan_to_num + clip pair.  Int4Scale/Int4Encode are
// the amax/7 nibble-packed twins (collectives.quantize_int4).
inline float AbsMax(const float* x, size_t n, int* has_nan_out) {
  float amax = 0.0f;
  int has_nan = 0;
  size_t i = 0;
#if defined(__SSE2__)
  // GCC 10 won't if-convert the mixed float/int reduction, so the SIMD
  // form is spelled out: NaN lanes are masked to 0 before the max (maxps
  // would otherwise propagate the NaN) and recorded separately — numpy's
  // np.max propagates NaN, and a NaN amax means scale 1.0 below, so the
  // two forms agree on every input.
  __m128 vamax = _mm_setzero_ps();
  __m128 vnan = _mm_setzero_ps();
  const __m128 abs_mask = _mm_castsi128_ps(_mm_set1_epi32(0x7fffffff));
  for (; i + 4 <= n; i += 4) {
    __m128 a = _mm_and_ps(_mm_loadu_ps(x + i), abs_mask);
    __m128 ord = _mm_cmpord_ps(a, a);
    vnan = _mm_or_ps(vnan, _mm_cmpunord_ps(a, a));
    vamax = _mm_max_ps(vamax, _mm_and_ps(a, ord));
  }
  float lanes[4];
  _mm_storeu_ps(lanes, vamax);
  for (float l : lanes) amax = (l > amax) ? l : amax;
  has_nan = _mm_movemask_ps(vnan) != 0;
#endif
  for (; i < n; ++i) {
    float a = std::fabs(x[i]);
    has_nan |= (a != a);
    amax = (a > amax) ? a : amax;
  }
  *has_nan_out = has_nan;
  return amax;
}

inline float Int8Scale(const float* x, size_t n) {
  int has_nan = 0;
  float amax = AbsMax(x, n, &has_nan);
  if (has_nan || !(amax > 0.0f) || !std::isfinite(amax)) return 1.0f;
  return static_cast<float>(static_cast<double>(amax) / 127.0);
}

inline float Int4Scale(const float* x, size_t n) {
  int has_nan = 0;
  float amax = AbsMax(x, n, &has_nan);
  if (has_nan || !(amax > 0.0f) || !std::isfinite(amax)) return 1.0f;
  return static_cast<float>(static_cast<double>(amax) / 7.0);
}

inline void Int8Encode(const float* x, size_t n, uint8_t* dst) {
  float s = Int8Scale(x, n);
  memcpy(dst, &s, 4);
  int8_t* q = reinterpret_cast<int8_t*>(dst + 4);
  // Same arithmetic as quantize_int8's nan_to_num + rint + clip chain,
  // restructured as clamp-then-round: the clamp bounds are integers, so
  // rint(clamp(v)) == clip(rint(v)), an inf clamps to +/-127 exactly like
  // the FLT_MAX + clip pair, and the ordered-mask AND zeroes NaN.
  size_t i = 0;
#if defined(__SSE2__)
  // Hand-rolled because GCC 10 keeps the select chain as branches.
  // cvtps2dq rounds per MXCSR — round-to-nearest-even by default, the
  // same mode std::rint and np.rint use, so lanes match the scalar tail
  // bit for bit; packs saturation never fires (values already clamped).
  const __m128 vs = _mm_set1_ps(s);
  const __m128 hi = _mm_set1_ps(127.0f);
  const __m128 lo = _mm_set1_ps(-127.0f);
  for (; i + 16 <= n; i += 16) {
    __m128i iv[4];
    for (int k = 0; k < 4; ++k) {
      __m128 v = _mm_div_ps(_mm_loadu_ps(x + i + 4 * k), vs);
      v = _mm_and_ps(v, _mm_cmpord_ps(v, v));  // NaN -> 0
      v = _mm_min_ps(v, hi);
      v = _mm_max_ps(v, lo);
      iv[k] = _mm_cvtps_epi32(v);
    }
    __m128i w0 = _mm_packs_epi32(iv[0], iv[1]);
    __m128i w1 = _mm_packs_epi32(iv[2], iv[3]);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(q + i), _mm_packs_epi16(w0, w1));
  }
#endif
  for (; i < n; ++i) {
    float v = x[i] / s;
    v = (v != v) ? 0.0f : v;
    v = v > 127.0f ? 127.0f : v;
    v = v < -127.0f ? -127.0f : v;
    q[i] = static_cast<int8_t>(std::rint(v));
  }
}

// Nibble-packed 4-bit frame: 4-byte f32 scale, then ceil(n/2) bytes with
// element 2i in the low nibble and 2i+1 in the high nibble, two's
// complement in [-7, 7].  Same clamp-then-round equivalence as Int8Encode.
inline void Int4Encode(const float* x, size_t n, uint8_t* dst) {
  float s = Int4Scale(x, n);
  memcpy(dst, &s, 4);
  uint8_t* q = dst + 4;
  auto quant = [&](size_t i) -> int {
    float v = x[i] / s;
    v = (v != v) ? 0.0f : v;
    v = v > 7.0f ? 7.0f : v;
    v = v < -7.0f ? -7.0f : v;
    return static_cast<int>(std::rint(v));
  };
  size_t pairs = n / 2;
  for (size_t i = 0; i < pairs; ++i) {
    q[i] = static_cast<uint8_t>((quant(2 * i) & 0xF) |
                                ((quant(2 * i + 1) & 0xF) << 4));
  }
  if (n & 1) q[pairs] = static_cast<uint8_t>(quant(n - 1) & 0xF);
}

// Sign-extends one packed nibble (index parity picks the half).
inline float Int4Deq(const uint8_t* q, uint64_t i, float s) {
  uint8_t b = q[i >> 1];
  int nib = (i & 1) ? (b >> 4) : (b & 0xF);
  return static_cast<float>((nib ^ 8) - 8) * s;
}

}  // namespace

// ---------------------------------------------------------------------------
// Shaper — LinkShaper's shared virtual-time serialization budget.
// ---------------------------------------------------------------------------

void RingShaper::OnSend(size_t nbytes) {
  bytes_sent += nbytes;
  frames_sent += 1;
  if (!enabled) return;
  double wake;
  {
    std::lock_guard<std::mutex> lk(mu);
    double now = NowS();
    double start = std::max(now, busy_until_s);
    busy_until_s = start + static_cast<double>(nbytes) / bytes_per_s;
    wake = busy_until_s + half_rtt_s;
  }
  // Sliced sleep: a multi-MB frame at single-digit modeled Mbps pays tens
  // of seconds here, and Close() must not have to wait that out before it
  // can safely recycle fd numbers — the pacer is the one blocking state
  // the socket shutdown cannot interrupt.
  double t0 = NowS();
  for (double remaining = wake - NowS(); remaining > 0;
       remaining = wake - NowS()) {
    if (closed != nullptr && closed->load()) break;
    std::this_thread::sleep_for(
        std::chrono::duration<double>(std::min(remaining, 0.05)));
  }
  double slept = NowS() - t0;
  if (slept > 0) {
    wait_us.fetch_add(static_cast<uint64_t>(slept * 1e6),
                      std::memory_order_relaxed);
  }
}

void RingShaper::SetRate(double mbps, double rtt_ms) {
  std::lock_guard<std::mutex> lk(mu);
  if (mbps > 0) {
    enabled = true;
    bytes_per_s = mbps * 1e6 / 8.0;
    half_rtt_s = rtt_ms / 2000.0;
  } else {
    enabled = false;
  }
}

// ---------------------------------------------------------------------------
// Send jobs + sender threads
// ---------------------------------------------------------------------------

struct RingSendJob {
  uint8_t hdr[kHdrSize];
  const uint8_t* a = nullptr;  // caller-owned; stable until the job is done
  size_t alen = 0;
  const uint8_t* b = nullptr;
  size_t blen = 0;
  double timeout_s = 0;
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  RingStatus status = RingStatus::kOk;
  std::string err;

  void Finish(RingStatus st, const std::string& e) {
    std::lock_guard<std::mutex> lk(mu);
    status = st;
    err = e;
    done = true;
    cv.notify_all();
  }
};

namespace {

// One wait slice of a blocked shm producer/consumer: cheap flag checks on
// every call, then (past the spin budget) a deadline check plus a socket
// liveness probe — the TCP connection carries no frames on an shm lane, so
// readability is either EOF (peer process gone: SIGKILL's only signal) or
// a protocol violation.  A local shutdown() (Close/_fail_ring) flips
// l->dead first, so aborts wake blocked shm ops exactly like tcp ones.
// For the CONSUMER, peer-death signals (poison, EOF) only fail once the
// ring is drained: the producer's final frames land in the ring before its
// close poisons the segment, exactly like bytes sitting in a closed TCP
// socket's buffer — the peer re-checks availability before dying.
RingStatus ShmWaitSlice(RingLink* l, int* spins, double deadline,
                        std::string* err, bool consumer) {
  auto drainable = [l, consumer]() {
    return consumer &&
           ShmU64(l->shm, kShmHeadOff)->load(std::memory_order_acquire) !=
               ShmU64(l->shm, kShmTailOff)->load(std::memory_order_relaxed);
  };
  if (l->dead.load(std::memory_order_acquire)) {
    *err = l->dead_reason.empty() ? "peer connection closed" : l->dead_reason;
    return RingStatus::kClosed;
  }
  if (ShmU32(l->shm, kShmPoisonOff)->load(std::memory_order_acquire) != 0) {
    if (drainable()) return RingStatus::kOk;
    *err = "shm segment poisoned by peer";
    return RingStatus::kClosed;
  }
  if (++*spins < 512) {
    std::this_thread::yield();
    return RingStatus::kOk;
  }
  *spins = 0;
  if (NowS() >= deadline) {
    *err = "shm ring timed out";
    return RingStatus::kTimeout;
  }
  struct pollfd p = {l->fd, POLLIN, 0};
  int pr = ::poll(&p, 1, 0);
  if (pr > 0 && (p.revents & (POLLIN | POLLERR | POLLHUP)) != 0) {
    char c;
    ssize_t r = ::recv(l->fd, &c, 1, MSG_DONTWAIT | MSG_PEEK);
    if (r == 0) {
      if (drainable()) return RingStatus::kOk;
      *err = "peer connection closed";
      return RingStatus::kClosed;
    }
    if (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
      if (drainable()) return RingStatus::kOk;
      *err = std::string("peer connection closed: ") + strerror(errno);
      return RingStatus::kClosed;
    }
    if (r > 0) {
      *err = "unexpected socket data on shm lane";
      return RingStatus::kError;
    }
  }
#ifdef __linux__
  // Park on the peer-advanced cursor instead of burning the scheduler:
  // the consumer sleeps until head moves, the producer until tail moves.
  // Dekker-style handshake with ShmWakePeer — flag store and condition
  // re-check are seq_cst-fenced so either the waker sees our parked flag
  // or we see its cursor advance; the kernel's FUTEX_WAIT value check
  // closes the capture-to-sleep gap.  The 2 ms timeout bounds latency
  // against peers that never futex_wake (the Python engine's _ShmRing,
  // or a dead peer whose EOF the next liveness poll catches).
  const size_t watch_off = consumer ? kShmHeadOff : kShmTailOff;
  const size_t flag_off = consumer ? kShmConsWaitOff : kShmProdWaitOff;
  std::atomic<uint32_t>* flag = ShmU32(l->shm, flag_off);
  flag->store(1, std::memory_order_seq_cst);
  std::atomic_thread_fence(std::memory_order_seq_cst);
  const uint32_t seen = static_cast<uint32_t>(
      ShmU64(l->shm, watch_off)->load(std::memory_order_relaxed));
  const uint64_t h = ShmU64(l->shm, kShmHeadOff)->load(std::memory_order_acquire);
  const uint64_t t = ShmU64(l->shm, kShmTailOff)->load(std::memory_order_acquire);
  const bool ready = consumer ? (h != t)
                              : (static_cast<size_t>(h - t) < l->shm_cap);
  const bool poisoned =
      ShmU32(l->shm, kShmPoisonOff)->load(std::memory_order_acquire) != 0 ||
      l->dead.load(std::memory_order_acquire);
  if (!ready && !poisoned) {
    ShmFutexWaitLow(l->shm, watch_off, seen, 2 * 1000 * 1000);
  }
  flag->store(0, std::memory_order_release);
#else
  std::this_thread::sleep_for(std::chrono::microseconds(20));
#endif
  return RingStatus::kOk;
}

// Producer side: copies the iovec set into the SPSC byte ring (wrap-aware,
// partial writes allowed — frames larger than the segment flow in pieces),
// refreshing the progress deadline on every advance like the socket path.
RingStatus ShmWriteAll(RingLink* l, struct iovec* iov, int iovcnt,
                       double timeout_s, std::string* err) {
  std::atomic<uint64_t>* head = ShmU64(l->shm, kShmHeadOff);
  std::atomic<uint64_t>* tail = ShmU64(l->shm, kShmTailOff);
  uint8_t* data = l->shm + kShmHdr;
  const size_t cap = l->shm_cap;
  double deadline = NowS() + timeout_s;
  int spins = 0;
  for (int idx = 0; idx < iovcnt; ++idx) {
    const uint8_t* src = static_cast<const uint8_t*>(iov[idx].iov_base);
    size_t left = iov[idx].iov_len;
    while (left > 0) {
      uint64_t h = head->load(std::memory_order_relaxed);
      uint64_t t = tail->load(std::memory_order_acquire);
      size_t free_b = cap - static_cast<size_t>(h - t);
      if (free_b == 0) {
        RingStatus st = ShmWaitSlice(l, &spins, deadline, err, false);
        if (st != RingStatus::kOk) return st;
        continue;
      }
      size_t nwr = std::min(left, free_b);
      size_t pos = static_cast<size_t>(h % cap);
      size_t first = std::min(nwr, cap - pos);
      memcpy(data + pos, src, first);
      memcpy(data, src + first, nwr - first);
      head->store(h + nwr, std::memory_order_release);
      ShmWakePeer(l->shm, kShmHeadOff, kShmConsWaitOff);
      src += nwr;
      left -= nwr;
      l->bytes += static_cast<uint64_t>(nwr);
      deadline = NowS() + timeout_s;
      spins = 0;
    }
  }
  return RingStatus::kOk;
}

// Consumer side of the SPSC byte ring.
RingStatus ShmReadExact(RingLink* l, uint8_t* dst, size_t n, double timeout_s,
                        std::string* err, size_t* got_out = nullptr) {
  std::atomic<uint64_t>* head = ShmU64(l->shm, kShmHeadOff);
  std::atomic<uint64_t>* tail = ShmU64(l->shm, kShmTailOff);
  uint8_t* data = l->shm + kShmHdr;
  const size_t cap = l->shm_cap;
  double deadline = NowS() + timeout_s;
  int spins = 0;
  size_t got = 0;
  while (got < n) {
    uint64_t t = tail->load(std::memory_order_relaxed);
    uint64_t h = head->load(std::memory_order_acquire);
    size_t avail = static_cast<size_t>(h - t);
    if (avail == 0) {
      RingStatus st = ShmWaitSlice(l, &spins, deadline, err, true);
      if (st != RingStatus::kOk) {
        if (got_out) *got_out = got;
        return st;
      }
      continue;
    }
    size_t nrd = std::min(n - got, avail);
    size_t pos = static_cast<size_t>(t % cap);
    size_t first = std::min(nrd, cap - pos);
    memcpy(dst + got, data + pos, first);
    memcpy(dst + got + first, data, nrd - first);
    tail->store(t + nrd, std::memory_order_release);
    ShmWakePeer(l->shm, kShmTailOff, kShmProdWaitOff);
    got += nrd;
    l->bytes += static_cast<uint64_t>(nrd);
    deadline = NowS() + timeout_s;
    spins = 0;
  }
  if (got_out) *got_out = got;
  return RingStatus::kOk;
}

// Writes the full iovec set with MSG_DONTWAIT + poll, refreshing the
// progress deadline on every advance (the Python socket-timeout model).
RingStatus WriteAll(RingLink* l, struct iovec* iov, int iovcnt, double timeout_s,
                    std::string* err) {
  if (l->shm != nullptr) return ShmWriteAll(l, iov, iovcnt, timeout_s, err);
  double deadline = NowS() + timeout_s;
  int idx = 0;
  while (idx < iovcnt) {
    if (iov[idx].iov_len == 0) {
      ++idx;
      continue;
    }
    struct msghdr msg;
    memset(&msg, 0, sizeof(msg));
    msg.msg_iov = iov + idx;
    msg.msg_iovlen = static_cast<size_t>(iovcnt - idx);
    ssize_t r = ::sendmsg(l->fd, &msg, MSG_DONTWAIT | MSG_NOSIGNAL);
    if (r > 0) {
      l->bytes += static_cast<uint64_t>(r);
      size_t left = static_cast<size_t>(r);
      while (left > 0 && idx < iovcnt) {
        if (left >= iov[idx].iov_len) {
          left -= iov[idx].iov_len;
          iov[idx].iov_len = 0;
          ++idx;
        } else {
          iov[idx].iov_base = static_cast<uint8_t*>(iov[idx].iov_base) + left;
          iov[idx].iov_len -= left;
          left = 0;
        }
      }
      deadline = NowS() + timeout_s;
      continue;
    }
    if (r < 0 && errno == EINTR) continue;
    if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      double left_s = deadline - NowS();
      if (left_s <= 0) {
        *err = "send timed out";
        return RingStatus::kTimeout;
      }
      struct pollfd p = {l->fd, POLLOUT, 0};
      int pr = ::poll(&p, 1, static_cast<int>(std::min(left_s * 1000.0, 1e8)));
      if (pr < 0 && errno != EINTR) {
        *err = std::string("poll: ") + strerror(errno);
        return RingStatus::kError;
      }
      continue;
    }
    if (r < 0 && (errno == EPIPE || errno == ECONNRESET || errno == EBADF ||
                  errno == ENOTCONN)) {
      *err = std::string("peer connection closed: ") + strerror(errno);
      return RingStatus::kClosed;
    }
    *err = std::string("send: ") + strerror(errno);
    return RingStatus::kError;
  }
  return RingStatus::kOk;
}

RingStatus ReadExact(RingLink* l, uint8_t* dst, size_t n, double timeout_s,
                     std::string* err, size_t* got_out = nullptr) {
  if (l->shm != nullptr) return ShmReadExact(l, dst, n, timeout_s, err, got_out);
  double deadline = NowS() + timeout_s;
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(l->fd, dst + got, n - got, MSG_DONTWAIT);
    if (r > 0) {
      got += static_cast<size_t>(r);
      l->bytes += static_cast<uint64_t>(r);
      deadline = NowS() + timeout_s;
      continue;
    }
    if (r == 0) {
      if (got_out) *got_out = got;
      *err = "peer connection closed";
      return RingStatus::kClosed;
    }
    if (errno == EINTR) continue;
    if (errno == EAGAIN || errno == EWOULDBLOCK) {
      double left_s = deadline - NowS();
      if (left_s <= 0) {
        if (got_out) *got_out = got;
        *err = "recv timed out";
        return RingStatus::kTimeout;
      }
      struct pollfd p = {l->fd, POLLIN, 0};
      int pr = ::poll(&p, 1, static_cast<int>(std::min(left_s * 1000.0, 1e8)));
      if (pr < 0 && errno != EINTR) {
        if (got_out) *got_out = got;
        *err = std::string("poll: ") + strerror(errno);
        return RingStatus::kError;
      }
      continue;
    }
    if (got_out) *got_out = got;
    if (errno == ECONNRESET || errno == EBADF || errno == ENOTCONN) {
      *err = std::string("peer connection closed: ") + strerror(errno);
      return RingStatus::kClosed;
    }
    *err = std::string("recv: ") + strerror(errno);
    return RingStatus::kError;
  }
  if (got_out) *got_out = got;
  return RingStatus::kOk;
}

}  // namespace

void RingEngine::SenderLoop(RingLink* l) {
  for (;;) {
    std::shared_ptr<RingSendJob> job;
    {
      std::unique_lock<std::mutex> lk(l->qmu);
      l->qcv.wait(lk, [&] { return l->stop || !l->queue.empty(); });
      if (l->queue.empty()) return;  // stop && drained
      job = l->queue.front();
      l->queue.pop_front();
    }
    if (l->dead.load() || closed_.load()) {
      job->Finish(RingStatus::kClosed,
                  l->dead_reason.empty() ? "ring engine closed" : l->dead_reason);
      continue;
    }
    size_t total = kHdrSize + job->alen + job->blen;
    if (l->shaper) l->shaper->OnSend(total);
    struct iovec iov[3];
    iov[0].iov_base = job->hdr;
    iov[0].iov_len = kHdrSize;
    iov[1].iov_base = const_cast<uint8_t*>(job->a);
    iov[1].iov_len = job->alen;
    iov[2].iov_base = const_cast<uint8_t*>(job->b);
    iov[2].iov_len = job->blen;
    std::string err;
    RingStatus st = WriteAll(l, iov, 3, job->timeout_s, &err);
    if (st != RingStatus::kOk) PoisonLink(l, err);
    job->Finish(st, err);
  }
}

std::shared_ptr<RingSendJob> RingEngine::EnqueueSend(RingLink* l, uint32_t tag,
                                                     const uint8_t* a, size_t alen,
                                                     const uint8_t* b, size_t blen,
                                                     double timeout_s) {
  auto job = std::make_shared<RingSendJob>();
  PutHdr(job->hdr, tag, static_cast<uint64_t>(alen + blen));
  job->a = a;
  job->alen = alen;
  job->b = b;
  job->blen = blen;
  job->timeout_s = timeout_s;
  {
    std::lock_guard<std::mutex> lk(l->qmu);
    if (l->stop) {
      job->Finish(RingStatus::kClosed, "ring engine closed");
      return job;
    }
    l->queue.push_back(job);
  }
  l->qcv.notify_one();
  return job;
}

RingStatus RingEngine::WaitSend(const std::shared_ptr<RingSendJob>& job,
                                double timeout_s, std::string* err) {
  std::unique_lock<std::mutex> lk(job->mu);
  if (!job->cv.wait_for(lk, std::chrono::duration<double>(timeout_s),
                        [&] { return job->done; })) {
    *err = "send timed out waiting for lane sender";
    return RingStatus::kTimeout;
  }
  if (job->status != RingStatus::kOk) *err = job->err;
  return job->status;
}

void RingEngine::AbandonSend(RingLink* nl,
                             const std::shared_ptr<RingSendJob>& job,
                             const std::string& why) {
  // The job holds raw pointers into caller-owned buffers (the op's stack
  // scratch, or Python bytes alive only for the ctypes call), so an op
  // CANNOT return while its send is still queued or in flight.  Poison
  // the link — shutdown() makes a mid-write sendmsg fail immediately and
  // SenderLoop fails queued jobs on the dead flag — then the wait is
  // bounded in practice (Close() finishes queued jobs the same way).
  PoisonLink(nl, why.empty() ? "ring op abandoned" : why);
  if (nl->fd >= 0) ::shutdown(nl->fd, SHUT_RDWR);
  std::unique_lock<std::mutex> lk(job->mu);
  job->cv.wait(lk, [&] { return job->done; });
}

// ---------------------------------------------------------------------------
// Demux (leader/follower reader, PR 8's design natively)
// ---------------------------------------------------------------------------

RingStatus RingEngine::ReadPayload(RingLink* l, uint64_t nbytes, uint32_t tag,
                                   uint32_t expect_tag, uint8_t* dst,
                                   size_t dst_len, std::string* out,
                                   double timeout_s, std::string* err) {
  if (tag == expect_tag) {
    if (dst != nullptr) {
      if (nbytes != dst_len) {
        *err = "frame length mismatch for tag";
        return RingStatus::kError;
      }
      return ReadExact(l, dst, dst_len, timeout_s, err);
    }
    out->resize(nbytes);
    return ReadExact(l, reinterpret_cast<uint8_t*>(out->empty() ? nullptr : &(*out)[0]),
                     nbytes, timeout_s, err);
  }
  // Someone else's frame: stash it and notify so its waiter takes it
  // without queuing behind this leader's next blocking read.
  std::string stashed;
  stashed.resize(nbytes);
  RingStatus st = ReadExact(
      l, reinterpret_cast<uint8_t*>(stashed.empty() ? nullptr : &stashed[0]),
      nbytes, timeout_s, err);
  if (st != RingStatus::kOk) return st;
  {
    std::lock_guard<std::mutex> lk(l->rmu);
    l->stash[tag].push_back(std::move(stashed));
  }
  l->rcv.notify_all();
  return RingStatus::kOk;
}

RingStatus RingEngine::RecvFrame(RingLink* l, uint32_t tag, uint8_t* dst,
                                 size_t dst_len, std::string* out,
                                 double timeout_s, std::string* err) {
  {
    std::unique_lock<std::mutex> lk(l->rmu);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                        std::chrono::duration<double>(timeout_s));
    for (;;) {
      auto it = l->stash.find(tag);
      if (it != l->stash.end() && !it->second.empty()) {
        std::string payload = std::move(it->second.front());
        it->second.pop_front();
        if (it->second.empty()) l->stash.erase(it);
        if (dst != nullptr) {
          if (payload.size() != dst_len) {
            *err = "frame length mismatch for tag";
            return RingStatus::kError;
          }
          memcpy(dst, payload.data(), payload.size());
        } else {
          *out = std::move(payload);
        }
        return RingStatus::kOk;
      }
      if (l->dead.load()) {
        *err = l->dead_reason.empty() ? "peer connection closed" : l->dead_reason;
        return RingStatus::kClosed;
      }
      if (!l->reading) {
        l->reading = true;
        break;
      }
      if (l->rcv.wait_until(lk, deadline) == std::cv_status::timeout) {
        *err = "recv timed out waiting for demux leader";
        return RingStatus::kTimeout;
      }
    }
  }
  // We are the leader on this socket.
  RingStatus st = RingStatus::kOk;
  bool got_ours = false;
  while (!got_ours) {
    uint8_t hdr[kHdrSize];
    size_t got = 0;
    st = ReadExact(l, hdr, kHdrSize, timeout_s, err, &got);
    if (st != RingStatus::kOk) {
      // A clean timeout at a frame boundary leaves the stream intact (the
      // Python engine's per-recv socket timeout behaves the same); any
      // other failure — or a mid-frame timeout — poisons the link.
      if (!(st == RingStatus::kTimeout && got == 0)) PoisonLink(l, *err);
      break;
    }
    uint32_t ftag;
    uint64_t nbytes;
    memcpy(&ftag, hdr, 4);
    memcpy(&nbytes, hdr + 4, 8);
    st = ReadPayload(l, nbytes, ftag, tag, dst, dst_len, out, timeout_s, err);
    if (st != RingStatus::kOk) {
      PoisonLink(l, *err);
      break;
    }
    got_ours = (ftag == tag);
  }
  {
    std::lock_guard<std::mutex> lk(l->rmu);
    l->reading = false;
  }
  l->rcv.notify_all();
  return st;
}

// ---------------------------------------------------------------------------
// Engine lifecycle
// ---------------------------------------------------------------------------

RingEngine::RingEngine(int lanes, double shaper_mbps, double shaper_rtt_ms)
    : lanes_(lanes), mbps_(shaper_mbps), rtt_ms_(shaper_rtt_ms) {}

RingEngine::~RingEngine() { Close(); }

bool RingEngine::SetTier(int tier, int nlanes, const int32_t* next_fds,
                         const int32_t* prev_fds, std::string* err) {
  if (tier < 0 || tier >= kNumTiers) {
    *err = "bad tier";
    return false;
  }
  if (closed_.load()) {
    *err = "ring engine closed";
    return false;
  }
  Tier* t = &tiers_[tier];
  if (t->present) {
    *err = "tier already registered";
    return false;
  }
  auto init_shaper = [&](RingShaper* s) {
    s->closed = &closed_;
    if (mbps_ > 0) {
      s->enabled = true;
      s->bytes_per_s = mbps_ * 1e6 / 8.0;
      s->half_rtt_s = rtt_ms_ / 2000.0;
    }
  };
  init_shaper(&t->next_shaper);
  init_shaper(&t->prev_shaper);
  for (int i = 0; i < nlanes; ++i) {
    for (int dir = 0; dir < 2; ++dir) {
      int fd = ::dup(dir == kDirNext ? next_fds[i] : prev_fds[i]);
      if (fd < 0) {
        *err = std::string("dup: ") + strerror(errno);
        // Unwind this call's partial registration: stop + JOIN the sender
        // threads already spawned (destroying a RingLink with a joinable
        // thread is std::terminate), then close the dup'd fds.
        for (auto& l : t->next) {
          {
            std::lock_guard<std::mutex> qlk(l->qmu);
            l->stop = true;
          }
          l->qcv.notify_all();
          if (l->sender.joinable()) l->sender.join();
          if (l->fd >= 0) ::close(l->fd);
        }
        for (auto& l : t->prev) {
          if (l->fd >= 0) ::close(l->fd);
        }
        t->next.clear();
        t->prev.clear();
        return false;
      }
      auto link = std::make_unique<RingLink>();
      link->fd = fd;
      link->shaper = dir == kDirNext ? &t->next_shaper : &t->prev_shaper;
      if (dir == kDirNext) {
        RingLink* raw = link.get();
        link->sender = std::thread([this, raw] { SenderLoop(raw); });
        t->next.push_back(std::move(link));
      } else {
        t->prev.push_back(std::move(link));
      }
    }
  }
  t->present = true;
  return true;
}

bool RingEngine::SetShm(int tier, int direction, int lane, const char* path,
                        uint64_t token, std::string* err) {
  if (closed_.load()) {
    *err = "ring engine closed";
    return false;
  }
  RingLink* l = link(tier, direction, lane);
  if (l == nullptr) {
    *err = "no such tier/lane";
    return false;
  }
  if (l->shm != nullptr) {
    *err = "shm already attached";
    return false;
  }
  // Plain open of the /dev/shm path (the Python side created it there —
  // same file shm_open names, without the librt dependency).
  int fd = ::open(path, O_RDWR | O_CLOEXEC);
  if (fd < 0) {
    *err = std::string("shm open: ") + strerror(errno);
    return false;
  }
  struct stat st;
  if (::fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) <= kShmHdr) {
    ::close(fd);
    *err = "shm segment truncated";
    return false;
  }
  size_t len = static_cast<size_t>(st.st_size);
  void* m = ::mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (m == MAP_FAILED) {
    *err = std::string("shm mmap: ") + strerror(errno);
    return false;
  }
  uint8_t* base = static_cast<uint8_t*>(m);
  // Generation guard: only the segment minted for THIS rendezvous (magic +
  // negotiated token) is ever attached — a dead peer's stale segment has a
  // different token and is refused here.
  if (ShmU64(base, 0)->load(std::memory_order_acquire) != kShmMagic ||
      ShmU64(base, kShmTokenOff)->load(std::memory_order_acquire) != token) {
    ::munmap(m, len);
    *err = "stale shm segment (generation mismatch)";
    return false;
  }
  l->shm = base;
  l->shm_len = len;
  l->shm_cap = len - kShmHdr;
  return true;
}

void RingEngine::Close() {
  std::lock_guard<std::mutex> lk(close_mu_);
  if (closed_.exchange(true)) {
    // Already closed; nothing left to do (idempotent).
    return;
  }
  // Phase 1: shut the sockets down (wakes every blocked op on both ends)
  // and stop the senders.  The fd numbers stay valid through the drain so
  // a racing reader can never touch a recycled descriptor.
  for (auto& t : tiers_) {
    if (!t.present) continue;
    for (auto& l : t.next) {
      {
        std::lock_guard<std::mutex> qlk(l->qmu);
        l->stop = true;
        for (auto& job : l->queue) {
          job->Finish(RingStatus::kClosed, "ring engine closed");
        }
        l->queue.clear();
      }
      l->qcv.notify_all();
      PoisonLink(l.get(), "ring engine closed");
      if (l->fd >= 0) ::shutdown(l->fd, SHUT_RDWR);
    }
    for (auto& l : t.prev) {
      PoisonLink(l.get(), "ring engine closed");
      if (l->fd >= 0) ::shutdown(l->fd, SHUT_RDWR);
      l->rcv.notify_all();
    }
  }
  // Multi-stripe pool: poisoned links make in-flight batch stripes fail
  // fast, so the join is bounded like the sender joins below.
  {
    std::lock_guard<std::mutex> mlk(mw_mu_);
    mw_stop_ = true;
    mw_queue_.clear();  // callers complete their batches inline
  }
  mw_cv_.notify_all();
  for (auto& th : mw_threads_) {
    if (th.joinable()) th.join();
  }
  mw_threads_.clear();
  // Phase 2: wait (bounded) for in-flight ops to drain, join senders,
  // close the dup'd fds.
  double deadline = NowS() + 2.0;
  while (active_ops_.load() > 0 && NowS() < deadline) {
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
  }
  for (auto& t : tiers_) {
    if (!t.present) continue;
    for (auto& l : t.next) {
      if (l->sender.joinable()) l->sender.join();
      if (l->fd >= 0) {
        ::close(l->fd);
        l->fd = -1;
      }
    }
    for (auto& l : t.prev) {
      if (l->fd >= 0) {
        ::close(l->fd);
        l->fd = -1;
      }
    }
  }
  // Unmap shm segments only once the op drain succeeded — a straggler op
  // past the deadline keeps its (leaked) mapping rather than faulting.
  if (active_ops_.load() == 0) {
    for (auto& t : tiers_) {
      if (!t.present) continue;
      for (auto& l : t.next) {
        if (l->shm != nullptr) {
          ::munmap(l->shm, l->shm_len);
          l->shm = nullptr;
        }
      }
      for (auto& l : t.prev) {
        if (l->shm != nullptr) {
          ::munmap(l->shm, l->shm_len);
          l->shm = nullptr;
        }
      }
    }
  }
}

bool RingEngine::Detach(std::string* err) {
  std::lock_guard<std::mutex> lk(close_mu_);
  if (closed_.load()) {
    *err = "ring engine already closed";
    return false;
  }
  // Fence new op entries first (CheckOpEntry reads closed_), then require
  // quiescence.  A racing op that slipped past the fence shows up in
  // active_ops_ within its first instruction; the bounded wait below
  // rides that out.  If ops genuinely are in flight the caller's
  // incremental reconfigure was wrong to try — degrade to the Close()
  // semantics (sockets shut down, full-path rebuild) and report failure.
  closed_.store(true);
  double deadline = NowS() + 0.5;
  while (active_ops_.load() > 0 && NowS() < deadline) {
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  // Stop the sender threads and the multi-stripe pool.  Quiescent links
  // have empty queues; any stragglers are failed with kClosed exactly as
  // Close() does.
  for (auto& t : tiers_) {
    if (!t.present) continue;
    for (auto& l : t.next) {
      {
        std::lock_guard<std::mutex> qlk(l->qmu);
        l->stop = true;
        for (auto& job : l->queue) {
          job->Finish(RingStatus::kClosed, "ring engine detached");
        }
        l->queue.clear();
      }
      l->qcv.notify_all();
    }
  }
  {
    std::lock_guard<std::mutex> mlk(mw_mu_);
    mw_stop_ = true;
    mw_queue_.clear();
  }
  mw_cv_.notify_all();
  for (auto& th : mw_threads_) {
    if (th.joinable()) th.join();
  }
  mw_threads_.clear();
  bool quiescent = active_ops_.load() == 0;
  if (!quiescent) {
    // A straggler op slipped in: degrade to the Close() contract — wake
    // it with shutdown (the shared sockets are sacrificed; the Python
    // side sees dead lanes and takes the full-rendezvous path), drain
    // with fd numbers still valid, THEN close.
    for (auto& t : tiers_) {
      if (!t.present) continue;
      for (auto& l : t.next) {
        PoisonLink(l.get(), "ring engine detached");
        if (l->fd >= 0) ::shutdown(l->fd, SHUT_RDWR);
      }
      for (auto& l : t.prev) {
        PoisonLink(l.get(), "ring engine detached");
        if (l->fd >= 0) ::shutdown(l->fd, SHUT_RDWR);
        l->rcv.notify_all();
      }
    }
    double drain = NowS() + 2.0;
    while (active_ops_.load() > 0 && NowS() < drain) {
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
  }
  bool drained = active_ops_.load() == 0;
  for (auto& t : tiers_) {
    if (!t.present) continue;
    for (auto& l : t.next) {
      if (l->sender.joinable()) l->sender.join();
      if (l->fd >= 0) {
        ::close(l->fd);
        l->fd = -1;
      }
      if (drained && l->shm != nullptr) {
        ::munmap(l->shm, l->shm_len);
        l->shm = nullptr;
      }
    }
    for (auto& l : t.prev) {
      if (l->fd >= 0) {
        ::close(l->fd);
        l->fd = -1;
      }
      if (drained && l->shm != nullptr) {
        ::munmap(l->shm, l->shm_len);
        l->shm = nullptr;
      }
      l->rcv.notify_all();
    }
  }
  if (!quiescent) {
    *err = "ops in flight during detach";
    return false;
  }
  return true;
}

int RingEngine::OpenFds() const {
  int n = 0;
  for (const auto& t : tiers_) {
    if (!t.present) continue;
    for (const auto& l : t.next) {
      if (l->fd >= 0) ++n;
    }
    for (const auto& l : t.prev) {
      if (l->fd >= 0) ++n;
    }
  }
  return n;
}

RingLink* RingEngine::link(int tier, int direction, int lane) {
  if (tier < 0 || tier >= kNumTiers || !tiers_[tier].present) return nullptr;
  auto& v = direction == kDirNext ? tiers_[tier].next : tiers_[tier].prev;
  if (lane < 0 || lane >= static_cast<int>(v.size())) return nullptr;
  return v[static_cast<size_t>(lane)].get();
}

bool RingEngine::CheckOpEntry(int tier, int lane, std::string* err) {
  if (closed_.load()) {
    *err = "ring engine closed";
    return false;
  }
  if (link(tier, kDirNext, lane) == nullptr || link(tier, kDirPrev, lane) == nullptr) {
    *err = "no such tier/lane";
    return false;
  }
  return true;
}

namespace {
// RAII in-flight guard so Close() can drain before closing fd numbers.
struct OpGuard {
  std::atomic<int>* c;
  explicit OpGuard(std::atomic<int>* counter) : c(counter) { ++*c; }
  ~OpGuard() { --*c; }
};
}  // namespace

// ---------------------------------------------------------------------------
// Ops
// ---------------------------------------------------------------------------

RingStatus RingEngine::Hop(Tier* t, int lane, uint32_t tag, const uint8_t* a,
                           size_t alen, const uint8_t* b, size_t blen,
                           uint8_t* rdst, size_t rlen, double timeout_s,
                           std::string* err, RingHopRecord* rec) {
  // Zero-length frames are real traffic (a striped pass over a payload
  // smaller than the stripe count produces empty chunks — the Python
  // engine frames them as header-only too), but rdst may then be a null
  // vector-data pointer; RecvFrame treats a null dst as "return via
  // string", so give the empty frame a real landing address.
  uint8_t zero = 0;
  if (rdst == nullptr && rlen == 0) rdst = &zero;
  RingLink* nl = t->next[static_cast<size_t>(lane)].get();
  RingLink* pl = t->prev[static_cast<size_t>(lane)].get();
  if (rec != nullptr) rec->ts = NowWallS();
  auto job = EnqueueSend(nl, tag, a, alen, b, blen, timeout_s);
  double t_recv = NowS();
  std::string recv_err;
  RingStatus rst = RecvFrame(pl, tag, rdst, rlen, nullptr, timeout_s, &recv_err);
  if (rst != RingStatus::kOk) {
    // The op is failing; the send may be stuck behind a full socket with
    // no reader.  Never return with the job holding our buffers.
    AbandonSend(nl, job, recv_err);
    *err = recv_err;
    return rst;
  }
  double t_send = NowS();
  std::string send_err;
  RingStatus sst = WaitSend(job, timeout_s, &send_err);
  if (sst == RingStatus::kTimeout) AbandonSend(nl, job, send_err);
  if (sst != RingStatus::kOk) {
    *err = send_err;
    return sst;
  }
  if (rec != nullptr) {
    rec->recv_s = t_send - t_recv;
    rec->send_s = NowS() - t_send;
    rec->nbytes = alen + blen;
  }
  return RingStatus::kOk;
}

void RingEngine::RecordHop(const RingHopRecord& rec) {
  int tier = rec.tier;
  if (tier < 0 || tier >= kNumTiers) return;
  agg_hops_[tier].fetch_add(1, std::memory_order_relaxed);
  agg_send_us_[tier].fetch_add(static_cast<uint64_t>(rec.send_s * 1e6),
                               std::memory_order_relaxed);
  agg_recv_us_[tier].fetch_add(static_cast<uint64_t>(rec.recv_s * 1e6),
                               std::memory_order_relaxed);
  agg_comb_us_[tier].fetch_add(static_cast<uint64_t>(rec.comb_s * 1e6),
                               std::memory_order_relaxed);
  int sample = hop_sample_.load(std::memory_order_relaxed);
  if (sample <= 0) return;  // aggregates only
  uint64_t n = hop_counter_.fetch_add(1, std::memory_order_relaxed);
  if (n % static_cast<uint64_t>(sample) != 0) return;
  std::lock_guard<std::mutex> lk(hop_mu_);
  if (hop_ring_.size() < hop_cap_) {
    hop_ring_.push_back(rec);
    hop_next_ = hop_ring_.size() % hop_cap_;
  } else {
    hop_ring_[hop_next_] = rec;
    hop_next_ = (hop_next_ + 1) % hop_cap_;
  }
}

void RingEngine::SetHopRecorder(int sample, int cap) {
  hop_sample_.store(sample, std::memory_order_relaxed);
  std::lock_guard<std::mutex> lk(hop_mu_);
  if (cap > 0 && static_cast<size_t>(cap) != hop_cap_) {
    hop_cap_ = static_cast<size_t>(cap);
    hop_ring_.clear();
    hop_next_ = 0;
  }
}

int RingEngine::HopStats(int tier, double* out4) {
  out4[0] = out4[1] = out4[2] = out4[3] = 0;
  if (tier < 0 || tier >= kNumTiers || !tiers_[tier].present) return 0;
  out4[0] = static_cast<double>(agg_hops_[tier].load(std::memory_order_relaxed));
  out4[1] = agg_send_us_[tier].load(std::memory_order_relaxed) / 1e6;
  out4[2] = agg_recv_us_[tier].load(std::memory_order_relaxed) / 1e6;
  out4[3] = agg_comb_us_[tier].load(std::memory_order_relaxed) / 1e6;
  return 1;
}

int RingEngine::HopRecords(double* out, int cap_records) {
  std::lock_guard<std::mutex> lk(hop_mu_);
  size_t n = hop_ring_.size();
  size_t take = std::min(n, static_cast<size_t>(cap_records));
  // Oldest first: when the ring has wrapped, the oldest retained record
  // sits at hop_next_.
  size_t start = (n < hop_cap_) ? 0 : hop_next_;
  size_t skip = n - take;
  for (size_t i = 0; i < take; ++i) {
    const RingHopRecord& r = hop_ring_[(start + skip + i) % n];
    double* o = out + i * 8;
    o[0] = r.ts;
    o[1] = r.tier;
    o[2] = r.lane;
    o[3] = r.tag;
    o[4] = r.send_s;
    o[5] = r.recv_s;
    o[6] = r.comb_s;
    o[7] = static_cast<double>(r.nbytes);
  }
  return static_cast<int>(take);
}

RingStatus RingEngine::Exchange(int tier, int lane, uint32_t tag,
                                const uint8_t* buf, size_t len, std::string* out,
                                double timeout_s, std::string* err) {
  if (!CheckOpEntry(tier, lane, err)) {
    return closed_.load() ? RingStatus::kClosed : RingStatus::kError;
  }
  OpGuard guard(&active_ops_);
  Tier* t = &tiers_[tier];
  RingLink* nl = t->next[static_cast<size_t>(lane)].get();
  RingLink* pl = t->prev[static_cast<size_t>(lane)].get();
  auto job = EnqueueSend(nl, tag, buf, len, nullptr, 0, timeout_s);
  std::string recv_err;
  RingStatus rst = RecvFrame(pl, tag, nullptr, 0, out, timeout_s, &recv_err);
  if (rst != RingStatus::kOk) {
    // `buf` is Python-owned bytes alive only for this ctypes call — the
    // send job must release it before we return (see AbandonSend).
    AbandonSend(nl, job, recv_err);
    *err = recv_err;
    return rst;
  }
  std::string send_err;
  RingStatus sst = WaitSend(job, timeout_s, &send_err);
  if (sst == RingStatus::kTimeout) AbandonSend(nl, job, send_err);
  if (sst != RingStatus::kOk) {
    *err = send_err;
    return sst;
  }
  return RingStatus::kOk;
}

RingStatus RingEngine::RingPass(int tier, int lane, int n, int rank,
                                uint32_t tag_base, uint32_t rs_sub,
                                uint32_t ag_sub, int mode, int op, int wire,
                                float* const* chunk_ptrs,
                                const uint64_t* chunk_elems, double timeout_s,
                                std::string* err) {
  if (!CheckOpEntry(tier, lane, err)) {
    return closed_.load() ? RingStatus::kClosed : RingStatus::kError;
  }
  if (n < 1) {
    *err = "bad ring size";
    return RingStatus::kError;
  }
  OpGuard guard(&active_ops_);
  Tier* t = &tiers_[tier];

  auto enc_len = [&](uint64_t elems) -> size_t {
    switch (wire) {
      case kWireBf16:
        return static_cast<size_t>(elems) * 2;
      case kWireInt8:
        return 4 + static_cast<size_t>(elems);
      case kWireInt4:
        return 4 + (static_cast<size_t>(elems) + 1) / 2;
      default:
        return static_cast<size_t>(elems) * 4;
    }
  };
  size_t max_enc = 0;
  for (int i = 0; i < n; ++i) max_enc = std::max(max_enc, enc_len(chunk_elems[i]));

  // Encode into `dst` (wire != raw only); returns the frame length.
  auto encode = [&](const float* src, uint64_t elems, uint8_t* dst) -> size_t {
    if (wire == kWireBf16) {
      uint16_t* o = reinterpret_cast<uint16_t*>(dst);
      for (uint64_t i = 0; i < elems; ++i) o[i] = F32ToBf16(src[i]);
      return static_cast<size_t>(elems) * 2;
    }
    if (wire == kWireInt4) {
      Int4Encode(src, static_cast<size_t>(elems), dst);
      return 4 + (static_cast<size_t>(elems) + 1) / 2;
    }
    Int8Encode(src, static_cast<size_t>(elems), dst);
    return 4 + static_cast<size_t>(elems);
  };
  // decode(raw) elementwise, combined into dst (dst = combine(dst, in)).
  // kOpSum (the data plane's op — "avg" divides in Python) gets explicit
  // plain-add loops: the runtime `op` switch inside the generic loop
  // defeats the vectorizer, and the sum path is where every gradient
  // byte goes.
  auto decode_combine = [&](const uint8_t* raw, uint64_t elems, float* dst) {
    if (wire == kWireBf16) {
      const uint16_t* in = reinterpret_cast<const uint16_t*>(raw);
      if (op == kOpSum) {
        for (uint64_t i = 0; i < elems; ++i) dst[i] += Bf16ToF32(in[i]);
      } else {
        for (uint64_t i = 0; i < elems; ++i) {
          dst[i] = CombineOne(op, dst[i], Bf16ToF32(in[i]));
        }
      }
    } else if (wire == kWireInt8) {
      float s;
      memcpy(&s, raw, 4);
      const int8_t* q = reinterpret_cast<const int8_t*>(raw + 4);
      if (op == kOpSum) {
        for (uint64_t i = 0; i < elems; ++i) {
          dst[i] += static_cast<float>(q[i]) * s;
        }
      } else {
        for (uint64_t i = 0; i < elems; ++i) {
          dst[i] = CombineOne(op, dst[i], static_cast<float>(q[i]) * s);
        }
      }
    } else if (wire == kWireInt4) {
      float s;
      memcpy(&s, raw, 4);
      const uint8_t* q = raw + 4;
      if (op == kOpSum) {
        for (uint64_t i = 0; i < elems; ++i) dst[i] += Int4Deq(q, i, s);
      } else {
        for (uint64_t i = 0; i < elems; ++i) {
          dst[i] = CombineOne(op, dst[i], Int4Deq(q, i, s));
        }
      }
    } else {
      const float* in = reinterpret_cast<const float*>(raw);
      if (op == kOpSum) {
        for (uint64_t i = 0; i < elems; ++i) dst[i] += in[i];
      } else {
        for (uint64_t i = 0; i < elems; ++i) {
          dst[i] = CombineOne(op, dst[i], in[i]);
        }
      }
    }
  };
  auto decode_assign = [&](const uint8_t* raw, uint64_t elems, float* dst) {
    if (wire == kWireBf16) {
      const uint16_t* in = reinterpret_cast<const uint16_t*>(raw);
      for (uint64_t i = 0; i < elems; ++i) dst[i] = Bf16ToF32(in[i]);
    } else if (wire == kWireInt4) {
      float s;
      memcpy(&s, raw, 4);
      const uint8_t* q = raw + 4;
      for (uint64_t i = 0; i < elems; ++i) dst[i] = Int4Deq(q, i, s);
    } else {
      float s;
      memcpy(&s, raw, 4);
      const int8_t* q = reinterpret_cast<const int8_t*>(raw + 4);
      for (uint64_t i = 0; i < elems; ++i) dst[i] = static_cast<float>(q[i]) * s;
    }
  };

  // Per-thread persistent scratch: RingPass runs on the collective's
  // long-lived per-lane worker threads, and a fresh vector here would pay
  // mmap + page-fault + zero-fill for multi-MB scratch on EVERY pass (the
  // allocator mmaps anything past ~128KB).  Grown monotonically, touched
  // once, reused for the thread's lifetime.
  auto grow = [](std::vector<uint8_t>* v, size_t n) -> uint8_t* {
    if (v->size() < n) v->resize(n);
    return v->data();
  };
  static thread_local std::vector<uint8_t> sendbuf_tl, recvbuf_tl;
  uint8_t* recvbuf = grow(&recvbuf_tl, max_enc);
  uint8_t* sendbuf = wire != kWireRaw ? grow(&sendbuf_tl, max_enc) : nullptr;
  RingStatus st = RingStatus::kOk;

  if (mode != kPassAllgather) {
    // Reduce-scatter: after n-1 hops chunk (rank+1)%n holds the full
    // reduction on this rank.  Hop order and combine order are the Python
    // engine's, so f32 sums reassociate identically.
    uint32_t tag = tag_base + rs_sub;
    for (int step = 0; step < n - 1; ++step) {
      int send_idx = ModN(rank - step, n);
      int recv_idx = ModN(rank - step - 1, n);
      uint64_t selems = chunk_elems[send_idx];
      uint64_t relems = chunk_elems[recv_idx];
      RingHopRecord rec;
      rec.tier = tier;
      rec.lane = lane;
      rec.tag = tag;
      if (wire == kWireRaw) {
        st = Hop(t, lane, tag,
                 reinterpret_cast<const uint8_t*>(chunk_ptrs[send_idx]),
                 static_cast<size_t>(selems) * 4, nullptr, 0, recvbuf,
                 static_cast<size_t>(relems) * 4, timeout_s, err, &rec);
        if (st != RingStatus::kOk) return st;
        double t_comb = NowS();
        decode_combine(recvbuf, relems, chunk_ptrs[recv_idx]);
        rec.comb_s = NowS() - t_comb;
      } else {
        size_t slen = encode(chunk_ptrs[send_idx], selems, sendbuf);
        st = Hop(t, lane, tag, sendbuf, slen, nullptr, 0, recvbuf,
                 enc_len(relems), timeout_s, err, &rec);
        if (st != RingStatus::kOk) return st;
        double t_comb = NowS();
        decode_combine(recvbuf, relems, chunk_ptrs[recv_idx]);
        rec.comb_s = NowS() - t_comb;
      }
      RecordHop(rec);
    }
  }

  if (mode == kPassReduceScatter) return RingStatus::kOk;

  // Allgather circulation: each rank owns chunk (rank+1)%n.  With a wire
  // codec the owner encodes ONCE and every rank forwards the received wire
  // bytes untouched (replica consistency: all ranks decode identical
  // values, including the owner decoding its own encode — requantization
  // is part of the contract).  Raw frames land straight in the destination
  // chunk views: no stash, no reassembly copies.
  uint32_t tag = tag_base + ag_sub;
  if (wire == kWireRaw) {
    for (int step = 0; step < n - 1; ++step) {
      int send_idx = ModN(rank - step + 1, n);
      int recv_idx = ModN(rank - step, n);
      RingHopRecord rec;
      rec.tier = tier;
      rec.lane = lane;
      rec.tag = tag;
      st = Hop(t, lane, tag,
               reinterpret_cast<const uint8_t*>(chunk_ptrs[send_idx]),
               static_cast<size_t>(chunk_elems[send_idx]) * 4, nullptr, 0,
               reinterpret_cast<uint8_t*>(chunk_ptrs[recv_idx]),
               static_cast<size_t>(chunk_elems[recv_idx]) * 4, timeout_s, err,
               &rec);
      if (st != RingStatus::kOk) return st;
      RecordHop(rec);
    }
    return RingStatus::kOk;
  }
  // One arena for all n encoded chunk frames (same persistent per-thread
  // scratch policy as sendbuf/recvbuf above).
  std::vector<size_t> off(static_cast<size_t>(n) + 1, 0);
  for (int i = 0; i < n; ++i) off[static_cast<size_t>(i) + 1] = off[i] + enc_len(chunk_elems[i]);
  static thread_local std::vector<uint8_t> arena_tl;
  uint8_t* arena = grow(&arena_tl, off[static_cast<size_t>(n)]);
  int own = (rank + 1) % n;
  encode(chunk_ptrs[own], chunk_elems[own], arena + off[own]);
  for (int step = 0; step < n - 1; ++step) {
    int send_idx = ModN(rank - step + 1, n);
    int recv_idx = ModN(rank - step, n);
    RingHopRecord rec;
    rec.tier = tier;
    rec.lane = lane;
    rec.tag = tag;
    st = Hop(t, lane, tag, arena + off[send_idx],
             enc_len(chunk_elems[send_idx]), nullptr, 0,
             arena + off[recv_idx], enc_len(chunk_elems[recv_idx]),
             timeout_s, err, &rec);
    if (st != RingStatus::kOk) return st;
    RecordHop(rec);
  }
  for (int i = 0; i < n; ++i) {
    decode_assign(arena + off[i], chunk_elems[i], chunk_ptrs[i]);
  }
  return RingStatus::kOk;
}

// ---------------------------------------------------------------------------
// Batched multi-stripe pass (one capi crossing per op)
// ---------------------------------------------------------------------------

// One op's whole stripe set.  Workers claim stripes off `next`; the caller
// thread claims too, so the op progresses even with every pool worker busy
// on other ops' batches.  Args are copied in so a straggler pool task that
// pops the batch after completion touches only live memory.
struct RingEngine::MultiBatch {
  int tier = 0, nstripes = 0, n = 0, rank = 0, mode = 0, op = 0, wire = 0;
  uint32_t rs_sub = 0, ag_sub = 0;
  std::vector<int32_t> lanes;
  std::vector<uint32_t> tag_bases;
  std::vector<uint64_t> ptrs, elems;
  double timeout_s = 0;
  std::atomic<int> next{0};
  std::mutex mu;
  std::condition_variable cv;
  int done = 0;
  RingStatus st = RingStatus::kOk;
  std::string err;
};

void RingEngine::RunBatchClaims(const std::shared_ptr<MultiBatch>& b) {
  for (;;) {
    int s = b->next.fetch_add(1, std::memory_order_relaxed);
    if (s >= b->nstripes) return;
    std::string werr;
    RingStatus st = RingPass(
        b->tier, b->lanes[static_cast<size_t>(s)], b->n, b->rank,
        b->tag_bases[static_cast<size_t>(s)], b->rs_sub, b->ag_sub, b->mode,
        b->op, b->wire,
        reinterpret_cast<float* const*>(b->ptrs.data() +
                                        static_cast<size_t>(s) * b->n),
        b->elems.data() + static_cast<size_t>(s) * b->n, b->timeout_s, &werr);
    bool first_fail = false;
    {
      std::lock_guard<std::mutex> lk(b->mu);
      if (st != RingStatus::kOk && b->st == RingStatus::kOk) {
        b->st = st;
        b->err = werr;
        first_fail = true;
      }
      ++b->done;
    }
    if (first_fail && b->tier >= 0 && b->tier < kNumTiers) {
      // Mirror _run_striped's _fail_ring: poison + shut down every lane of
      // the tier so sibling stripes (and the peer) fail fast instead of
      // each waiting out its own timeout.
      Tier* t = &tiers_[b->tier];
      for (auto& l : t->next) {
        PoisonLink(l.get(), "stripe sibling failed: " + werr);
        if (l->fd >= 0) ::shutdown(l->fd, SHUT_RDWR);
        l->qcv.notify_all();
      }
      for (auto& l : t->prev) {
        PoisonLink(l.get(), "stripe sibling failed: " + werr);
        if (l->fd >= 0) ::shutdown(l->fd, SHUT_RDWR);
        l->rcv.notify_all();
      }
    }
    b->cv.notify_all();
  }
}

void RingEngine::MultiWorkerLoop() {
  for (;;) {
    std::shared_ptr<MultiBatch> b;
    {
      std::unique_lock<std::mutex> lk(mw_mu_);
      mw_cv_.wait(lk, [&] { return mw_stop_ || !mw_queue_.empty(); });
      if (mw_stop_) return;  // callers finish their batches inline
      b = mw_queue_.front();
      mw_queue_.pop_front();
    }
    RunBatchClaims(b);
  }
}

void RingEngine::EnsureMultiPool() {
  std::lock_guard<std::mutex> lk(mw_mu_);
  if (!mw_threads_.empty() || mw_stop_) return;
  int nw = std::max(1, std::min(lanes_ * 2, 16));
  for (int i = 0; i < nw; ++i) {
    mw_threads_.emplace_back([this] { MultiWorkerLoop(); });
  }
}

RingStatus RingEngine::RingPassMulti(int tier, int nstripes, int n, int rank,
                                     const int32_t* lanes,
                                     const uint32_t* tag_bases, uint32_t rs_sub,
                                     uint32_t ag_sub, int mode, int op,
                                     int wire, const uint64_t* chunk_ptrs,
                                     const uint64_t* chunk_elems,
                                     double timeout_s, std::string* err) {
  if (nstripes < 1 || n < 1) {
    *err = "bad stripe set";
    return RingStatus::kError;
  }
  if (closed_.load()) {
    *err = "ring engine closed";
    return RingStatus::kClosed;
  }
  OpGuard guard(&active_ops_);
  auto b = std::make_shared<MultiBatch>();
  b->tier = tier;
  b->nstripes = nstripes;
  b->n = n;
  b->rank = rank;
  b->mode = mode;
  b->op = op;
  b->wire = wire;
  b->rs_sub = rs_sub;
  b->ag_sub = ag_sub;
  b->timeout_s = timeout_s;
  b->lanes.assign(lanes, lanes + nstripes);
  b->tag_bases.assign(tag_bases, tag_bases + nstripes);
  size_t total = static_cast<size_t>(nstripes) * static_cast<size_t>(n);
  b->ptrs.assign(chunk_ptrs, chunk_ptrs + total);
  b->elems.assign(chunk_elems, chunk_elems + total);
  if (nstripes > 1) {
    EnsureMultiPool();
    {
      std::lock_guard<std::mutex> lk(mw_mu_);
      if (!mw_stop_) {
        int helpers =
            std::min(nstripes - 1, static_cast<int>(mw_threads_.size()));
        for (int i = 0; i < helpers; ++i) mw_queue_.push_back(b);
      }
    }
    mw_cv_.notify_all();
  }
  RunBatchClaims(b);
  std::unique_lock<std::mutex> lk(b->mu);
  b->cv.wait(lk, [&] { return b->done >= b->nstripes; });
  if (b->st != RingStatus::kOk) *err = b->err;
  return b->st;
}

int RingEngine::Counters(int tier, uint64_t* sent, uint64_t* recv, int cap) {
  if (tier < 0 || tier >= kNumTiers || !tiers_[tier].present) return 0;
  Tier* t = &tiers_[tier];
  int nl = static_cast<int>(t->next.size());
  for (int i = 0; i < nl && i < cap; ++i) {
    sent[i] = t->next[static_cast<size_t>(i)]->bytes.load();
    recv[i] = t->prev[static_cast<size_t>(i)]->bytes.load();
  }
  return std::min(nl, cap);
}

void RingEngine::ShaperCounters(int tier, int direction, uint64_t* bytes,
                                uint64_t* frames) {
  *bytes = 0;
  *frames = 0;
  if (tier < 0 || tier >= kNumTiers || !tiers_[tier].present) return;
  RingShaper* s = direction == kDirNext ? &tiers_[tier].next_shaper
                                        : &tiers_[tier].prev_shaper;
  *bytes = s->bytes_sent.load();
  *frames = s->frames_sent.load();
}

double RingEngine::ShaperWaitS(int tier, int direction) {
  if (tier < 0 || tier >= kNumTiers || !tiers_[tier].present) return 0.0;
  RingShaper* s = direction == kDirNext ? &tiers_[tier].next_shaper
                                        : &tiers_[tier].prev_shaper;
  return s->wait_us.load(std::memory_order_relaxed) / 1e6;
}

void RingEngine::SetShaper(int tier, int direction, double mbps, double rtt_ms) {
  if (tier < 0 || tier >= kNumTiers || !tiers_[tier].present) return;
  RingShaper* s = direction == kDirNext ? &tiers_[tier].next_shaper
                                        : &tiers_[tier].prev_shaper;
  s->SetRate(mbps, rtt_ms);
}

uint64_t RingEngine::LinkBytes(int tier, int direction, int lane) {
  RingLink* l = link(tier, direction, lane);
  return l ? l->bytes.load() : 0;
}

}  // namespace tpuft
