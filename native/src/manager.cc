#include "manager.h"

#include <algorithm>
#include <cstdlib>
#include <vector>

#include "log.h"

namespace tpuft {

bool ComputeQuorumResults(const std::string& replica_id, int64_t group_rank, const Quorum& quorum,
                          bool init_sync, bool force_recover, ManagerQuorumResponse* resp,
                          std::string* err) {
  // Participants are kept sorted by replica_id by the Lighthouse; sort again
  // defensively since replica rank assignment depends on it.
  std::vector<QuorumMember> members(quorum.participants().begin(), quorum.participants().end());
  std::sort(members.begin(), members.end(), [](const QuorumMember& a, const QuorumMember& b) {
    return a.replica_id() < b.replica_id();
  });
  if (members.empty()) {
    if (err) *err = "empty quorum";
    return false;
  }

  int64_t replica_rank = -1;
  for (size_t i = 0; i < members.size(); ++i) {
    if (members[i].replica_id() == replica_id) replica_rank = static_cast<int64_t>(i);
  }
  if (replica_rank < 0) {
    if (err) *err = "replica " + replica_id + " not in quorum";
    return false;
  }

  int64_t max_step = 0;
  for (const auto& m : members) max_step = std::max(max_step, m.step());

  std::vector<int64_t> up_to_date;
  for (size_t i = 0; i < members.size(); ++i) {
    if (members[i].step() == max_step) up_to_date.push_back(static_cast<int64_t>(i));
  }
  // Initial weight sync: at step 0 everyone is nominally "up to date" but has
  // different random init; collapse the source set to participant 0 so all
  // other groups pull its weights (skipped when init_sync=false;
  // reference: src/manager.rs init_sync tests + torchft/manager.py:118-131).
  if (init_sync && max_step == 0 && members.size() > 1) {
    up_to_date = {0};
  }

  std::vector<int64_t> recovering;
  for (size_t i = 0; i < members.size(); ++i) {
    int64_t idx = static_cast<int64_t>(i);
    if (std::find(up_to_date.begin(), up_to_date.end(), idx) == up_to_date.end()) {
      recovering.push_back(idx);
    }
  }

  resp->set_quorum_id(quorum.quorum_id());
  resp->set_max_step(max_step);
  // Full participant membership (fields 15-16): the erasure-shard
  // placement and donor-free reconstruction operate over EVERY live
  // participant, not just the max-step donor set.
  for (size_t i = 0; i < members.size(); ++i) {
    resp->add_participant_replica_ranks(static_cast<int64_t>(i));
    resp->add_participant_manager_addresses(members[i].address());
  }
  resp->set_max_world_size(static_cast<int64_t>(up_to_date.size()));
  resp->set_replica_rank(replica_rank);
  resp->set_replica_world_size(static_cast<int64_t>(members.size()));

  int64_t max_replica_rank = -1;
  for (size_t i = 0; i < up_to_date.size(); ++i) {
    if (up_to_date[i] == replica_rank) max_replica_rank = static_cast<int64_t>(i);
  }
  resp->set_max_replica_rank(max_replica_rank);

  // Stripe store load: local rank r uses participant (r % n)'s store.
  const auto& store_member = members[group_rank % static_cast<int64_t>(members.size())];
  resp->set_store_address(store_member.store_address());

  bool heal = std::find(recovering.begin(), recovering.end(), replica_rank) != recovering.end();
  if (force_recover && !heal && up_to_date.size() > 1) {
    // A replica that repeatedly failed commits re-fetches weights even though
    // its step looks current.
    heal = true;
  }
  resp->set_heal(heal);

  // Striped multi-donor recovery assignment.  The PRIMARY donor is still
  // round-robin over (recovery position + local rank) — different recovering
  // groups and different local ranks of one group lead with different
  // donors — but the full ordered donor rotation now travels with the
  // response (recover_src_replica_ranks / _manager_addresses) so the
  // receiver can stripe its fetch across every healthy max-step group and
  // fail a stripe over to the next donor if one dies mid-heal.  Every
  // up-to-date member learns the complete recovering set and opens its
  // serving window, not just the primaries.
  if (!up_to_date.empty()) {
    // Donor candidates exclude the requester itself: a force-recovering
    // group sits in up_to_date (its step LOOKS current) yet must not be
    // told to heal from — or serve — itself.
    std::vector<int64_t> donors;
    for (int64_t idx : up_to_date) {
      if (idx != replica_rank) donors.push_back(idx);
    }
    auto set_donor_rotation = [&](int64_t lead_pos) {
      resp->set_recover_src_replica_rank(donors[lead_pos]);
      resp->set_recover_src_manager_address(members[donors[lead_pos]].address());
      for (size_t i = 0; i < donors.size(); ++i) {
        int64_t src = donors[(lead_pos + static_cast<int64_t>(i)) %
                             static_cast<int64_t>(donors.size())];
        resp->add_recover_src_replica_ranks(src);
        resp->add_recover_src_manager_addresses(members[src].address());
      }
    };
    for (size_t j = 0; j < recovering.size(); ++j) {
      // Primary assignment: unchanged single-donor round-robin over
      // up_to_date (a recovering member is never in up_to_date, so its
      // donor rotation below leads with this same primary).
      int64_t primary = up_to_date[(static_cast<int64_t>(j) + group_rank) %
                                   static_cast<int64_t>(up_to_date.size())];
      if (recovering[j] == replica_rank && !donors.empty()) {
        set_donor_rotation((static_cast<int64_t>(j) + group_rank) %
                           static_cast<int64_t>(donors.size()));
      }
      if (primary == replica_rank) {
        // Field 11 keeps PRIMARY-only semantics: point-to-point transports
        // (collective send/recv) block until matched, so a donor must only
        // send where the healer is guaranteed to recv.
        resp->add_recover_dst_replica_ranks(recovering[j]);
      }
    }
    if (heal && !donors.empty() &&
        std::find(recovering.begin(), recovering.end(), replica_rank) == recovering.end()) {
      // force_recover path: an up-to-date replica re-fetching after repeated
      // failed commits stripes over the same rotation, led by local rank.
      set_donor_rotation(group_rank % static_cast<int64_t>(donors.size()));
    }
    if (max_replica_rank >= 0 && !heal) {
      // Field 14: EVERY donor learns the full recovering set so pull-based
      // transports open their serving windows for striped multi-donor
      // fetches (serving is passive — an unused window costs nothing).
      for (int64_t r : recovering) resp->add_recover_dst_replica_ranks_all(r);
    }
  }
  return true;
}

ManagerServer::ManagerServer(ManagerOpt opt) : opt_(std::move(opt)) {}

ManagerServer::~ManagerServer() { Shutdown(); }

bool ManagerServer::Start(std::string* err) {
  server_ = std::make_unique<RpcServer>(
      opt_.bind, [this](uint16_t method, const std::string& req, Deadline dl,
                        const std::string& peer, std::string* resp) {
        return Dispatch(method, req, dl, peer, resp);
      });
  if (!server_->Start(err)) return false;
  flight_.SetIdentity("manager", opt_.replica_id);
  heartbeat_client_ = std::make_unique<FailoverRpcClient>(opt_.lighthouse_addr);
  quorum_client_ = std::make_unique<FailoverRpcClient>(opt_.lighthouse_addr);
  // Startup reachability probe: with EVERY lighthouse address dead (typo'd
  // TPUFT_LIGHTHOUSE, lighthouse not started), fail construction with an
  // actionable error within the connect timeout — without this, the first
  // quorum call sat in the retry loop for its full deadline and a train
  // loop with a long quorum_timeout looked simply hung.
  if (quorum_client_->Connect(opt_.connect_timeout_ms, err) != Status::kOk) {
    server_->Shutdown();
    return false;
  }
  hb_thread_ = std::thread([this] { HeartbeatLoop(); });
  LOGI("manager %s listening on %s (lighthouse %s)", opt_.replica_id.c_str(),
       server_->address().c_str(), opt_.lighthouse_addr.c_str());
  return true;
}

void ManagerServer::Shutdown() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (shutdown_) return;
    shutdown_ = true;
    cv_.notify_all();
  }
  if (heartbeat_client_) heartbeat_client_->Close();
  if (quorum_client_) quorum_client_->Close();
  if (hb_thread_.joinable()) hb_thread_.join();
  if (server_) server_->Shutdown();
  // Black-box dump (see Lighthouse::Shutdown): a cleanly departing group
  // leaves flight_manager_<replica_id>.json in $TPUFT_FLIGHT_DIR.
  flight_.RecordEvent(kFlightShutdown, "server=manager replica=" + opt_.replica_id);
  std::string dump = flight_.DumpPathFromEnv();
  if (!dump.empty() && !flight_.DumpToFile(dump)) {
    LOGW("manager %s: flight recorder dump to %s failed",
         opt_.replica_id.c_str(), dump.c_str());
  }
}

std::string ManagerServer::address() const { return server_ ? server_->address() : ""; }

void ManagerServer::SetStatus(int64_t step, const std::string& state,
                              double step_time_ms_ewma, double step_time_ms_last,
                              double allreduce_gb_per_s, int64_t ec_shards_held,
                              int64_t ec_shard_step, int64_t ec_k,
                              double link_recv_gbps, double link_send_gbps,
                              double link_hop_rtt_ms) {
  std::lock_guard<std::mutex> lk(mu_);
  status_step_ = step;
  status_state_ = state;
  // 0 means "no new telemetry": a phase-transition push (e.g. "quorum")
  // between commits must not wipe the last committed step's pacing data off
  // the heartbeat — the sentinel needs the EWMA continuously visible.
  if (step_time_ms_ewma > 0.0) {
    status_step_time_ewma_ms_ = step_time_ms_ewma;
    status_step_time_last_ms_ = step_time_ms_last;
  }
  // Unlike the EWMA above, 0 IS a report here: the Manager always pushes
  // the authoritative gauge (a committed no-traffic step — healing, spare —
  // zeroes it), so only a negative value means "keep the prior reading".
  if (allreduce_gb_per_s >= 0.0) {
    status_allreduce_gbps_ = allreduce_gb_per_s;
  }
  // Shard-inventory coverage (heartbeat fields 8-9): like the gauge above,
  // 0 is an authoritative report (store empty / pruned) and a negative
  // value means "keep the prior reading" for status-only pushes.
  if (ec_shards_held >= 0) {
    status_ec_shards_ = ec_shards_held;
    status_ec_step_ = ec_shard_step;
  }
  if (ec_k >= 0) {
    status_ec_k_ = ec_k;
  }
  // Link health EWMAs (heartbeat fields 11-13): 0 is an authoritative
  // "no observation" report, negative keeps the prior reading.
  if (link_recv_gbps >= 0.0) status_link_recv_gbps_ = link_recv_gbps;
  if (link_send_gbps >= 0.0) status_link_send_gbps_ = link_send_gbps;
  if (link_hop_rtt_ms >= 0.0) status_link_rtt_ms_ = link_hop_rtt_ms;
}

void ManagerServer::SetLedger(double goodput_ratio, double compute_seconds,
                              const double* lost_seconds, int32_t n_causes) {
  std::lock_guard<std::mutex> lk(mu_);
  status_goodput_ratio_ = goodput_ratio;
  status_ledger_compute_s_ = compute_seconds;
  status_ledger_lost_s_.assign(
      lost_seconds, lost_seconds + (n_causes > 0 ? n_causes : 0));
}

void ManagerServer::HeartbeatLoop() {
  std::string payload, resp, err;
  // A single heartbeat RPC must never be allowed to eat a whole
  // heartbeat_timeout window: the lighthouse keeps a replica alive as long
  // as one heartbeat lands within each heartbeat_timeout window, so a
  // bounded per-call timeout with an immediate retry on the next tick is
  // strictly safer than one long in-call wait that could blow through the
  // whole window on a single stuck connection.
  const uint64_t call_timeout_ms = std::max<uint64_t>(opt_.heartbeat_interval_ms * 5, 500);
  int64_t consecutive_failures = 0;
  auto last_iter = Clock::now();
  while (true) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      if (cv_.wait_for(lk, std::chrono::milliseconds(opt_.heartbeat_interval_ms),
                       [&] { return shutdown_; })) {
        return;
      }
    }
    auto now = Clock::now();
    auto gap_ms =
        std::chrono::duration_cast<std::chrono::milliseconds>(now - last_iter).count();
    if (gap_ms > static_cast<int64_t>(opt_.heartbeat_interval_ms) * 10) {
      LOGW("manager %s: heartbeat loop stalled for %lld ms", opt_.replica_id.c_str(),
           static_cast<long long>(gap_ms));
    }
    last_iter = now;
    // Rebuilt every tick: the payload carries the LIVE step/state pushed by
    // SetStatus, which is what makes the lighthouse's /metrics step-lag and
    // last-commit gauges real-time rather than quorum-snapshot stale.
    {
      LighthouseHeartbeatRequest req;
      req.set_replica_id(opt_.replica_id);
      std::lock_guard<std::mutex> lk(mu_);
      req.set_step(status_step_);
      req.set_state(status_state_);
      req.set_step_time_ms_ewma(status_step_time_ewma_ms_);
      req.set_step_time_ms_last(status_step_time_last_ms_);
      req.set_allreduce_gb_per_s(status_allreduce_gbps_);
      req.set_ec_shards_held(status_ec_shards_);
      req.set_ec_shard_step(status_ec_step_);
      req.set_ec_k(status_ec_k_);
      req.set_link_recv_gbps(status_link_recv_gbps_);
      req.set_link_send_gbps(status_link_send_gbps_);
      req.set_link_hop_rtt_ms(status_link_rtt_ms_);
      req.set_goodput_ratio(status_goodput_ratio_);
      req.set_ledger_compute_seconds(status_ledger_compute_s_);
      for (double v : status_ledger_lost_s_) req.add_ledger_lost_seconds(v);
      req.set_trace_id(status_trace_id_);
      req.SerializeToString(&payload);
    }
    Status st = heartbeat_client_->Call(kLighthouseHeartbeat, payload, call_timeout_ms,
                                        &resp, &err);
    if (st != Status::kOk) {
      consecutive_failures += 1;
      // First failure and every ~2s of continued failure: visible, bounded.
      if (consecutive_failures == 1 || consecutive_failures % 20 == 0) {
        LOGW("manager %s: heartbeat to %s failed (x%lld): %s", opt_.replica_id.c_str(),
             opt_.lighthouse_addr.c_str(), static_cast<long long>(consecutive_failures),
             err.c_str());
      }
    } else {
      consecutive_failures = 0;
    }
  }
}

Status ManagerServer::Dispatch(uint16_t method, const std::string& req, Deadline dl,
                               const std::string& peer, std::string* resp) {
  auto t0 = Clock::now();
  std::string trace_id;
  Status st = DispatchInner(method, req, dl, resp, &trace_id);
  int64_t dur_us =
      std::chrono::duration_cast<std::chrono::microseconds>(Clock::now() - t0)
          .count();
  flight_.RecordRpc(MethodName(method).c_str(), peer,
                    static_cast<uint16_t>(st), dur_us, std::move(trace_id));
  return st;
}

Status ManagerServer::DispatchInner(uint16_t method, const std::string& req, Deadline dl,
                                    std::string* resp, std::string* trace_id) {
  switch (method) {
    case kManagerQuorum: {
      ManagerQuorumRequest r;
      if (!r.ParseFromString(req)) return Status::kInvalidArgument;
      *trace_id = r.trace_id();
      ManagerQuorumResponse out;
      std::string err;
      Status st = HandleQuorum(r, dl, &out, &err);
      if (st != Status::kOk) {
        *resp = err;
        return st;
      }
      out.SerializeToString(resp);
      return Status::kOk;
    }
    case kManagerCheckpointMetadata: {
      CheckpointMetadataRequest r;
      if (!r.ParseFromString(req)) return Status::kInvalidArgument;
      *trace_id = r.trace_id();
      CheckpointMetadataResponse out;
      std::string err;
      Status st = HandleCheckpointMetadata(r, &out, &err);
      if (st != Status::kOk) {
        *resp = err;
        return st;
      }
      out.SerializeToString(resp);
      return Status::kOk;
    }
    case kManagerShouldCommit: {
      ShouldCommitRequest r;
      if (!r.ParseFromString(req)) return Status::kInvalidArgument;
      *trace_id = r.trace_id();
      ShouldCommitResponse out;
      std::string err;
      Status st = HandleShouldCommit(r, dl, &out, &err);
      if (st != Status::kOk) {
        *resp = err;
        return st;
      }
      out.SerializeToString(resp);
      return Status::kOk;
    }
    case kManagerKill: {
      KillRequest r;
      r.ParseFromString(req);
      LOGE("manager %s: kill requested: %s", opt_.replica_id.c_str(), r.msg().c_str());
      std::exit(1);
    }
    default:
      *resp = "unknown manager method " + std::to_string(method);
      return Status::kUnknown;
  }
}

Status ManagerServer::HandleQuorum(const ManagerQuorumRequest& req, Deadline deadline,
                                   ManagerQuorumResponse* resp, std::string* err) {
  std::unique_lock<std::mutex> lk(mu_);
  if (req.group_rank() < 0 || req.group_rank() >= static_cast<int64_t>(opt_.world_size)) {
    *err = "group_rank " + std::to_string(req.group_rank()) + " out of range for world_size " +
           std::to_string(opt_.world_size);
    return Status::kInvalidArgument;
  }
  checkpoint_metadata_[req.group_rank()] = req.checkpoint_metadata();
  round_reqs_[req.group_rank()] = req;
  int64_t my_round = round_;
  if (!req.trace_id().empty()) {
    // The step's causal trace id (minted by the Python Manager, docs/
    // wire.md "Causal trace ids"): forwarded on the lighthouse RPC below
    // and stamped onto every heartbeat until the next round replaces it.
    status_trace_id_ = req.trace_id();
  }

  if (round_reqs_.size() == opt_.world_size) {
    // This rank completed the set: perform the Lighthouse RPC for the group.
    int64_t step = 0;
    bool shrink_only = false;
    std::string trace_id;
    for (const auto& [rank, r] : round_reqs_) {
      step = std::max(step, r.step());
      shrink_only = shrink_only || r.shrink_only();
      if (!r.trace_id().empty()) trace_id = r.trace_id();
    }
    LighthouseQuorumRequest lreq;
    lreq.set_trace_id(trace_id);
    auto* member = lreq.mutable_requester();
    member->set_replica_id(opt_.replica_id);
    member->set_address(server_->address());
    member->set_store_address(opt_.store_addr);
    member->set_step(step);
    member->set_world_size(opt_.world_size);
    member->set_shrink_only(shrink_only);

    lk.unlock();
    std::string payload, lresp_bytes, lerr;
    lreq.SerializeToString(&payload);
    uint64_t timeout = static_cast<uint64_t>(
        std::min<int64_t>(deadline.remaining_ms(), 24LL * 3600 * 1000));
    Status st = quorum_client_->Call(kLighthouseQuorum, payload, timeout, &lresp_bytes, &lerr);
    lk.lock();

    if (round_ == my_round) {
      result_round_ = my_round;
      result_status_ = st;
      result_error_ = lerr;
      if (st == Status::kOk) {
        LighthouseQuorumResponse lresp;
        if (!lresp.ParseFromString(lresp_bytes)) {
          result_status_ = Status::kInternal;
          result_error_ = "bad lighthouse response";
        } else {
          result_quorum_ = lresp.quorum();
        }
      }
      // Outcome of the round the group just paid for: quorum id +
      // membership size on success, the failure status otherwise.
      flight_.RecordEvent(
          kFlightQuorumResult,
          result_status_ == Status::kOk
              ? "quorum_id=" + std::to_string(result_quorum_.quorum_id()) +
                    " participants=" +
                    std::to_string(result_quorum_.participants_size()) +
                    " step=" + std::to_string(step)
              : "status=" + StatusName(result_status_) + " step=" +
                    std::to_string(step),
          trace_id);
      round_ += 1;
      round_reqs_.clear();
      cv_.notify_all();
    }
  } else {
    bool ok = cv_.wait_until(lk, deadline.at, [&] {
      return result_round_ >= my_round || shutdown_;
    });
    if (shutdown_) {
      *err = "manager shutting down";
      return Status::kUnavailable;
    }
    if (!ok) {
      // Leave our request in place; peers may still arrive and complete the
      // round, but this caller gives up now.
      *err = "timed out waiting for all " + std::to_string(opt_.world_size) +
             " local ranks to call quorum";
      return Status::kDeadlineExceeded;
    }
  }

  if (result_round_ != my_round) {
    *err = "quorum round moved on; retry";
    return Status::kAborted;
  }
  if (result_status_ != Status::kOk) {
    *err = "lighthouse quorum failed: " + result_error_;
    return result_status_;
  }
  if (!ComputeQuorumResults(opt_.replica_id, req.group_rank(), result_quorum_, req.init_sync(),
                            req.commit_failures() > 0, resp, err)) {
    return Status::kInternal;
  }
  return Status::kOk;
}

Status ManagerServer::HandleCheckpointMetadata(const CheckpointMetadataRequest& req,
                                               CheckpointMetadataResponse* resp,
                                               std::string* err) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = checkpoint_metadata_.find(req.group_rank());
  if (it == checkpoint_metadata_.end()) {
    *err = "no checkpoint metadata for rank " + std::to_string(req.group_rank());
    return Status::kNotFound;
  }
  resp->set_checkpoint_metadata(it->second);
  return Status::kOk;
}

Status ManagerServer::HandleShouldCommit(const ShouldCommitRequest& req, Deadline deadline,
                                         ShouldCommitResponse* resp, std::string* err) {
  std::unique_lock<std::mutex> lk(mu_);
  CommitRound& cr = commits_[req.step()];
  cr.votes[req.group_rank()] = req.should_commit();
  if (!req.should_commit()) {
    LOGW("manager %s: rank %lld voted to abort step %lld", opt_.replica_id.c_str(),
         static_cast<long long>(req.group_rank()), static_cast<long long>(req.step()));
  }
  if (cr.votes.size() == opt_.world_size) {
    cr.decided = true;
    cr.decision = true;
    for (const auto& [rank, vote] : cr.votes) cr.decision = cr.decision && vote;
    cv_.notify_all();
  } else {
    bool ok = cv_.wait_until(lk, deadline.at, [&] {
      return commits_[req.step()].decided || shutdown_;
    });
    if (shutdown_) {
      *err = "manager shutting down";
      return Status::kUnavailable;
    }
    if (!ok) {
      *err = "timed out waiting for all ranks to vote on step " + std::to_string(req.step());
      return Status::kDeadlineExceeded;
    }
  }
  CommitRound& done = commits_[req.step()];
  resp->set_should_commit(done.decision);
  done.handed_out += 1;
  // Reset once every rank has its answer so a failed step can be re-voted.
  if (done.handed_out == static_cast<int64_t>(opt_.world_size)) {
    commits_.erase(req.step());
  }
  return Status::kOk;
}

}  // namespace tpuft
