// Lighthouse: global membership + quorum service.
//
// Reference parity: src/lighthouse.rs.  Tracks per-replica heartbeats, admits
// participants per quorum round, computes a quorum on a periodic tick (and on
// every join), bumps the quorum id only when membership changes, broadcasts
// the new quorum to every blocked Quorum RPC caller, serves an HTML/JSON
// dashboard, and can kill replicas through their Manager.
#pragma once

#include <condition_variable>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include <atomic>

#include "flight.h"
#include "tpuft.pb.h"
#include "wire.h"

namespace tpuft {

class HttpServer;

struct LighthouseOpt {
  // RPC bind address, e.g. "[::]:0".
  std::string bind = "[::]:0";
  // Dashboard HTTP bind address; empty disables the dashboard.
  std::string http_bind = "[::]:0";
  uint64_t min_replicas = 1;
  // How long to wait for stragglers after the first joiner of a round.
  // Reference default: 60 s (src/lighthouse.rs:97-102).
  uint64_t join_timeout_ms = 60000;
  // Reference default: 100 ms (src/lighthouse.rs:110-115).
  uint64_t quorum_tick_ms = 100;
  // Reference default: 5 s (src/lighthouse.rs:117-122).
  uint64_t heartbeat_timeout_ms = 5000;
};

// Straggler sentinel state for one replica (docs/architecture.md
// "Straggler detection").  Heartbeats carry a rolling per-step busy-time
// EWMA; the engine scores each replica's EWMA against the cluster's lower
// median and runs a hysteresis state machine over per-step observations:
//   healthy --(ratio >= R)--> suspect --(grace consecutive over)--> straggler
//   straggler --(grace consecutive under)--> healthy (alert resolved)
//   suspect --(one under)--> healthy
// R = TPUFT_STRAGGLER_RATIO, grace = TPUFT_STRAGGLER_GRACE_STEPS.
struct ReplicaHealth {
  double ewma_ms = 0.0;   // latest reported step-time EWMA
  double last_ms = 0.0;   // latest single-step observation
  double ratio = 0.0;     // ewma / cluster lower-median ewma (0 = unscored)
  int state = 0;          // 0 healthy, 1 suspect, 2 straggler
  int64_t over = 0;       // consecutive step observations at ratio >= R
  int64_t under = 0;      // consecutive step observations at ratio < R
  // The sentinel's OWN step cursor.  hb_step_ is also advanced by quorum
  // joins (which carry no step-time telemetry and usually beat the next
  // heartbeat to a freshly committed step), so gating observations on a
  // hb_step_ advance would drop most steps to a race; this cursor moves
  // only on telemetry-carrying heartbeats, giving exactly one observation
  // per committed step.
  int64_t last_step = -1;
  // Total observations for this incarnation: promotions to straggler are
  // suppressed until past the warmup (JIT compilation skews early busy
  // times wildly and replica-asymmetrically — without the gate a slow
  // first compile reads as a straggler and can trigger a spurious
  // auto-drain).
  int64_t observations = 0;
};

// Slow-link sentinel state for one replica's outbound ring edge
// (docs/architecture.md "Data-plane observability").  Heartbeats carry the
// Manager's per-neighbor link health EWMAs derived from the ring engines'
// hop telemetry; the engine scores each replica's OUTBOUND goodput
// (send_gbps — the localizing signal: only the degraded edge's SENDER
// sees its send-blocked time explode, while recv-waits equalize around
// the lockstep ring) against the cluster's upper median and runs the same
// hysteresis shape as the straggler sentinel:
//   healthy --(median/gbps >= R)--> suspect --(grace over)--> degraded
//   degraded --(grace under)--> healthy (alert resolved)
// R = TPUFT_LINK_RATIO, grace = TPUFT_LINK_GRACE_STEPS.
struct LinkHealth {
  double recv_gbps = 0.0;  // inbound-edge goodput EWMA (receiver view)
  double send_gbps = 0.0;  // outbound-edge goodput EWMA (the scored signal)
  double rtt_ms = 0.0;     // mean per-hop recv-wait
  double ratio = 0.0;      // cluster median send_gbps / own (>= 1 = slow)
  int state = 0;           // 0 healthy, 1 suspect, 2 degraded
  int64_t over = 0;
  int64_t under = 0;
  int64_t last_step = -1;  // own step cursor (same rationale as ReplicaHealth)
  int64_t observations = 0;
};

// Number of lost-cause classes in the goodput ledger's pinned taxonomy
// (kLedgerCauses in lighthouse.cc == torchft_tpu/obs/ledger.py
// LOST_CAUSES; the heartbeat's ledger_lost_seconds vector order).
constexpr size_t kLedgerCauseCount = 10;

// Goodput-ledger counters for one replica incarnation, as last reported on
// its heartbeats (fields 14-16).  Monotonic per incarnation; a restart is
// a NEW id, whose predecessor's high-water mark is banked into the
// cluster accumulator when its entry is pruned or evicted.
struct ReplicaLedger {
  double goodput_ratio = 0.0;  // replica's cumulative productive fraction
  double compute_s = 0.0;      // cumulative productive seconds
  double lost_s[kLedgerCauseCount] = {0};  // per cause, pinned order
};

// One auto-capture trigger record, served on GET /incident.json.  The
// lighthouse only RECORDS triggers (always-on, bounded); the capture
// itself — bundling flight rings, alerts, goodput, span tails into
// incident_<step>/ — is driven by torchft_tpu/obs/incident.py, which
// polls this feed.  reason: "alert:<kind>" (sentinel raise),
// "replica_stale" (heartbeat loss) / "replica_evicted"
// (supervisor-reported death — together the kill signatures), or
// "goodput_floor" (windowed cluster goodput dipped below its EWMA floor).
// Culprit attribution for one closed goodput window (docs/observability.md
// "Culprit attribution"): when the window's goodput is scored, every
// entity's (replica incarnation or federated region) per-cause ledger
// delta is compared against its OWN trailing per-window baseline; the
// entity with the largest positive excess is the culprit, and the cause
// with the largest excess within it is dominant.  Attached to
// goodput_floor incidents and slo_burn alerts so the verdict names a
// replica instead of "cluster".
struct IncidentAttribution {
  std::string replica;     // culprit entity id ("" = no attribution yet)
  std::string region;      // owning region ("" when flat / unknown)
  std::string cause;       // dominant LOST cause (kLedgerCauses name)
  double charged_s = 0.0;  // total excess-over-baseline seconds charged
  std::string delta_json;  // {"<id>":{"compute_s":..,"lost_s":..,"excess_s":..}}
};

struct IncidentRecord {
  int64_t id = 0;
  std::string reason;
  std::string replica_id;  // victim / edge endpoint; "cluster" for cluster scope
  int64_t step = 0;        // max live step at trigger time
  int64_t ts_ms = 0;       // epoch ms
  double detail = 0.0;     // reason-specific scalar (ratio / goodput / age ms)
  // Culprit attribution (goodput_floor / slo_burn triggers; empty
  // otherwise).  replica_id stays "cluster" for schema + debounce-key
  // stability — the blame rides here.
  std::string culprit_replica;
  std::string culprit_region;
  std::string dominant_cause;
  double charged_seconds = 0.0;
  std::string delta_by_replica_json;  // per-replica window deltas (JSON object)
};

// One operator-visible alert, served on GET /alerts.json.  resolved_ms == 0
// while active.
struct AlertRecord {
  int64_t id = 0;
  std::string kind;        // "straggler" | "ec_coverage" | "slow_link" | "slo_burn"
  std::string replica_id;  // "cluster" for cluster-scope kinds
  int64_t raised_ms = 0;   // epoch ms
  int64_t resolved_ms = 0;
  double ratio = 0.0;        // slowness ratio at raise time
  double step_time_ms = 0.0; // EWMA at raise time
  bool auto_drained = false; // the sentinel rotated the replica out itself
  // kind == "ec_coverage": live shards at the newest encode generation
  // (kept current while active) and the k + 1 paging threshold.
  int64_t coverage = 0;
  int64_t threshold = 0;
  // kind == "slow_link": observed outbound goodput of the degraded edge
  // and the reporting endpoint (the edge's sender); replica_id names the
  // edge's RECEIVING endpoint — the auto-drain target.
  double gbps = 0.0;
  std::string src_replica_id;
  // kind == "slo_burn": multi-window burn rates at raise time (refreshed
  // while active) + the culprit attribution of the newest closed goodput
  // window, so the alert names who is burning the budget.
  double burn_fast = 0.0;
  double burn_slow = 0.0;
  std::string dominant_cause;
  double charged_seconds = 0.0;
};

// Root-side record of one regional child lighthouse (docs/wire.md
// "Federation").  Created on the first accepted RegionDigest push and kept
// for the lifetime of the root (region count is O(10), not O(N)); `stale`
// flips when pushes stop arriving for a heartbeat timeout — the region's
// members drop out of the global quorum through the ordinary
// heartbeat-freshness rule (their installed heartbeats freeze at the last
// push), and a "region_stale" incident names the lost region for the
// capture driver.  Not replicated to HA standbys: a promoted root
// repopulates this table from each region's next push (one push interval),
// re-latching child-epoch fences as digests arrive.
struct RegionEntry {
  TimePoint last_push{};      // when the last digest was accepted
  int64_t child_epoch = 0;    // fencing: highest child lease epoch accepted
  int64_t seq = 0;            // child's digest sequence at last accept
  int64_t replicas_total = 0;
  int64_t replicas_fresh = 0;
  double compute_s = 0.0;     // region ledger rollup (cumulative)
  double lost_s[kLedgerCauseCount] = {0};
  double goodput_ratio = 0.0;
  int64_t alerts_active = 0;
  int64_t incident_seq = 0;   // child's incident counter (digest freshness)
  int64_t digests = 0;        // accepted pushes (gauge)
  bool stale = false;         // digests stopped arriving
  // One-shot downward directives queued for the region's next digest
  // response: evict/drain prefixes issued at the root (ops endpoints,
  // auto-drain) for ids this region owns.
  std::vector<std::string> pending_evicts;
  std::vector<std::string> pending_drains;
  int64_t pending_drain_deadline_ms = 0;
};

// Pure quorum math, unit-testable without sockets.
// Reference parity: quorum_compute, src/lighthouse.rs:133-261.
struct QuorumState {
  struct Joined {
    QuorumMember member;
    TimePoint joined_at;
  };
  // Replicas that called Quorum this round, keyed by replica id.
  std::map<std::string, Joined> participants;
  // Last heartbeat seen per replica id (includes non-participants).
  std::map<std::string, TimePoint> heartbeats;
  // Replica ids departing cooperatively (drain notice received): excluded
  // from candidates AND from the healthy-set arithmetic (majority guard,
  // straggler wait), so the next quorum forms without them immediately.
  // Value: when the drain was announced (for pruning/status).
  std::map<std::string, TimePoint> draining;
  std::optional<Quorum> prev_quorum;
  int64_t quorum_id = 0;
};

// Returns the members of a valid quorum (sorted by replica id), or nullopt
// with `reason` describing what is still missing.
std::optional<std::vector<QuorumMember>> QuorumCompute(TimePoint now, const QuorumState& state,
                                                       const LighthouseOpt& opt,
                                                       std::string* reason);

class Lighthouse {
 public:
  explicit Lighthouse(LighthouseOpt opt);
  ~Lighthouse();

  bool Start(std::string* err);
  void Shutdown();
  std::string address() const;
  std::string http_address() const;

  // RPC handlers (public for in-process tests).
  Status HandleQuorum(const LighthouseQuorumRequest& req, Deadline deadline,
                      LighthouseQuorumResponse* resp, std::string* err);
  Status HandleHeartbeat(const LighthouseHeartbeatRequest& req);
  void FillStatus(LighthouseStatusResponse* resp);

  // Supervisor-assisted failure notification: drop a replica's heartbeat
  // and pending join immediately so the next quorum round does not spend
  // join_timeout waiting for a process the SUPERVISOR already knows is
  // dead (the heartbeat would otherwise look fresh for up to
  // heartbeat_timeout_ms).  `prefix` matches a full replica id or a
  // "<group>:" uuid-suffixed family.  Returns how many ids were dropped.
  int EvictReplica(const std::string& prefix);

  // Cooperative drain: a PLANNED departure announced before the process is
  // gone (maintenance events, preemption notices, SIGTERM grace periods).
  // Marks every id matching `prefix` (full id or "<group>:" family) as
  // draining: excluded from the NEXT quorum round immediately — no
  // join-timeout straggler wait, no heartbeat-timeout wait — and
  // tombstoned against late re-joins, while the id's in-flight step and
  // blocked handlers are left alone (unlike EvictReplica, which declares
  // the process already dead and aborts them).  The replacement
  // incarnation has a fresh ":<uuid>" suffix and joins normally.
  // `deadline_ms` is advisory (recorded for observability).  Returns how
  // many ids were marked.
  int DrainReplica(const std::string& prefix, int64_t deadline_ms);

  // Asks the replica's manager to exit. Used by the dashboard kill button.
  // Reference parity: src/lighthouse.rs:433-458.
  bool KillReplica(const std::string& replica_id, std::string* err);

  // Straggler sentinel introspection (public for in-process tests; the
  // wire-facing surfaces are /metrics, /status.json and /alerts.json).
  int StragglerState(const std::string& replica_id);
  // Slow-link sentinel introspection: the hysteresis state of the
  // replica's OUTBOUND edge (0 healthy, 1 suspect, 2 degraded).
  int LinkState(const std::string& replica_id);
  // JSON alert feed: {"active": N, "alerts": [...]} — newest last.
  std::string AlertsJson();
  // Goodput ledger rollup: cluster + per-replica cause-attributed totals
  // (the GET /goodput.json body; docs/wire.md "Goodput ledger").
  std::string GoodputJson();
  // Incident-trigger feed (GET /incident.json), newest last.
  std::string IncidentJson();
  // SLO engine snapshot (GET /slo.json): target, multi-window burn rates,
  // error budget remaining, the newest culprit attribution, and per-region
  // rollups when federated (the root evaluates over digest rollups, so the
  // fleet view costs O(R)).  Valid at every tier; {"enabled": false} when
  // TPUFT_SLO_TARGET is unset.
  std::string SloJson();

  // Flight-recorder snapshot (newest-first, bounded; 0 = all retained) —
  // the GET /debug/flight.json body and the capi accessor.
  std::string FlightJson(size_t limit = 0) { return flight_.Json(limit); }

  // -- HA role (docs/wire.md "HA lighthouse") -----------------------------
  // A standalone lighthouse is a permanent leader (the default — existing
  // single-instance deployments are unchanged).  Under the HA election
  // (torchft_tpu/ha), the election driver flips the role here on every
  // lease transition:
  //   - leader: serve authoritatively, but ONLY while the lease is valid —
  //     lease_expires_ms is the serve-time guard: once it passes without a
  //     renewal, HandleQuorum/HandleHeartbeat refuse with "not the leader"
  //     (an expired-lease leader must stop answering Quorum before a rival
  //     can win the lease), and blocked quorum joins are woken to abort;
  //   - follower: every mutating method (Quorum/Heartbeat/Evict/Drain) is
  //     refused with "not the leader; leader=<addr> ..." so clients
  //     redirect instead of split-braining; HTTP redirects with 307.
  // leader_addr/leader_http name the CURRENT leader (self when leader),
  // epoch is the lease epoch (fencing token for replication pushes).
  void SetRole(bool leader, const std::string& leader_addr,
               const std::string& leader_http, int64_t epoch,
               int64_t lease_expires_ms);
  // 1 leader (with a live lease), 0 otherwise.
  int Role();
  int64_t LeaderEpoch();

  // Serializes the full replicable state (membership, health, alerts,
  // prev quorum) as a LighthouseReplicateRequest — what the HA election
  // driver pushes to the standbys every replication tick.
  std::string SnapshotState();
  // Ingests a replication push (wire method 6 body).  Returns false (and
  // fills the response's applied=false) when this replica holds a HIGHER
  // epoch than the sender — the sender is a deposed leader.
  Status HandleReplicate(const LighthouseReplicateRequest& req,
                         LighthouseReplicateResponse* resp);
  void FillLeaderInfo(LighthouseLeaderInfoResponse* resp);

  // -- Federation (docs/wire.md "Federation") -----------------------------
  // Makes this lighthouse a regional CHILD: it keeps owning heartbeats,
  // sentinel scoring and the goodput-ledger rollup for its own replica
  // groups (Manager clients keep pointing at the region's address list,
  // unchanged), but stops forming local quorums — instead a push loop
  // reports a bounded membership + ledger digest to the ROOT lighthouse at
  // `root_addrs` (comma-separated; the root's HA replica set) every
  // `push_interval_ms`, installs the root's returned GLOBAL quorum for its
  // blocked joiners, and applies the root's downward evict/drain
  // directives.  Pushes only while this instance holds its region's lease
  // (HA follower children stay quiet); the digest carries the child lease
  // epoch so a deposed child leader is fenced at the root.  Call after
  // Start.  A lighthouse that never calls this and never receives digests
  // behaves bit-identically to the flat single-tier service.
  void SetFederation(const std::string& region, const std::string& root_addrs,
                     int64_t push_interval_ms);
  // Root-side ingest of one region digest (wire method 8): fences on the
  // child epoch, installs the region's members into the global membership
  // maps (heartbeats via age-carry, joined members as participants), rolls
  // the region's ledger into the fleet totals, attempts a global quorum,
  // and answers with the latest quorum + any pending directives for the
  // region.  Public for in-process tests.
  Status HandleRegionDigest(const LighthouseRegionDigestRequest& req,
                            LighthouseRegionDigestResponse* resp,
                            std::string* err);
  // Read-only federation rollup (wire method 9 / GET /regions.json),
  // answered by every instance regardless of role: role ("root" once any
  // digest was accepted, "child" when federated, else "flat") + one row
  // per known region.
  void FillRegions(LighthouseRegionsResponse* resp);
  std::string RegionsJson();

 private:
  // Outer dispatch: times the handler, records the server-side RPC span
  // (method, peer, status, duration, trace id) into the flight recorder
  // and the per-method latency histogram, then defers to DispatchInner —
  // which surfaces the request's trace id from the message it parses
  // anyway (re-parsing here would charge every heartbeat a second
  // deserialization inside the very latency window being measured).
  Status Dispatch(uint16_t method, const std::string& req, Deadline deadline,
                  const std::string& peer, std::string* resp);
  Status DispatchInner(uint16_t method, const std::string& req, Deadline deadline,
                       std::string* resp, std::string* trace_id);
  // True when an ops-endpoint request may mutate state (docs/wire.md
  // "Trust model"): the shared-secret header matches TPUFT_ADMIN_TOKEN, or
  // no token is configured and the peer is loopback.
  bool AdminAllowed(const std::string& token, bool peer_loopback) const;
  void TickLoop();
  // Runs one quorum attempt; on success installs + broadcasts it.
  // Caller must hold mu_.
  void TickLocked();
  // DrainReplica body; caller must hold mu_ (the sentinel's auto-drain
  // fires from inside HandleHeartbeat, which already does).
  int DrainLocked(const std::string& prefix, int64_t deadline_ms);
  // One sentinel observation for `id` (its reported step advanced with a
  // step-time EWMA attached): rescore against the cluster median and run
  // the hysteresis state machine.  Caller must hold mu_.
  void ObserveStepTimeLocked(const std::string& id);
  // Lower median of eligible (fresh, non-draining, reporting) replica
  // EWMAs; 0 when fewer than two replicas report.  Caller must hold mu_.
  double ClusterMedianEwmaLocked() const;
  // Raise/resolve the straggler alert for one replica.  Caller holds mu_.
  void RaiseStragglerAlertLocked(const std::string& id, ReplicaHealth* h);
  void ResolveAlertsLocked(const std::string& id);
  // Slow-link sentinel (docs/architecture.md "Data-plane observability"):
  // one observation for `id`'s outbound-edge goodput (its reported step
  // advanced with link telemetry attached).  Caller must hold mu_.
  void ObserveLinkLocked(const std::string& id);
  // Upper median of eligible (fresh, non-draining, reporting) outbound
  // goodputs; 0 when fewer than two replicas report.  Caller holds mu_.
  double ClusterMedianLinkGbpsLocked() const;
  void RaiseLinkAlertLocked(const std::string& id, LinkHealth* h);
  // Resolves slow_link alerts REPORTED by src_id (alerts are keyed by the
  // edge's receiving endpoint in replica_id, so resolution goes through
  // the reporter recorded in src_replica_id).
  void ResolveLinkAlertsLocked(const std::string& src_id);
  // The receiving endpoint of `id`'s outbound ring edge — its successor
  // in the last formed quorum's sorted participant order (the ring
  // order), or empty when no quorum/successor is known.  Caller holds mu_.
  std::string RingSuccessorLocked(const std::string& id) const;
  // EC coverage sentinel (docs/wire.md "Erasure shard endpoints"): pages
  // via /alerts.json + tpuft_alerts_active when the newest encode
  // generation's shard coverage stays below k + 1 for a heartbeat
  // timeout — one more holder loss from unreconstructable.  Runs on every
  // heartbeat carrying EC fields and on the housekeeping sweep (which is
  // what notices holders DYING — their entries leave ec_shards_ by
  // heartbeat-staleness pruning, not by a report).  Caller holds mu_.
  void CheckEcCoverageLocked();
  // THE heartbeat-freshness rule, shared by the ec_coverage alert and the
  // tpuft_ec_shard_coverage gauge so the two can never disagree.  Caller
  // holds mu_.
  bool HeartbeatFreshLocked(const std::string& id, TimePoint now) const;
  // Bounded alert history push shared by every alert kind.
  void PushAlertLocked(AlertRecord a);
  // -- goodput ledger + incident auto-capture (docs/wire.md) --------------
  // Folds one incarnation's last-reported ledger counters into the
  // cluster bank (called before its entry is pruned/evicted, so cluster
  // totals never go backwards under id churn).  ``undoable`` records the
  // banked amount so a RESUMING incarnation (long stall, not a death —
  // sweep prunes cannot tell the two apart) can have its bank share
  // subtracted before its monotonic counters re-ingest; evictions are
  // tombstoned against resume and bank without an undo entry.  Caller
  // holds mu_.
  void BankLedgerLocked(const std::string& id, bool undoable);
  // Cluster totals = bank + every live incarnation.  Caller holds mu_.
  void ClusterLedgerLocked(double* compute_s,
                           double lost_s[kLedgerCauseCount]) const;
  // One windowed cluster-goodput observation after a ledger-carrying
  // heartbeat: the goodput of the wall added since the previous
  // observation, EWMA'd; a dip below EWMA * TPUFT_GOODPUT_DIP_RATIO after
  // the warmup records a "goodput_floor" incident.  Caller holds mu_.
  void ObserveGoodputLocked();
  // Culprit attribution for the window just closed: per entity (live
  // replica incarnations + federated regions), delta its cumulative
  // ledger against the previous window's snapshot, score the delta's
  // lost seconds against the entity's own trailing per-window baseline
  // (EWMA), and blame the largest positive excess.  Updates last_attr_.
  // Caller holds mu_.
  void AttributeWindowLocked();
  // SLO burn-rate evaluation over the window just closed (d_compute /
  // d_lost = the window's accounted seconds).  No-op unless
  // TPUFT_SLO_TARGET is set; raises/refreshes/resolves the "slo_burn"
  // alert.  Caller holds mu_.
  void EvaluateSloLocked(double d_compute, double d_lost);
  // Bounded, debounced incident-trigger record (+ flight event).  `attr`
  // attaches culprit attribution (goodput_floor / slo_burn).  Caller
  // holds mu_.
  void RecordIncidentLocked(const std::string& reason,
                            const std::string& replica_id, double detail,
                            const IncidentAttribution* attr = nullptr);
  // Flight-records a sentinel hysteresis transition when prev != h.state.
  void RecordSentinelLocked(const std::string& id, int prev,
                            const ReplicaHealth& h);
  // Auto-drain attempt for a confirmed straggler / slow-link endpoint:
  // marks it draining via the cooperative path iff ``enabled`` (the
  // calling sentinel's auto-drain knob) and the remaining healthy count
  // stays above min_replicas.  Returns whether the replica is (now)
  // draining.  Retried on every later confirming observation, so a
  // rotation skipped at the capacity floor happens as soon as capacity
  // recovers.  Caller holds mu_.
  bool MaybeAutoDrainLocked(const std::string& id, bool log_skip, bool enabled);
  std::string StatusJson();
  std::string StatusHtml();
  // Prometheus text exposition for GET /metrics: quorum size/id/age,
  // per-replica step + step lag + heartbeat age, draining/tombstoned
  // counts, heal-in-progress and pending-join gauges (docs/wire.md).
  std::string MetricsText();
  // Housekeeping sweep (freshness-transition logs + graveyard prunes),
  // factored out of TickLocked so it can run on a bounded cadence instead
  // of once per quorum join.  Caller holds mu_.
  void SweepLocked(TimePoint tick_now, std::chrono::milliseconds hb_timeout);
  // -- federation internals ----------------------------------------------
  // Child push loop: builds + pushes the region digest on a fixed cadence
  // on its own thread (a slow root must not stall quorum ticks).
  void FederationLoop();
  // Snapshots this child's digest: every heartbeating id with its age,
  // joined/draining flags and step, plus the region ledger rollup.
  // Caller holds mu_.
  void BuildDigestLocked(RegionDigest* d);
  // Installs a root-returned global quorum on a child (same broadcast
  // discipline as TickLocked: set prev_quorum/quorum_id, clear the round's
  // participants, bump quorum_gen_, wake blocked joiners).  Caller holds
  // mu_.
  void InstallGlobalQuorumLocked(const Quorum& q, int64_t root_gen);
  // Root-side region staleness check (runs inside SweepLocked): a region
  // whose pushes stopped for a heartbeat timeout goes stale — its
  // participants drop from the current round and a "region_stale" incident
  // names it.  Caller holds mu_.
  void SweepRegionsLocked(TimePoint tick_now,
                          std::chrono::milliseconds hb_timeout);

  LighthouseOpt opt_;
  std::unique_ptr<RpcServer> server_;
  std::unique_ptr<HttpServer> http_;

  std::mutex mu_;
  std::condition_variable quorum_cv_;
  QuorumState state_;
  // Broadcast slot: generation bumps on every new quorum.
  int64_t quorum_gen_ = 0;
  std::optional<Quorum> latest_quorum_;
  // Dedup logging of quorum status changes
  // (reference ChangeLogger, src/lighthouse.rs:68-84).
  // Reasons already logged for the CURRENT membership situation; cleared
  // whenever quorum membership changes.  Plain last-value dedup was not
  // enough: during healthy steady state the tick alternates between the
  // waiting reason and the formed reason every round, which defeated it
  // (reference logs only on change, src/lighthouse.rs:68-84).
  std::set<std::string> logged_reasons_;
  // Replicas observed heartbeat-fresh on the previous tick, for logging
  // healthy<->stale transitions (failure-detection visibility).
  std::map<std::string, bool> last_fresh_;
  // Last housekeeping sweep (freshness-transition logs + graveyard prunes)
  // in TickLocked.  The sweep walks every per-replica map, and TickLocked
  // runs once per quorum JOIN on top of the timer tick — a rejoin wave of
  // N replicas (mass preemption) used to pay O(N) map scans N times per
  // round.  Throttled to a bounded cadence; quorum math still runs on
  // every call.
  TimePoint last_sweep_{};
  // Live per-replica training status carried on heartbeats (step/state
  // fields, wire method 2): feeds /metrics and /status.json.  Pruned with
  // the heartbeat graveyard so replica-id churn cannot grow them.
  std::map<std::string, int64_t> hb_step_;
  std::map<std::string, std::string> hb_state_;
  // Epoch ms when a replica's reported step last ADVANCED — the lighthouse's
  // view of its last commit (steps advance exactly on committed steps).
  std::map<std::string, int64_t> last_commit_ms_;
  // Per-replica allreduce payload GB/s from heartbeat field 6 (last
  // committed step's data-plane throughput; 0 = never reported).
  std::map<std::string, double> allreduce_gbps_;
  // Per-replica erasure-shard inventory from heartbeat fields 8-9:
  // (newest encode generation step, shards held at it).  Feeds the
  // tpuft_ec_shards_held gauge and the per-step tpuft_ec_shard_coverage
  // count (docs/wire.md "Erasure shard endpoints").
  std::map<std::string, std::pair<int64_t, int64_t>> ec_shards_;
  // Tombstones for supervisor-evicted incarnations (id -> evict time): a
  // dead incarnation's still-blocked quorum handler or in-flight heartbeat
  // must not re-register the corpse after EvictReplica dropped it.  Pruned
  // on the tick after 10x the heartbeat timeout (same horizon as the
  // heartbeat graveyard) — fresh incarnations carry new uuids, so exact-id
  // tombstones cannot block a legitimate rejoin.
  std::map<std::string, TimePoint> evicted_;
  // Announced drain deadlines (id -> epoch ms when the process will be
  // forcibly gone): a drain mark is never pruned before its deadline
  // passes, so a long grace period keeps its exclusion for the duration.
  std::map<std::string, int64_t> drain_deadline_ms_;
  // Shared secret for the mutating HTTP ops endpoints, from
  // TPUFT_ADMIN_TOKEN at Start; empty = loopback-only access.
  std::string admin_token_;
  // Straggler sentinel (docs/architecture.md "Straggler detection").
  // Rolling health per replica id, pruned with the heartbeat graveyard.
  std::map<std::string, ReplicaHealth> health_;
  // Alert history (newest last, bounded); active = resolved_ms == 0.
  std::vector<AlertRecord> alerts_;
  int64_t alert_seq_ = 0;
  // EC coverage sentinel state: the data-shard count k latched off
  // heartbeats (0 until any replica reports one), whether a nonzero shard
  // inventory was EVER reported (gates the alert so a pre-first-encode
  // cluster with EC configured never pages), and when coverage first
  // dipped below k + 1 (0 = not low) — the raise waits out one heartbeat
  // timeout so the per-holder rollover to a new encode generation (each
  // holder re-reports its count at the new step as its heartbeats land)
  // cannot flap an alert per encode.
  int64_t ec_k_ = 0;
  bool ec_seen_ = false;
  int64_t ec_low_since_ms_ = 0;
  // Knobs, read from the environment at Start:
  //   TPUFT_STRAGGLER_RATIO        slowness ratio threshold (default 1.5)
  //   TPUFT_STRAGGLER_GRACE_STEPS  consecutive step observations over/under
  //                                the threshold before promoting to
  //                                straggler / demoting back (default 5)
  //   TPUFT_STRAGGLER_AUTO_DRAIN   "1": a confirmed straggler is marked
  //                                draining (the PR-1 cooperative path) the
  //                                moment its alert raises, provided the
  //                                remaining healthy count stays above
  //                                min_replicas
  //   TPUFT_STRAGGLER_WARMUP_STEPS observations per incarnation before a
  //                                suspect may be promoted to straggler
  //                                (default 10): JIT warmup skews early
  //                                busy times asymmetrically and must not
  //                                raise alerts or trigger auto-drain
  double straggler_ratio_ = 1.5;
  int64_t straggler_grace_ = 5;
  bool straggler_auto_drain_ = false;
  int64_t straggler_warmup_ = 10;

  // Slow-link sentinel (docs/architecture.md "Data-plane observability").
  // Rolling per-replica outbound-edge health, pruned with the graveyard.
  std::map<std::string, LinkHealth> link_health_;
  // Knobs, read from the environment at Start:
  //   TPUFT_LINK_RATIO         outbound-goodput slowness ratio threshold
  //                            (cluster median / replica, default 4.0 —
  //                            deliberately loose: healthy send-blocked
  //                            time is near zero, so healthy goodput
  //                            readings are high-variance)
  //   TPUFT_LINK_GRACE_STEPS   consecutive step observations over/under
  //                            before promoting to degraded / demoting
  //                            (default 3)
  //   TPUFT_LINK_AUTO_DRAIN    "1": the degraded edge's RECEIVING endpoint
  //                            is marked draining when the alert raises
  //                            (never below min_replicas)
  //   TPUFT_LINK_WARMUP_STEPS  observations per incarnation before a
  //                            suspect may be promoted (default 3; first
  //                            steps mix rendezvous + warmup traffic)
  double link_ratio_ = 4.0;
  int64_t link_grace_ = 3;
  bool link_auto_drain_ = false;
  int64_t link_warmup_ = 3;

  // Goodput ledger (docs/wire.md "Goodput ledger"): per-incarnation
  // counters from heartbeat fields 14-16, pruned with the graveyard
  // (banked first), plus the cluster bank of departed incarnations.
  std::map<std::string, ReplicaLedger> ledger_;
  double ledger_banked_compute_ = 0.0;
  double ledger_banked_lost_[kLedgerCauseCount] = {0};
  // Sweep-banked amounts kept for UNDO (id -> (banked counters, bank
  // epoch ms)): a heartbeat resuming after a staleness prune re-reports
  // the SAME incarnation's monotonic counters, which would double-count
  // against its banked share.  Pruned on the tombstone horizon.
  std::map<std::string, std::pair<ReplicaLedger, int64_t>>
      ledger_banked_entries_;
  // Windowed cluster-goodput EWMA (the incident floor trigger) + the
  // previous observation's cluster totals closing each delta window.
  double goodput_ewma_ = -1.0;
  int64_t goodput_obs_ = 0;
  double goodput_prev_compute_ = 0.0;
  double goodput_prev_lost_ = 0.0;
  // Incident-trigger records (bounded, newest last) + per-(reason,
  // replica) debounce stamps.  Knobs, read at Start:
  //   TPUFT_GOODPUT_DIP_RATIO   windowed goodput below EWMA * ratio
  //                             records a goodput_floor incident
  //                             (default 0.9)
  //   TPUFT_GOODPUT_WARMUP_OBS  ledger observations before the floor
  //                             trigger may fire (default 8; early
  //                             windows mix JIT-skewed steps)
  std::vector<IncidentRecord> incidents_;
  int64_t incident_seq_ = 0;
  std::map<std::string, int64_t> incident_last_ms_;
  double goodput_dip_ratio_ = 0.9;
  int64_t goodput_warmup_ = 8;

  // -- culprit attribution (docs/observability.md) ------------------------
  // Per-entity window-delta state: cumulative counters at the previous
  // window close + a trailing EWMA baseline of per-window lost seconds
  // per cause.  Keyed by replica incarnation id (win_replicas_, pruned
  // when the id leaves ledger_) or region name (win_regions_).
  struct WindowDelta {
    double prev_compute = 0.0;
    double prev_lost[kLedgerCauseCount] = {0};
    double base_lost[kLedgerCauseCount] = {0};  // per-window baseline EWMA
    bool primed = false;  // first window seeds the baseline, never blames
  };
  std::map<std::string, WindowDelta> win_replicas_;
  std::map<std::string, WindowDelta> win_regions_;
  // Attribution of the newest closed window (replica == "" until any
  // window produced a positive excess).
  IncidentAttribution last_attr_;

  // -- SLO engine (docs/observability.md "SLO engine") --------------------
  // Knobs, read at Start:
  //   TPUFT_SLO_TARGET  goodput SLO target in (0, 1); unset/invalid
  //                     disables the engine entirely (default off)
  //   TPUFT_SLO_FAST_S  fast burn-rate window, accounted seconds (60)
  //   TPUFT_SLO_SLOW_S  slow burn-rate window, accounted seconds (600)
  // Burn rate = (window lost fraction) / (1 - target); the "slo_burn"
  // alert raises when BOTH windows burn > 1.0 and resolves when the fast
  // window drops below 1.0 (multi-window discipline: the slow window
  // confirms, the fast window gates paging latency both ways).
  double slo_target_ = 0.0;
  double slo_fast_s_ = 60.0;
  double slo_slow_s_ = 600.0;
  struct SloWindow {
    double compute_s = 0.0;
    double lost_s = 0.0;
  };
  std::deque<SloWindow> slo_windows_;  // newest last; pruned to slow_s
  double slo_burn_fast_ = 0.0;
  double slo_burn_slow_ = 0.0;
  double last_windowed_goodput_ = -1.0;

  // HA role state (SetRole).  Default: standalone permanent leader with no
  // lease (lease_expires_ms_ == 0 disables the serve-time expiry guard).
  bool role_leader_ = true;
  std::string leader_addr_;
  std::string leader_http_;
  int64_t leader_epoch_ = 0;
  int64_t lease_expires_ms_ = 0;
  // True when this instance may answer authoritatively RIGHT NOW: leader
  // role AND (no lease configured OR lease unexpired).  Caller holds mu_.
  bool IsLeaderLocked() const;
  // The standby-rejection message (kNotLeaderPrefix contract, wire.h).
  std::string NotLeaderErrLocked() const;

  // -- federation state (docs/wire.md "Federation") -----------------------
  // Child side: region name ("" = not a child), root address list, push
  // cadence, and the last installed root quorum generation (installs only
  // on advance, so a repeated push response cannot re-clear the round's
  // participants).
  bool fed_child_ = false;
  std::string fed_region_;
  std::string fed_root_addrs_;
  int64_t fed_push_interval_ms_ = 500;
  int64_t fed_digest_seq_ = 0;
  int64_t fed_root_gen_ = 0;
  int64_t fed_pushes_ok_ = 0;        // digests the root accepted
  int64_t fed_pushes_rejected_ = 0;  // fenced / not-applied responses
  std::thread fed_thread_;
  // Root side: one entry per region that has ever pushed (the federation
  // fan-in surface the /metrics region gauges render), plus the member-id
  // -> region owner map directives route through.  region_of_ is pruned
  // with the heartbeat graveyard.
  std::map<std::string, RegionEntry> regions_;
  std::map<std::string, std::string> region_of_;

  std::thread tick_thread_;
  bool shutdown_ = false;

  // -- control-plane observability (docs/architecture.md) -----------------
  // Always-on bounded black box: RPC spans + state transitions, served on
  // GET /debug/flight.json and dumped to $TPUFT_FLIGHT_DIR on Shutdown.
  FlightRecorder flight_;
  // Server-side handling latency per wire method (pre-populated for
  // methods 1-9 in the ctor so lookups never mutate the map).
  std::map<uint16_t, LatencyHistogram> rpc_hist_;
  // Round first-joiner -> formation latency, observed on every formation.
  LatencyHistogram quorum_formation_hist_;
  // Sum of heartbeat handling time between quorum ticks, observed once per
  // tick that handled at least one heartbeat (the fan-in cost ROADMAP
  // item 2's scale sweep measures vs replica count).
  LatencyHistogram heartbeat_fanin_hist_;
  std::atomic<int64_t> hb_fanin_accum_us_{0};
  std::atomic<int64_t> hb_fanin_count_{0};
  // /metrics self-observation: render duration of the PREVIOUS scrapes
  // (observed after the body is built, so it appears from scrape 2 on).
  LatencyHistogram scrape_hist_;
};

int64_t NowEpochMs();

}  // namespace tpuft
