// Lighthouse: global membership + quorum service.
//
// Reference parity: src/lighthouse.rs.  Tracks per-replica heartbeats, admits
// participants per quorum round, computes a quorum on a periodic tick (and on
// every join), bumps the quorum id only when membership changes, broadcasts
// the new quorum to every blocked Quorum RPC caller, serves an HTML/JSON
// dashboard, and can kill replicas through their Manager.
#pragma once

#include <condition_variable>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "tpuft.pb.h"
#include "wire.h"

namespace tpuft {

class HttpServer;

struct LighthouseOpt {
  // RPC bind address, e.g. "[::]:0".
  std::string bind = "[::]:0";
  // Dashboard HTTP bind address; empty disables the dashboard.
  std::string http_bind = "[::]:0";
  uint64_t min_replicas = 1;
  // How long to wait for stragglers after the first joiner of a round.
  // Reference default: 60 s (src/lighthouse.rs:97-102).
  uint64_t join_timeout_ms = 60000;
  // Reference default: 100 ms (src/lighthouse.rs:110-115).
  uint64_t quorum_tick_ms = 100;
  // Reference default: 5 s (src/lighthouse.rs:117-122).
  uint64_t heartbeat_timeout_ms = 5000;
};

// Pure quorum math, unit-testable without sockets.
// Reference parity: quorum_compute, src/lighthouse.rs:133-261.
struct QuorumState {
  struct Joined {
    QuorumMember member;
    TimePoint joined_at;
  };
  // Replicas that called Quorum this round, keyed by replica id.
  std::map<std::string, Joined> participants;
  // Last heartbeat seen per replica id (includes non-participants).
  std::map<std::string, TimePoint> heartbeats;
  // Replica ids departing cooperatively (drain notice received): excluded
  // from candidates AND from the healthy-set arithmetic (majority guard,
  // straggler wait), so the next quorum forms without them immediately.
  // Value: when the drain was announced (for pruning/status).
  std::map<std::string, TimePoint> draining;
  std::optional<Quorum> prev_quorum;
  int64_t quorum_id = 0;
};

// Returns the members of a valid quorum (sorted by replica id), or nullopt
// with `reason` describing what is still missing.
std::optional<std::vector<QuorumMember>> QuorumCompute(TimePoint now, const QuorumState& state,
                                                       const LighthouseOpt& opt,
                                                       std::string* reason);

class Lighthouse {
 public:
  explicit Lighthouse(LighthouseOpt opt);
  ~Lighthouse();

  bool Start(std::string* err);
  void Shutdown();
  std::string address() const;
  std::string http_address() const;

  // RPC handlers (public for in-process tests).
  Status HandleQuorum(const LighthouseQuorumRequest& req, Deadline deadline,
                      LighthouseQuorumResponse* resp, std::string* err);
  Status HandleHeartbeat(const LighthouseHeartbeatRequest& req);
  void FillStatus(LighthouseStatusResponse* resp);

  // Supervisor-assisted failure notification: drop a replica's heartbeat
  // and pending join immediately so the next quorum round does not spend
  // join_timeout waiting for a process the SUPERVISOR already knows is
  // dead (the heartbeat would otherwise look fresh for up to
  // heartbeat_timeout_ms).  `prefix` matches a full replica id or a
  // "<group>:" uuid-suffixed family.  Returns how many ids were dropped.
  int EvictReplica(const std::string& prefix);

  // Cooperative drain: a PLANNED departure announced before the process is
  // gone (maintenance events, preemption notices, SIGTERM grace periods).
  // Marks every id matching `prefix` (full id or "<group>:" family) as
  // draining: excluded from the NEXT quorum round immediately — no
  // join-timeout straggler wait, no heartbeat-timeout wait — and
  // tombstoned against late re-joins, while the id's in-flight step and
  // blocked handlers are left alone (unlike EvictReplica, which declares
  // the process already dead and aborts them).  The replacement
  // incarnation has a fresh ":<uuid>" suffix and joins normally.
  // `deadline_ms` is advisory (recorded for observability).  Returns how
  // many ids were marked.
  int DrainReplica(const std::string& prefix, int64_t deadline_ms);

  // Asks the replica's manager to exit. Used by the dashboard kill button.
  // Reference parity: src/lighthouse.rs:433-458.
  bool KillReplica(const std::string& replica_id, std::string* err);

 private:
  Status Dispatch(uint16_t method, const std::string& req, Deadline deadline, std::string* resp);
  // True when an ops-endpoint request may mutate state (docs/wire.md
  // "Trust model"): the shared-secret header matches TPUFT_ADMIN_TOKEN, or
  // no token is configured and the peer is loopback.
  bool AdminAllowed(const std::string& token, bool peer_loopback) const;
  void TickLoop();
  // Runs one quorum attempt; on success installs + broadcasts it.
  // Caller must hold mu_.
  void TickLocked();
  std::string StatusJson();
  std::string StatusHtml();
  // Prometheus text exposition for GET /metrics: quorum size/id/age,
  // per-replica step + step lag + heartbeat age, draining/tombstoned
  // counts, heal-in-progress and pending-join gauges (docs/wire.md).
  std::string MetricsText();

  LighthouseOpt opt_;
  std::unique_ptr<RpcServer> server_;
  std::unique_ptr<HttpServer> http_;

  std::mutex mu_;
  std::condition_variable quorum_cv_;
  QuorumState state_;
  // Broadcast slot: generation bumps on every new quorum.
  int64_t quorum_gen_ = 0;
  std::optional<Quorum> latest_quorum_;
  // Dedup logging of quorum status changes
  // (reference ChangeLogger, src/lighthouse.rs:68-84).
  // Reasons already logged for the CURRENT membership situation; cleared
  // whenever quorum membership changes.  Plain last-value dedup was not
  // enough: during healthy steady state the tick alternates between the
  // waiting reason and the formed reason every round, which defeated it
  // (reference logs only on change, src/lighthouse.rs:68-84).
  std::set<std::string> logged_reasons_;
  // Replicas observed heartbeat-fresh on the previous tick, for logging
  // healthy<->stale transitions (failure-detection visibility).
  std::map<std::string, bool> last_fresh_;
  // Live per-replica training status carried on heartbeats (step/state
  // fields, wire method 2): feeds /metrics and /status.json.  Pruned with
  // the heartbeat graveyard so replica-id churn cannot grow them.
  std::map<std::string, int64_t> hb_step_;
  std::map<std::string, std::string> hb_state_;
  // Epoch ms when a replica's reported step last ADVANCED — the lighthouse's
  // view of its last commit (steps advance exactly on committed steps).
  std::map<std::string, int64_t> last_commit_ms_;
  // Tombstones for supervisor-evicted incarnations (id -> evict time): a
  // dead incarnation's still-blocked quorum handler or in-flight heartbeat
  // must not re-register the corpse after EvictReplica dropped it.  Pruned
  // on the tick after 10x the heartbeat timeout (same horizon as the
  // heartbeat graveyard) — fresh incarnations carry new uuids, so exact-id
  // tombstones cannot block a legitimate rejoin.
  std::map<std::string, TimePoint> evicted_;
  // Announced drain deadlines (id -> epoch ms when the process will be
  // forcibly gone): a drain mark is never pruned before its deadline
  // passes, so a long grace period keeps its exclusion for the duration.
  std::map<std::string, int64_t> drain_deadline_ms_;
  // Shared secret for the mutating HTTP ops endpoints, from
  // TPUFT_ADMIN_TOKEN at Start; empty = loopback-only access.
  std::string admin_token_;

  std::thread tick_thread_;
  bool shutdown_ = false;
};

int64_t NowEpochMs();

}  // namespace tpuft
