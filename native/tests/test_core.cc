// Native-core unit tests.
//
// These port the semantics of the reference's Rust in-file tests — they are
// the spec for quorum math (src/lighthouse.rs:606-1038), recovery assignment
// (src/manager.rs:752-934), and the in-process Lighthouse+Manager end-to-end
// paths (src/lighthouse.rs:946-988, src/manager.rs:534-578).
#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cassert>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <random>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "lighthouse.h"
#include "manager.h"
#include "retry.h"
#include "store.h"
#include "wire.h"

using namespace tpuft;

#define CHECK(cond)                                                      \
  do {                                                                   \
    if (!(cond)) {                                                       \
      fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__, __LINE__, \
              #cond);                                                    \
      exit(1);                                                           \
    }                                                                    \
  } while (0)

namespace {

QuorumMember MakeMember(const std::string& id, int64_t step, uint64_t world_size = 1,
                        bool shrink_only = false) {
  QuorumMember m;
  m.set_replica_id(id);
  m.set_address("addr-" + id + ":1");
  m.set_store_address("store-" + id + ":2");
  m.set_step(step);
  m.set_world_size(world_size);
  m.set_shrink_only(shrink_only);
  return m;
}

void Join(QuorumState* s, const QuorumMember& m, TimePoint now) {
  s->participants[m.replica_id()] = QuorumState::Joined{m, now};
  s->heartbeats[m.replica_id()] = now;
}

// --- QuorumCompute -----------------------------------------------------------

void TestQuorumMinReplicas() {
  LighthouseOpt opt;
  opt.min_replicas = 2;
  opt.join_timeout_ms = 0;  // no straggler wait
  QuorumState s;
  auto now = Clock::now();
  Join(&s, MakeMember("a", 0), now);
  std::string reason;
  CHECK(!QuorumCompute(now, s, opt, &reason).has_value());
  Join(&s, MakeMember("b", 0), now);
  auto q = QuorumCompute(now, s, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 2);
  CHECK((*q)[0].replica_id() == "a");  // sorted
}

void TestQuorumHeartbeatExpiry() {
  LighthouseOpt opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 0;
  opt.heartbeat_timeout_ms = 1000;
  QuorumState s;
  auto now = Clock::now();
  Join(&s, MakeMember("a", 0), now);
  Join(&s, MakeMember("b", 0), now);
  // b's heartbeat goes stale: it drops out of the quorum.
  s.heartbeats["b"] = now - std::chrono::milliseconds(5000);
  std::string reason;
  auto q = QuorumCompute(now, s, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 1);
  CHECK((*q)[0].replica_id() == "a");
}

void TestQuorumJoinTimeoutStragglers() {
  // A healthy replica that has not re-joined blocks quorum until
  // join_timeout elapses.
  LighthouseOpt opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 60000;
  QuorumState s;
  auto now = Clock::now();
  Join(&s, MakeMember("a", 0), now);
  Join(&s, MakeMember("b", 0), now);
  s.heartbeats["c"] = now;  // healthy but not joined
  std::string reason;
  CHECK(!QuorumCompute(now, s, opt, &reason).has_value());
  CHECK(reason.find("straggler") != std::string::npos);
  // After join_timeout, proceed without the straggler.
  auto later = now + std::chrono::milliseconds(61000);
  s.heartbeats["a"] = later;
  s.heartbeats["b"] = later;
  s.heartbeats["c"] = later;
  auto q = QuorumCompute(later, s, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 2);
}

void TestQuorumFast() {
  // All members of the previous quorum re-joined: quorum forms immediately
  // even though join_timeout has not elapsed and a new healthy replica exists.
  LighthouseOpt opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 60000;
  QuorumState s;
  auto now = Clock::now();
  Quorum prev;
  prev.set_quorum_id(1);
  *prev.add_participants() = MakeMember("a", 5);
  *prev.add_participants() = MakeMember("b", 5);
  s.prev_quorum = prev;
  Join(&s, MakeMember("a", 5), now);
  Join(&s, MakeMember("b", 5), now);
  Join(&s, MakeMember("c", 0), now);  // new joiner rides along
  std::string reason;
  auto q = QuorumCompute(now, s, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 3);
  CHECK(reason.find("fast") != std::string::npos);
}

void TestQuorumShrinkOnly() {
  // shrink_only restricts membership to previous members even when a new
  // replica joins.
  LighthouseOpt opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 0;
  QuorumState s;
  auto now = Clock::now();
  Quorum prev;
  prev.set_quorum_id(3);
  *prev.add_participants() = MakeMember("a", 5);
  *prev.add_participants() = MakeMember("b", 5);
  s.prev_quorum = prev;
  Join(&s, MakeMember("a", 5, 1, /*shrink_only=*/true), now);
  Join(&s, MakeMember("b", 5), now);
  Join(&s, MakeMember("c", 0), now);
  std::string reason;
  auto q = QuorumCompute(now, s, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 2);
  CHECK((*q)[0].replica_id() == "a");
  CHECK((*q)[1].replica_id() == "b");
}

void TestQuorumSplitBrain() {
  // Only 1 of 3 heartbeating replicas joined: no majority, no quorum, even
  // after the join timeout.
  LighthouseOpt opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 0;
  QuorumState s;
  auto now = Clock::now();
  Join(&s, MakeMember("a", 0), now);
  s.heartbeats["b"] = now;
  s.heartbeats["c"] = now;
  std::string reason;
  CHECK(!QuorumCompute(now, s, opt, &reason).has_value());
  CHECK(reason.find("split brain") != std::string::npos);
  // 2 of 3 is a strict majority; with join_timeout=0 it proceeds.
  Join(&s, MakeMember("b", 0), now);
  auto q = QuorumCompute(now, s, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 2);
}

// --- ComputeQuorumResults ----------------------------------------------------

Quorum MakeQuorum(const std::vector<QuorumMember>& members, int64_t id = 7) {
  Quorum q;
  q.set_quorum_id(id);
  for (const auto& m : members) *q.add_participants() = m;
  return q;
}

void TestResultsHealthySteadyState() {
  auto q = MakeQuorum({MakeMember("a", 10), MakeMember("b", 10)});
  ManagerQuorumResponse r;
  std::string err;
  CHECK(ComputeQuorumResults("a", 0, q, true, false, &r, &err));
  CHECK(r.quorum_id() == 7);
  CHECK(r.replica_rank() == 0);
  CHECK(r.replica_world_size() == 2);
  CHECK(r.max_step() == 10);
  CHECK(r.max_world_size() == 2);
  CHECK(r.max_replica_rank() == 0);
  CHECK(!r.heal());
  CHECK(r.recover_dst_replica_ranks_size() == 0);
}

void TestResultsRecovery() {
  // b is behind: it heals from an up-to-date member; a learns it is a source.
  auto q = MakeQuorum({MakeMember("a", 10), MakeMember("b", 4), MakeMember("c", 10)});
  ManagerQuorumResponse ra, rb;
  std::string err;
  CHECK(ComputeQuorumResults("b", 0, q, true, false, &rb, &err));
  CHECK(rb.heal());
  CHECK(rb.max_step() == 10);
  CHECK(rb.max_replica_rank() == -1);  // not in the up-to-date set
  // recovering j=0 (which is b, index 1), group_rank 0 -> src = up_to_date[0] = a(0)
  CHECK(rb.recover_src_replica_rank() == 0);
  CHECK(rb.recover_src_manager_address() == "addr-a:1");

  CHECK(ComputeQuorumResults("a", 0, q, true, false, &ra, &err));
  CHECK(!ra.heal());
  CHECK(ra.recover_dst_replica_ranks_size() == 1);
  CHECK(ra.recover_dst_replica_ranks(0) == 1);
  // a is up-to-date rank 0 of 2.
  CHECK(ra.max_world_size() == 2);
  CHECK(ra.max_replica_rank() == 0);
}

void TestResultsRankStriping() {
  // Different local ranks stripe to different recovery sources and stores.
  auto q = MakeQuorum({MakeMember("a", 10), MakeMember("b", 4), MakeMember("c", 10)});
  ManagerQuorumResponse r0, r1;
  std::string err;
  CHECK(ComputeQuorumResults("b", 0, q, true, false, &r0, &err));
  CHECK(ComputeQuorumResults("b", 1, q, true, false, &r1, &err));
  CHECK(r0.recover_src_replica_rank() == 0);  // a
  CHECK(r1.recover_src_replica_rank() == 2);  // c
  CHECK(r0.store_address() == "store-a:2");
  CHECK(r1.store_address() == "store-b:2");
}

void TestResultsInitSync() {
  // Step 0 with init_sync: everyone but participant 0 heals from it.
  auto q = MakeQuorum({MakeMember("a", 0), MakeMember("b", 0)});
  ManagerQuorumResponse ra, rb;
  std::string err;
  CHECK(ComputeQuorumResults("a", 0, q, true, false, &ra, &err));
  CHECK(ComputeQuorumResults("b", 0, q, true, false, &rb, &err));
  CHECK(!ra.heal());
  CHECK(ra.recover_dst_replica_ranks_size() == 1);
  CHECK(rb.heal());
  CHECK(rb.recover_src_replica_rank() == 0);
  // init_sync=false skips the step-0 sync (reference: src/manager.rs init_sync tests).
  ManagerQuorumResponse rb2;
  CHECK(ComputeQuorumResults("b", 0, q, false, false, &rb2, &err));
  CHECK(!rb2.heal());
}

void TestResultsMultiDonor() {
  // Two donors at max_step: the recovering group gets the FULL ordered
  // donor rotation (primary first) for striped fetch + failover, and BOTH
  // donors open their serving windows for it.
  auto q = MakeQuorum({MakeMember("a", 10), MakeMember("b", 4), MakeMember("c", 10)});
  ManagerQuorumResponse ra, rb, rc;
  std::string err;
  CHECK(ComputeQuorumResults("b", 0, q, true, false, &rb, &err));
  CHECK(rb.heal());
  CHECK(rb.recover_src_replica_ranks_size() == 2);
  CHECK(rb.recover_src_manager_addresses_size() == 2);
  // Rotation leads with the primary assignment (same as the scalar field).
  CHECK(rb.recover_src_replica_ranks(0) == rb.recover_src_replica_rank());
  CHECK(rb.recover_src_manager_addresses(0) == rb.recover_src_manager_address());
  CHECK(rb.recover_src_replica_ranks(0) == 0);
  CHECK(rb.recover_src_replica_ranks(1) == 2);
  CHECK(rb.recover_src_manager_addresses(1) == "addr-c:1");
  // Primary-dst (field 11) stays primary-only: a is b's assigned donor, c
  // is not.  The _all set (field 14) makes EVERY up-to-date member open
  // its pull-serving window for the recovering group.
  CHECK(ComputeQuorumResults("a", 0, q, true, false, &ra, &err));
  CHECK(ComputeQuorumResults("c", 0, q, true, false, &rc, &err));
  CHECK(ra.recover_dst_replica_ranks_size() == 1);
  CHECK(ra.recover_dst_replica_ranks(0) == 1);
  CHECK(rc.recover_dst_replica_ranks_size() == 0);
  CHECK(ra.recover_dst_replica_ranks_all_size() == 1);
  CHECK(ra.recover_dst_replica_ranks_all(0) == 1);
  CHECK(rc.recover_dst_replica_ranks_all_size() == 1);
  CHECK(rc.recover_dst_replica_ranks_all(0) == 1);
  // Local rank 1 of the healer leads with the OTHER donor but still sees both.
  ManagerQuorumResponse rb1;
  CHECK(ComputeQuorumResults("b", 1, q, true, false, &rb1, &err));
  CHECK(rb1.recover_src_replica_ranks(0) == 2);
  CHECK(rb1.recover_src_replica_ranks(1) == 0);
}

void TestResultsForceRecover() {
  // force_recover makes an up-to-date replica heal anyway.
  auto q = MakeQuorum({MakeMember("a", 10), MakeMember("b", 10)});
  ManagerQuorumResponse r;
  std::string err;
  CHECK(ComputeQuorumResults("b", 0, q, true, true, &r, &err));
  CHECK(r.heal());
  CHECK(r.recover_src_replica_rank() == 0);
}

// --- End-to-end over real sockets -------------------------------------------

void TestLighthouseE2E() {
  LighthouseOpt opt;
  opt.bind = "127.0.0.1:0";
  opt.http_bind = "";
  opt.min_replicas = 2;
  opt.join_timeout_ms = 100;
  opt.quorum_tick_ms = 10;
  Lighthouse lh(opt);
  std::string err;
  CHECK(lh.Start(&err));

  auto join = [&](const std::string& id, LighthouseQuorumResponse* out) {
    RpcClient c(lh.address());
    CHECK(c.Connect(2000, &err) == Status::kOk);
    LighthouseQuorumRequest req;
    *req.mutable_requester() = MakeMember(id, 0);
    std::string payload, resp;
    req.SerializeToString(&payload);
    std::string cerr;
    Status st = c.Call(kLighthouseQuorum, payload, 5000, &resp, &cerr);
    CHECK(st == Status::kOk);
    CHECK(out->ParseFromString(resp));
  };

  LighthouseQuorumResponse qa, qb;
  std::thread ta([&] { join("a", &qa); });
  std::thread tb([&] { join("b", &qb); });
  ta.join();
  tb.join();
  CHECK(qa.quorum().participants_size() == 2);
  CHECK(qa.quorum().quorum_id() == qb.quorum().quorum_id());

  // Timeout path: a single joiner can't reach min_replicas.
  RpcClient c(lh.address());
  CHECK(c.Connect(2000, &err) == Status::kOk);
  LighthouseQuorumRequest req;
  *req.mutable_requester() = MakeMember("a", 1);
  std::string payload, resp, cerr;
  req.SerializeToString(&payload);
  auto t0 = Clock::now();
  Status st = c.Call(kLighthouseQuorum, payload, 300, &resp, &cerr);
  auto elapsed = std::chrono::duration_cast<std::chrono::milliseconds>(Clock::now() - t0);
  CHECK(st == Status::kDeadlineExceeded);
  CHECK(elapsed.count() < 2000);
  lh.Shutdown();
}

void TestManagerE2E() {
  LighthouseOpt lopt;
  lopt.bind = "127.0.0.1:0";
  lopt.http_bind = "";
  lopt.min_replicas = 1;
  lopt.join_timeout_ms = 50;
  lopt.quorum_tick_ms = 10;
  Lighthouse lh(lopt);
  std::string err;
  CHECK(lh.Start(&err));

  ManagerOpt mopt;
  mopt.replica_id = "group0";
  mopt.lighthouse_addr = lh.address();
  mopt.bind = "127.0.0.1:0";
  mopt.store_addr = "store0:1";
  mopt.world_size = 2;
  ManagerServer mgr(mopt);
  CHECK(mgr.Start(&err));

  // Both local ranks call quorum; the manager aggregates them into one
  // lighthouse join.
  auto call_quorum = [&](int64_t rank, ManagerQuorumResponse* out) {
    RpcClient c(mgr.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    ManagerQuorumRequest req;
    req.set_group_rank(rank);
    req.set_step(0);
    req.set_checkpoint_metadata("meta-rank" + std::to_string(rank));
    req.set_init_sync(true);
    std::string payload, resp;
    req.SerializeToString(&payload);
    Status st = c.Call(kManagerQuorum, payload, 5000, &resp, &cerr);
    if (st != Status::kOk) fprintf(stderr, "quorum rpc failed: %s\n", cerr.c_str());
    CHECK(st == Status::kOk);
    CHECK(out->ParseFromString(resp));
  };
  ManagerQuorumResponse q0, q1;
  std::thread t0([&] { call_quorum(0, &q0); });
  std::thread t1([&] { call_quorum(1, &q1); });
  t0.join();
  t1.join();
  CHECK(q0.replica_rank() == 0);
  CHECK(q0.replica_world_size() == 1);
  CHECK(!q0.heal());
  CHECK(q0.store_address() == "store0:1");
  CHECK(q1.store_address() == "store0:1");

  // Checkpoint metadata is stored per rank and served to peers.
  {
    RpcClient c(mgr.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    CheckpointMetadataRequest req;
    req.set_group_rank(1);
    std::string payload, resp;
    req.SerializeToString(&payload);
    CHECK(c.Call(kManagerCheckpointMetadata, payload, 2000, &resp, &cerr) == Status::kOk);
    CheckpointMetadataResponse out;
    CHECK(out.ParseFromString(resp));
    CHECK(out.checkpoint_metadata() == "meta-rank1");
  }

  // should_commit: all-yes commits, any-no aborts.
  auto vote = [&](int64_t rank, int64_t step, bool v, bool* decision) {
    RpcClient c(mgr.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    ShouldCommitRequest req;
    req.set_group_rank(rank);
    req.set_step(step);
    req.set_should_commit(v);
    std::string payload, resp;
    req.SerializeToString(&payload);
    CHECK(c.Call(kManagerShouldCommit, payload, 5000, &resp, &cerr) == Status::kOk);
    ShouldCommitResponse out;
    CHECK(out.ParseFromString(resp));
    *decision = out.should_commit();
  };
  bool d0 = false, d1 = false;
  std::thread v0([&] { vote(0, 1, true, &d0); });
  std::thread v1([&] { vote(1, 1, true, &d1); });
  v0.join();
  v1.join();
  CHECK(d0 && d1);
  std::thread v2([&] { vote(0, 2, true, &d0); });
  std::thread v3([&] { vote(1, 2, false, &d1); });
  v2.join();
  v3.join();
  CHECK(!d0 && !d1);
  // The same step can be re-voted after a failed round.
  std::thread v4([&] { vote(0, 2, true, &d0); });
  std::thread v5([&] { vote(1, 2, true, &d1); });
  v4.join();
  v5.join();
  CHECK(d0 && d1);

  mgr.Shutdown();
  lh.Shutdown();
}

void TestStoreE2E() {
  StoreServer store("127.0.0.1:0");
  std::string err;
  CHECK(store.Start(&err));
  RpcClient c(store.address());
  CHECK(c.Connect(2000, &err) == Status::kOk);

  StoreSetRequest set;
  set.set_key("k");
  set.set_value("v");
  std::string payload, resp, cerr;
  set.SerializeToString(&payload);
  CHECK(c.Call(kStoreSet, payload, 2000, &resp, &cerr) == Status::kOk);

  StoreGetRequest get;
  get.set_key("k");
  get.SerializeToString(&payload);
  CHECK(c.Call(kStoreGet, payload, 2000, &resp, &cerr) == Status::kOk);
  StoreGetResponse gout;
  CHECK(gout.ParseFromString(resp));
  CHECK(gout.found() && gout.value() == "v");

  // Blocking wait satisfied by a concurrent set.
  std::thread setter([&] {
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
    RpcClient c2(store.address());
    std::string e2;
    CHECK(c2.Connect(2000, &e2) == Status::kOk);
    StoreSetRequest s2;
    s2.set_key("later");
    s2.set_value("x");
    std::string p2, r2;
    s2.SerializeToString(&p2);
    CHECK(c2.Call(kStoreSet, p2, 2000, &r2, &e2) == Status::kOk);
  });
  StoreGetRequest wait_get;
  wait_get.set_key("later");
  wait_get.set_wait(true);
  wait_get.SerializeToString(&payload);
  CHECK(c.Call(kStoreGet, payload, 5000, &resp, &cerr) == Status::kOk);
  setter.join();

  // Wait timeout.
  StoreGetRequest missing;
  missing.set_key("never");
  missing.set_wait(true);
  missing.SerializeToString(&payload);
  CHECK(c.Call(kStoreGet, payload, 200, &resp, &cerr) == Status::kDeadlineExceeded);

  // Atomic add.
  StoreAddRequest add;
  add.set_key("ctr");
  add.set_delta(5);
  add.SerializeToString(&payload);
  CHECK(c.Call(kStoreAdd, payload, 2000, &resp, &cerr) == Status::kOk);
  StoreAddResponse aout;
  CHECK(aout.ParseFromString(resp));
  CHECK(aout.value() == 5);
  store.Shutdown();
}

// --- Retry backoff (reference semantics: src/retry.rs:49-99) -----------------

void TestRetryBackoff() {
  // Deterministic progression with jitter disabled: 1 -> 2 -> 4 -> 8 -> cap.
  ExponentialBackoff b(/*initial_ms=*/1, /*multiplier=*/2.0, /*max_ms=*/8,
                       /*jitter_ms=*/0);
  Deadline far = Deadline::FromMillis(60000);
  CHECK(b.next_ms() == 1);
  CHECK(b.Sleep(far));
  CHECK(b.next_ms() == 2);
  CHECK(b.Sleep(far));
  CHECK(b.next_ms() == 4);
  CHECK(b.Sleep(far));
  CHECK(b.next_ms() == 8);
  CHECK(b.Sleep(far));
  CHECK(b.next_ms() == 8);  // capped

  // An operation that fails twice then succeeds is attempted exactly 3 times
  // (the reference's retry_backoff contract).
  ExponentialBackoff b2(1, 2.0, 8, 1);
  Deadline dl = Deadline::FromMillis(60000);
  int attempts = 0;
  bool ok = false;
  do {
    attempts += 1;
    if (attempts >= 3) {
      ok = true;
      break;
    }
  } while (b2.Sleep(dl));
  CHECK(ok && attempts == 3);

  // A deadline with less time left than the next sleep stops retrying.
  ExponentialBackoff b3(50, 2.0, 100, 0);
  Deadline tight = Deadline::FromMillis(1);
  std::this_thread::sleep_for(std::chrono::milliseconds(2));
  CHECK(!b3.Sleep(tight));

  // Deadline accessors: 0 means "none" (never expires).
  Deadline none = Deadline::FromMillis(0);
  CHECK(!none.expired());
  CHECK(none.remaining_ms() == INT64_MAX);
  Deadline soon = Deadline::FromMillis(5);
  CHECK(soon.remaining_ms() <= 5);
  std::this_thread::sleep_for(std::chrono::milliseconds(10));
  CHECK(soon.expired());
  CHECK(soon.remaining_ms() == 0);
}

// --- Raw-frame helpers for wire-contract tests -------------------------------

bool RawCall(int fd, const FrameHeader& h, const std::string& payload) {
  std::string buf(reinterpret_cast<const char*>(&h), sizeof(h));
  buf += payload;
  size_t sent = 0;
  while (sent < buf.size()) {
    ssize_t r = send(fd, buf.data() + sent, buf.size() - sent, MSG_NOSIGNAL);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
  }
  return true;
}

bool RawRead(int fd, FrameHeader* h, std::string* payload) {
  char* p = reinterpret_cast<char*>(h);
  size_t got = 0;
  while (got < sizeof(*h)) {
    ssize_t r = recv(fd, p + got, sizeof(*h) - got, 0);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  if (h->magic != kFrameMagic || h->len > (1u << 20)) return false;
  payload->resize(h->len);
  got = 0;
  while (got < h->len) {
    ssize_t r = recv(fd, &(*payload)[got], h->len - got, 0);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

// The frame deadline is honored SERVER-side (the analogue of the reference's
// grpc-timeout header parsing, src/timeout.rs:18-61): a hand-written frame
// with deadline_ms=150 against a blocking store wait comes back
// DEADLINE_EXCEEDED from the server even though the client never times out.
void TestFrameDeadlinePropagation() {
  StoreServer store("127.0.0.1:0");
  std::string err;
  CHECK(store.Start(&err));
  int fd = DialTcp(store.address(), 2000, &err);
  CHECK(fd >= 0);

  StoreGetRequest get;
  get.set_key("never-set");
  get.set_wait(true);
  std::string payload;
  get.SerializeToString(&payload);

  FrameHeader h = {};
  h.magic = kFrameMagic;
  h.method = kStoreGet;
  h.status = 0;
  h.req_id = 7;
  h.deadline_ms = 150;
  h.len = static_cast<uint32_t>(payload.size());
  h.version = kWireVersion;
  auto t0 = Clock::now();
  CHECK(RawCall(fd, h, payload));
  FrameHeader rh;
  std::string rpayload;
  CHECK(RawRead(fd, &rh, &rpayload));  // no client-side deadline at all
  auto elapsed =
      std::chrono::duration_cast<std::chrono::milliseconds>(Clock::now() - t0).count();
  CHECK(static_cast<Status>(rh.status) == Status::kDeadlineExceeded);
  CHECK(rh.req_id == 7);
  CHECK(elapsed >= 100 && elapsed < 5000);
  close(fd);
  store.Shutdown();
}

// A mismatched wire version must fail loudly in both directions
// (docs/wire.md): servers answer FAILED_PRECONDITION and close; clients map a
// mismatched response the same way.
void TestWireVersionMismatch() {
  StoreServer store("127.0.0.1:0");
  std::string err;
  CHECK(store.Start(&err));

  // Client speaking version 0 (a pre-versioning build wrote 0 in this slot).
  int fd = DialTcp(store.address(), 2000, &err);
  CHECK(fd >= 0);
  StoreGetRequest get;
  get.set_key("k");
  std::string payload;
  get.SerializeToString(&payload);
  FrameHeader h = {};
  h.magic = kFrameMagic;
  h.method = kStoreGet;
  h.req_id = 1;
  h.len = static_cast<uint32_t>(payload.size());
  h.version = 0;
  CHECK(RawCall(fd, h, payload));
  FrameHeader rh;
  std::string rpayload;
  CHECK(RawRead(fd, &rh, &rpayload));
  CHECK(static_cast<Status>(rh.status) == Status::kFailedPrecondition);
  CHECK(rpayload.find("wire version mismatch") != std::string::npos);
  // ...and the server closes the connection afterwards (EOF or reset).
  char onebyte;
  CHECK(recv(fd, &onebyte, 1, 0) <= 0);
  close(fd);
  store.Shutdown();

  // Server speaking a FUTURE version: a raw listener echoes version 2; the
  // real client must reject it as FAILED_PRECONDITION, not misparse it.
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  CHECK(lfd >= 0);
  struct sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = 0;
  CHECK(bind(lfd, reinterpret_cast<struct sockaddr*>(&sa), sizeof(sa)) == 0);
  CHECK(listen(lfd, 1) == 0);
  socklen_t salen = sizeof(sa);
  CHECK(getsockname(lfd, reinterpret_cast<struct sockaddr*>(&sa), &salen) == 0);
  uint16_t port = ntohs(sa.sin_port);

  std::thread fake_server([&] {
    int cfd = accept(lfd, nullptr, nullptr);
    if (cfd < 0) return;
    FrameHeader req;
    std::string rq;
    if (RawRead(cfd, &req, &rq)) {
      FrameHeader resp = {};
      resp.magic = kFrameMagic;
      resp.method = req.method;
      resp.status = 0;
      resp.req_id = req.req_id;
      resp.len = 0;
      resp.version = 2;  // future
      RawCall(cfd, resp, "");
    }
    close(cfd);
  });

  RpcClient c("127.0.0.1:" + std::to_string(port));
  std::string cerr;
  CHECK(c.Connect(2000, &cerr) == Status::kOk);
  std::string resp;
  Status st = c.Call(kStoreGet, payload, 2000, &resp, &cerr);
  CHECK(st == Status::kFailedPrecondition);
  CHECK(cerr.find("wire version mismatch") != std::string::npos);
  fake_server.join();
  close(lfd);
}

// --- Join during shrink, end to end ------------------------------------------
// Ports the semantics of the reference's test_lighthouse_join_during_shrink
// (src/lighthouse.rs:1078-1181): a joiner whose quorum call lands during a
// shrink_only round is excluded from THAT quorum but stays queued and is
// admitted by the next normal round — its blocked RPC resolves with the
// 3-member quorum.
void TestJoinDuringShrink() {
  LighthouseOpt opt;
  opt.bind = "127.0.0.1:0";
  opt.http_bind = "";
  opt.min_replicas = 2;
  opt.join_timeout_ms = 1000;
  opt.quorum_tick_ms = 10;
  Lighthouse lh(opt);
  std::string err;
  CHECK(lh.Start(&err));

  auto join = [&](const std::string& id, int64_t step, bool shrink_only,
                  LighthouseQuorumResponse* out) {
    RpcClient c(lh.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    LighthouseQuorumRequest req;
    *req.mutable_requester() = MakeMember(id, step, 1, shrink_only);
    std::string payload, resp;
    req.SerializeToString(&payload);
    Status st = c.Call(kLighthouseQuorum, payload, 20000, &resp, &cerr);
    if (st != Status::kOk) fprintf(stderr, "join(%s) failed: %s\n", id.c_str(), cerr.c_str());
    CHECK(st == Status::kOk);
    CHECK(out->ParseFromString(resp));
  };

  // 1. First quorum: {a, b}.
  LighthouseQuorumResponse qa, qb, qjoin;
  std::thread t1a([&] { join("a", 1, false, &qa); });
  std::thread t1b([&] { join("b", 1, false, &qb); });
  t1a.join();
  t1b.join();
  CHECK(qa.quorum().participants_size() == 2);

  // 2. A fresh joiner's call lands first, then a shrink_only round runs.
  std::thread tj([&] { join("joiner", 1, false, &qjoin); });
  // Give the joiner time to register so the shrink round actually sees it.
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  std::thread t2a([&] { join("a", 2, true, &qa); });
  std::thread t2b([&] { join("b", 2, false, &qb); });
  t2a.join();
  t2b.join();
  CHECK(qa.quorum().participants_size() == 2);
  for (const auto& m : qa.quorum().participants()) CHECK(m.replica_id() != "joiner");

  // 3. Next normal round admits the queued joiner: quorum of 3, and the
  // joiner's original blocked call resolves with it.
  std::thread t3a([&] { join("a", 3, false, &qa); });
  std::thread t3b([&] { join("b", 3, false, &qb); });
  t3a.join();
  t3b.join();
  tj.join();
  CHECK(qa.quorum().participants_size() == 3);
  bool joiner_in = false;
  for (const auto& m : qa.quorum().participants())
    if (m.replica_id() == "joiner") joiner_in = true;
  CHECK(joiner_in);
  CHECK(qjoin.quorum().participants_size() == 3);
  CHECK(qjoin.quorum().quorum_id() == qa.quorum().quorum_id());

  lh.Shutdown();
}

// --- Supervisor-assisted eviction --------------------------------------------
// A dead replica whose heartbeat is still fresh blocks the next quorum (the
// healthy-majority guard counts the corpse) until heartbeat_timeout ages it
// out; EvictReplica (the launcher's failure notification) drops it so the
// round forms in tick time.  Also covers "<group>:" uuid-family prefix
// matching and idempotency.
void TestEvictSkipsStragglerWait() {
  LighthouseOpt opt;
  opt.bind = "127.0.0.1:0";
  opt.http_bind = "";
  opt.min_replicas = 1;
  opt.join_timeout_ms = 100;
  opt.quorum_tick_ms = 10;
  opt.heartbeat_timeout_ms = 5000;  // the wait evict must beat
  Lighthouse lh(opt);
  std::string err;
  CHECK(lh.Start(&err));

  auto join = [&](const std::string& id, int64_t step, LighthouseQuorumResponse* out) {
    RpcClient c(lh.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    LighthouseQuorumRequest req;
    *req.mutable_requester() = MakeMember(id, step);
    std::string payload, resp;
    req.SerializeToString(&payload);
    CHECK(c.Call(kLighthouseQuorum, payload, 20000, &resp, &cerr) == Status::kOk);
    CHECK(out->ParseFromString(resp));
  };

  // Round 1: group 1 alone (uuid-suffixed id, like real managers).
  LighthouseQuorumResponse q1;
  join("1:bbbb", 1, &q1);
  CHECK(q1.quorum().participants_size() == 1);

  // Group 1's process dies; its heartbeat is still fresh, so a NEW group's
  // join would be held by the healthy-majority guard (1 of 2 healthy
  // joined) until the corpse's heartbeat ages out at 5 s.  The
  // supervisor's evict removes it; prefix "1" matches the "1:bbbb" family.
  CHECK(lh.EvictReplica("1") == 1);
  CHECK(lh.EvictReplica("1") == 0);  // idempotent

  auto t0 = Clock::now();
  LighthouseQuorumResponse q2;
  join("0:aaaa", 2, &q2);
  auto elapsed =
      std::chrono::duration_cast<std::chrono::milliseconds>(Clock::now() - t0).count();
  CHECK(q2.quorum().participants_size() == 1);
  CHECK(q2.quorum().participants(0).replica_id() == "0:aaaa");
  CHECK(elapsed < 2000);  // far below the 5 s heartbeat staleness wait

  // The evicted family rejoins later as a fresh incarnation.
  CHECK(lh.EvictReplica("0") == 1);
  LighthouseQuorumResponse q3;
  join("1:cccc", 3, &q3);
  CHECK(q3.quorum().participants_size() == 1);
  CHECK(q3.quorum().participants(0).replica_id() == "1:cccc");

  // Tombstones: a ZOMBIE of an evicted incarnation (a join already in
  // flight when its process was reaped) must be rejected, not resurrect
  // the corpse into the healthy set.
  {
    RpcClient c(lh.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    LighthouseQuorumRequest req;
    *req.mutable_requester() = MakeMember("0:aaaa", 4);  // evicted id
    std::string payload, resp;
    req.SerializeToString(&payload);
    CHECK(c.Call(kLighthouseQuorum, payload, 5000, &resp, &cerr) == Status::kAborted);
    LighthouseHeartbeatRequest hb;
    hb.set_replica_id("0:aaaa");
    hb.SerializeToString(&payload);
    CHECK(c.Call(kLighthouseHeartbeat, payload, 2000, &resp, &cerr) == Status::kAborted);
  }

  // The Evict RPC itself (wire method 4 — what an external supervisor
  // uses): evicting the live "1:cccc" family over the wire.
  {
    RpcClient c(lh.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    LighthouseEvictRequest req;
    req.set_replica_prefix("1");
    std::string payload, resp;
    req.SerializeToString(&payload);
    CHECK(c.Call(kLighthouseEvict, payload, 2000, &resp, &cerr) == Status::kOk);
    LighthouseEvictResponse out;
    CHECK(out.ParseFromString(resp));
    CHECK(out.evicted() == 1);
  }

  lh.Shutdown();
}

// --- Cooperative drain -------------------------------------------------------
// A draining replica (planned departure announced) is invisible to quorum
// math: not a candidate AND not counted healthy, so the next round forms
// without any join-timeout or heartbeat-timeout wait while the departing
// process finishes its in-flight step undisturbed.
void TestQuorumComputeDraining() {
  LighthouseOpt opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 60000;  // the straggler wait drain must bypass
  QuorumState s;
  auto now = Clock::now();
  Quorum prev;
  prev.set_quorum_id(1);
  *prev.add_participants() = MakeMember("a", 5);
  *prev.add_participants() = MakeMember("b", 5);
  s.prev_quorum = prev;
  // Survivor a re-joins; b is draining with a FRESH heartbeat and a
  // pending join from the round the notice interrupted.
  Join(&s, MakeMember("a", 6), now);
  Join(&s, MakeMember("b", 6), now);
  s.draining["b"] = now;
  std::string reason;
  auto q = QuorumCompute(now, s, opt, &reason);
  // Without the draining mark this would block on the straggler wait
  // (b healthy, both joined -> fast quorum would need b... here b joined,
  // so contrast: mark makes b invisible even though it joined).
  CHECK(q.has_value());
  CHECK(q->size() == 1);
  CHECK((*q)[0].replica_id() == "a");

  // And when b has NOT re-joined (the common case: its train loop exited):
  s.participants.erase("b");
  q = QuorumCompute(now, s, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 1);

  // Split-brain arithmetic ignores draining ids too: one survivor out of
  // two heartbeating ids would otherwise NOT be a strict majority.
  QuorumState s2;
  Join(&s2, MakeMember("x", 3), now);
  s2.heartbeats["y"] = now;  // healthy, never joined
  s2.draining["y"] = now;
  q = QuorumCompute(now, s2, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 1);
  CHECK((*q)[0].replica_id() == "x");
}

// End-to-end drain through the server: the draining family is excluded
// from the next quorum immediately, its own late join is aborted, and the
// replacement incarnation (fresh uuid) is admitted normally.
void TestDrainCooperativeHandoff() {
  LighthouseOpt opt;
  opt.bind = "127.0.0.1:0";
  opt.http_bind = "";
  opt.min_replicas = 1;
  opt.join_timeout_ms = 100;
  opt.quorum_tick_ms = 10;
  opt.heartbeat_timeout_ms = 5000;  // the wait drain must beat
  Lighthouse lh(opt);
  std::string err;
  CHECK(lh.Start(&err));

  auto join = [&](const std::string& id, int64_t step, LighthouseQuorumResponse* out) {
    RpcClient c(lh.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    LighthouseQuorumRequest req;
    *req.mutable_requester() = MakeMember(id, step);
    std::string payload, resp;
    req.SerializeToString(&payload);
    CHECK(c.Call(kLighthouseQuorum, payload, 20000, &resp, &cerr) == Status::kOk);
    CHECK(out->ParseFromString(resp));
  };

  // Round 1: the departing group alone.
  LighthouseQuorumResponse q1;
  join("1:dddd", 7, &q1);
  CHECK(q1.quorum().participants_size() == 1);

  // Drain notice over the wire (method 5) — what the departing Manager
  // sends the moment its DrainWatcher fires.
  {
    RpcClient c(lh.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    LighthouseDrainRequest req;
    req.set_replica_prefix("1:dddd");
    req.set_deadline_ms(30000);
    std::string payload, resp;
    req.SerializeToString(&payload);
    CHECK(c.Call(kLighthouseDrain, payload, 2000, &resp, &cerr) == Status::kOk);
    LighthouseDrainResponse out;
    CHECK(out.ParseFromString(resp));
    CHECK(out.drained() == 1);
    // Idempotent: already marked.
    CHECK(c.Call(kLighthouseDrain, payload, 2000, &resp, &cerr) == Status::kOk);
    CHECK(out.ParseFromString(resp));
    CHECK(out.drained() == 0);
  }

  // A survivor's next quorum forms in tick time, NOT after the 5 s
  // heartbeat staleness wait the drainer's fresh heartbeat would force.
  auto t0 = Clock::now();
  LighthouseQuorumResponse q2;
  join("0:eeee", 8, &q2);
  auto elapsed =
      std::chrono::duration_cast<std::chrono::milliseconds>(Clock::now() - t0).count();
  CHECK(q2.quorum().participants_size() == 1);
  CHECK(q2.quorum().participants(0).replica_id() == "0:eeee");
  CHECK(elapsed < 2000);

  // The draining incarnation itself must not start a new round.
  {
    RpcClient c(lh.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    LighthouseQuorumRequest req;
    *req.mutable_requester() = MakeMember("1:dddd", 8);
    std::string payload, resp;
    req.SerializeToString(&payload);
    CHECK(c.Call(kLighthouseQuorum, payload, 5000, &resp, &cerr) == Status::kAborted);
    // Unlike eviction, its heartbeat stays accepted while it finishes the
    // in-flight step (the dashboard keeps showing it as draining).
    LighthouseHeartbeatRequest hb;
    hb.set_replica_id("1:dddd");
    hb.SerializeToString(&payload);
    CHECK(c.Call(kLighthouseHeartbeat, payload, 2000, &resp, &cerr) == Status::kOk);
  }

  // Status surfaces the drain.
  {
    RpcClient c(lh.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    std::string resp;
    CHECK(c.Call(kLighthouseStatus, "", 2000, &resp, &cerr) == Status::kOk);
    LighthouseStatusResponse st;
    CHECK(st.ParseFromString(resp));
    CHECK(st.draining_size() == 1);
    CHECK(st.draining(0) == "1:dddd");
  }

  // The replacement incarnation (same group prefix, fresh uuid) joins the
  // survivor normally — exact-id drain marks cannot block it.  The
  // replacement's join is registered FIRST (it alone is held by the
  // split-brain guard: 1 of 2 healthy), so the survivor's re-join
  // deterministically completes the round with both members.
  LighthouseQuorumResponse q3;
  std::thread replacement([&] { join("1:ffff", 0, &q3); });
  for (int i = 0; i < 200; ++i) {
    LighthouseStatusResponse st;
    lh.FillStatus(&st);
    bool pending = false;
    for (const auto& m : st.pending_participants())
      if (m.replica_id() == "1:ffff") pending = true;
    if (pending) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }
  LighthouseQuorumResponse q4;
  join("0:eeee", 9, &q4);
  replacement.join();
  CHECK(q3.quorum().participants_size() == 2);
  CHECK(q4.quorum().participants_size() == 2);

  // Family-prefix drain ("1" matches "1:ffff") for the supervisor-side
  // fallback path.
  CHECK(lh.DrainReplica("1", 0) == 1);

  lh.Shutdown();
}

// --- HTTP ops-endpoint trust model -------------------------------------------
// Mutating endpoints (kill/evict/drain) honor the shared-secret header;
// without a configured token they are loopback-only (docs/wire.md).
std::string HttpPost(const std::string& http_addr, const std::string& path,
                     const std::string& token) {
  // http_addr is "http://host:port".
  std::string hostport = http_addr.substr(7);
  std::string err;
  int fd = DialTcp(hostport, 2000, &err);
  CHECK(fd >= 0);
  // Mixed-case header NAME on purpose: names are case-insensitive (RFC
  // 9110) and clients capitalize them; the VALUE's case must be preserved.
  std::string req = "POST " + path + " HTTP/1.1\r\nHost: x\r\n" +
                    (token.empty() ? "" : "X-Tpuft-Token: " + token + "\r\n") +
                    "Content-Length: 0\r\n\r\n";
  CHECK(send(fd, req.data(), req.size(), 0) == static_cast<ssize_t>(req.size()));
  std::string out;
  char buf[4096];
  for (;;) {
    ssize_t r = recv(fd, buf, sizeof(buf), 0);
    if (r <= 0) break;
    out.append(buf, static_cast<size_t>(r));
  }
  close(fd);
  return out;
}

std::string HttpGet(const std::string& http_addr, const std::string& path) {
  std::string hostport = http_addr.substr(7);
  std::string err;
  int fd = DialTcp(hostport, 2000, &err);
  CHECK(fd >= 0);
  std::string req = "GET " + path + " HTTP/1.1\r\nHost: x\r\n\r\n";
  CHECK(send(fd, req.data(), req.size(), 0) == static_cast<ssize_t>(req.size()));
  std::string out;
  char buf[4096];
  for (;;) {
    ssize_t r = recv(fd, buf, sizeof(buf), 0);
    if (r <= 0) break;
    out.append(buf, static_cast<size_t>(r));
  }
  close(fd);
  return out;
}

// --- GET /metrics Prometheus exposition + heartbeat step/state fields --------
void TestMetricsExposition() {
  LighthouseOpt opt;
  opt.bind = "127.0.0.1:0";
  opt.http_bind = "127.0.0.1:0";
  opt.min_replicas = 1;
  opt.quorum_tick_ms = 20;
  Lighthouse lh(opt);
  std::string err;
  CHECK(lh.Start(&err));

  LighthouseHeartbeatRequest hb;
  hb.set_replica_id("0:aaaa");
  hb.set_step(5);
  hb.set_state("step");
  CHECK(lh.HandleHeartbeat(hb) == Status::kOk);
  hb.set_replica_id("1:bbbb");
  hb.set_step(2);
  hb.set_state("heal");
  CHECK(lh.HandleHeartbeat(hb) == Status::kOk);

  std::string m = HttpGet(lh.http_address(), "/metrics");
  CHECK(m.find("text/plain") != std::string::npos);
  CHECK(m.find("tpuft_replica_step{replica=\"0:aaaa\"} 5") != std::string::npos);
  CHECK(m.find("tpuft_replica_step_lag{replica=\"1:bbbb\"} 3") != std::string::npos);
  CHECK(m.find("tpuft_heal_in_progress 1") != std::string::npos);
  CHECK(m.find("tpuft_replicas_healthy 2") != std::string::npos);

  // Step advance = commit: lag closes, heal gauge clears, the last-commit
  // stamp appears for the healed replica.
  hb.set_replica_id("1:bbbb");
  hb.set_step(5);
  hb.set_state("step");
  CHECK(lh.HandleHeartbeat(hb) == Status::kOk);
  m = HttpGet(lh.http_address(), "/metrics");
  CHECK(m.find("tpuft_replica_step_lag{replica=\"1:bbbb\"} 0") != std::string::npos);
  CHECK(m.find("tpuft_heal_in_progress 0") != std::string::npos);
  CHECK(m.find("tpuft_replica_last_commit_age_seconds{replica=\"1:bbbb\"}") !=
        std::string::npos);

  // /status.json mirrors the live maps.
  std::string js = HttpGet(lh.http_address(), "/status.json");
  CHECK(js.find("\"replica_step\"") != std::string::npos);
  CHECK(js.find("\"1:bbbb\":5") != std::string::npos);
  CHECK(js.find("\"replica_state\"") != std::string::npos);
  CHECK(js.find("\"last_commit_ts_ms\"") != std::string::npos);

  // Eviction tombstones show, and the evicted id's series disappears.
  CHECK(lh.EvictReplica("1") == 1);
  m = HttpGet(lh.http_address(), "/metrics");
  CHECK(m.find("tpuft_replicas_tombstoned 1") != std::string::npos);
  CHECK(m.find("tpuft_replica_step{replica=\"1:bbbb\"}") == std::string::npos);

  lh.Shutdown();
}

// SetStatus rides the next heartbeat: the Python Manager's phase pushes
// reach the lighthouse within one heartbeat interval.
void TestManagerHeartbeatCarriesStatus() {
  LighthouseOpt lopt;
  lopt.bind = "127.0.0.1:0";
  lopt.http_bind = "";
  lopt.min_replicas = 1;
  Lighthouse lh(lopt);
  std::string err;
  CHECK(lh.Start(&err));

  ManagerOpt mopt;
  mopt.replica_id = "g0:x";
  mopt.lighthouse_addr = lh.address();
  mopt.bind = "127.0.0.1:0";
  mopt.heartbeat_interval_ms = 20;
  ManagerServer ms(mopt);
  CHECK(ms.Start(&err));
  ms.SetStatus(7, "step");

  auto deadline = Clock::now() + std::chrono::seconds(5);
  bool seen = false;
  while (Clock::now() < deadline && !seen) {
    LighthouseStatusResponse s;
    lh.FillStatus(&s);
    auto it = s.replica_step().find("g0:x");
    if (it != s.replica_step().end() && it->second == 7) {
      seen = true;
      auto st = s.replica_state().find("g0:x");
      CHECK(st != s.replica_state().end() && st->second == "step");
    } else {
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
  }
  CHECK(seen);
  ms.Shutdown();
  lh.Shutdown();
}

void TestHttpAdminGate() {
  // Token configured (mixed case: the value's case must survive header
  // parsing): remote AND loopback callers must present it.
  setenv("TPUFT_ADMIN_TOKEN", "SeKr1t", 1);
  {
    LighthouseOpt opt;
    opt.bind = "127.0.0.1:0";
    opt.http_bind = "127.0.0.1:0";
    opt.min_replicas = 1;
    Lighthouse lh(opt);
    std::string err;
    CHECK(lh.Start(&err));
    std::string denied = HttpPost(lh.http_address(), "/replica/1/evict", "");
    CHECK(denied.find("403") != std::string::npos);
    std::string wrong = HttpPost(lh.http_address(), "/replica/1/evict", "sekr1t");
    CHECK(wrong.find("403") != std::string::npos);
    std::string ok = HttpPost(lh.http_address(), "/replica/1/evict", "SeKr1t");
    CHECK(ok.find("200") != std::string::npos);
    std::string drain = HttpPost(lh.http_address(), "/replica/1/drain", "SeKr1t");
    CHECK(drain.find("200") != std::string::npos);
    lh.Shutdown();
  }
  unsetenv("TPUFT_ADMIN_TOKEN");
  // No token: loopback callers pass (the dashboard's own buttons), and
  // the evict/drain endpoints answer 200.
  {
    LighthouseOpt opt;
    opt.bind = "127.0.0.1:0";
    opt.http_bind = "127.0.0.1:0";
    opt.min_replicas = 1;
    Lighthouse lh(opt);
    std::string err;
    CHECK(lh.Start(&err));
    std::string ok = HttpPost(lh.http_address(), "/replica/1/drain", "");
    CHECK(ok.find("200") != std::string::npos);
    lh.Shutdown();
  }
}

// --- Straggler sentinel ------------------------------------------------------
// Heartbeats carrying step-time EWMAs drive the hysteresis state machine
// healthy -> suspect -> straggler and back, with the alert raised on
// /alerts.json and the scores exposed on /metrics (docs/wire.md).
void TestStragglerSentinel() {
  setenv("TPUFT_STRAGGLER_RATIO", "1.5", 1);
  setenv("TPUFT_STRAGGLER_GRACE_STEPS", "3", 1);
  setenv("TPUFT_STRAGGLER_AUTO_DRAIN", "0", 1);
  setenv("TPUFT_STRAGGLER_WARMUP_STEPS", "0", 1);
  LighthouseOpt opt;
  opt.bind = "127.0.0.1:0";
  opt.http_bind = "127.0.0.1:0";
  opt.min_replicas = 1;
  opt.quorum_tick_ms = 20;
  Lighthouse lh(opt);
  std::string err;
  CHECK(lh.Start(&err));
  auto hb = [&](const std::string& id, int64_t step, double ewma) {
    LighthouseHeartbeatRequest r;
    r.set_replica_id(id);
    r.set_step(step);
    r.set_state("step");
    r.set_step_time_ms_ewma(ewma);
    r.set_step_time_ms_last(ewma);
    CHECK(lh.HandleHeartbeat(r) == Status::kOk);
  };

  // On pace: both replicas report ~equal EWMAs.
  hb("0:fast", 1, 100.0);
  hb("1:slow", 1, 100.0);
  CHECK(lh.StragglerState("0:fast") == 0);
  CHECK(lh.StragglerState("1:slow") == 0);

  // One replica degrades to 3x the median: first over-threshold step makes
  // it suspect, grace consecutive steps confirm the straggler + raise the
  // alert.
  hb("1:slow", 2, 300.0);
  CHECK(lh.StragglerState("1:slow") == 1);
  hb("0:fast", 2, 100.0);
  CHECK(lh.StragglerState("0:fast") == 0);
  hb("1:slow", 3, 300.0);
  CHECK(lh.StragglerState("1:slow") == 1);
  hb("1:slow", 4, 300.0);
  CHECK(lh.StragglerState("1:slow") == 2);

  std::string m = HttpGet(lh.http_address(), "/metrics");
  CHECK(m.find("tpuft_straggler_state{replica=\"1:slow\"} 2") != std::string::npos);
  CHECK(m.find("tpuft_straggler_state{replica=\"0:fast\"} 0") != std::string::npos);
  CHECK(m.find("tpuft_replica_slowness_ratio{replica=\"1:slow\"} 3") != std::string::npos);
  CHECK(m.find("tpuft_replica_step_time_seconds{replica=\"1:slow\"} 0.3") != std::string::npos);
  CHECK(m.find("tpuft_stragglers 1") != std::string::npos);
  CHECK(m.find("tpuft_alerts_active 1") != std::string::npos);
  std::string a = HttpGet(lh.http_address(), "/alerts.json");
  CHECK(a.find("\"active\":1") != std::string::npos);
  CHECK(a.find("\"kind\":\"straggler\"") != std::string::npos);
  CHECK(a.find("\"replica_id\":\"1:slow\"") != std::string::npos);
  CHECK(a.find("\"resolved_ms\":0") != std::string::npos);
  std::string js = HttpGet(lh.http_address(), "/status.json");
  CHECK(js.find("\"straggler_state\"") != std::string::npos);
  CHECK(js.find("\"replica_step_time_ms\"") != std::string::npos);

  // Hysteresis down: grace consecutive on-pace steps clear the state and
  // resolve the alert.
  hb("1:slow", 5, 100.0);
  CHECK(lh.StragglerState("1:slow") == 2);
  hb("1:slow", 6, 100.0);
  hb("1:slow", 7, 100.0);
  CHECK(lh.StragglerState("1:slow") == 0);
  a = HttpGet(lh.http_address(), "/alerts.json");
  CHECK(a.find("\"active\":0") != std::string::npos);
  CHECK(a.find("\"resolved_ms\":0") == std::string::npos);

  lh.Shutdown();
  unsetenv("TPUFT_STRAGGLER_RATIO");
  unsetenv("TPUFT_STRAGGLER_GRACE_STEPS");
  unsetenv("TPUFT_STRAGGLER_AUTO_DRAIN");
  unsetenv("TPUFT_STRAGGLER_WARMUP_STEPS");
}

// Auto-drain: a confirmed straggler is marked draining (cooperative path)
// provided the remaining healthy set keeps the quorum floor.
void TestStragglerAutoDrain() {
  setenv("TPUFT_STRAGGLER_RATIO", "1.5", 1);
  setenv("TPUFT_STRAGGLER_GRACE_STEPS", "2", 1);
  setenv("TPUFT_STRAGGLER_AUTO_DRAIN", "1", 1);
  setenv("TPUFT_STRAGGLER_WARMUP_STEPS", "0", 1);
  LighthouseOpt opt;
  opt.bind = "127.0.0.1:0";
  opt.http_bind = "";
  opt.min_replicas = 1;
  opt.quorum_tick_ms = 20;
  Lighthouse lh(opt);
  std::string err;
  CHECK(lh.Start(&err));
  auto hb = [&](const std::string& id, int64_t step, double ewma) {
    LighthouseHeartbeatRequest r;
    r.set_replica_id(id);
    r.set_step(step);
    r.set_step_time_ms_ewma(ewma);
    CHECK(lh.HandleHeartbeat(r) == Status::kOk);
  };
  hb("0:fast", 1, 100.0);
  hb("1:slow", 1, 100.0);
  hb("1:slow", 2, 400.0);
  hb("1:slow", 3, 400.0);  // grace 2 -> straggler -> auto-drain fires
  LighthouseStatusResponse s;
  lh.FillStatus(&s);
  bool draining = false;
  for (const auto& id : s.draining()) draining = draining || id == "1:slow";
  CHECK(draining);
  // 2 healthy, min_replicas 1: the drain left the floor intact, and the
  // healthy survivor was never touched.
  for (const auto& id : s.draining()) CHECK(id != "0:fast");
  lh.Shutdown();
  unsetenv("TPUFT_STRAGGLER_RATIO");
  unsetenv("TPUFT_STRAGGLER_GRACE_STEPS");
  unsetenv("TPUFT_STRAGGLER_AUTO_DRAIN");
  unsetenv("TPUFT_STRAGGLER_WARMUP_STEPS");
}

// --- QuorumCompute property fuzz ---------------------------------------------
// Randomized join/leave/heartbeat/round sequences; the invariants the
// reference effectively specs with ~590 test lines (src/lighthouse.rs:606-1038):
//   1. a formed quorum is never below min_replicas;
//   2. every member is healthy (heartbeat younger than the timeout);
//   3. every member joined this round (is a participant);
//   4. a shrink_only round never admits anyone outside the previous quorum;
//   5. unless every previous member is present (fast quorum), membership is
//      a strict majority of everything healthy (split-brain guard).
void TestQuorumComputeFuzz() {
  std::mt19937 rng(0xf7);  // fixed seed: reproducible
  const std::vector<std::string> ids = {"r0", "r1", "r2", "r3", "r4", "r5"};
  auto hb = std::chrono::milliseconds(5000);

  for (int trial = 0; trial < 200; ++trial) {
    LighthouseOpt opt;
    opt.min_replicas = 1 + rng() % 3;
    opt.join_timeout_ms = rng() % 2 ? 0 : 60000;
    opt.heartbeat_timeout_ms = 5000;
    QuorumState s;
    auto now = Clock::now();

    for (int op = 0; op < 60; ++op) {
      const std::string& id = ids[rng() % ids.size()];
      switch (rng() % 5) {
        case 0:  // join (fresh heartbeat implied, like HandleQuorum)
          Join(&s, MakeMember(id, rng() % 10, 1, rng() % 4 == 0), now);
          break;
        case 1:  // heartbeat only
          s.heartbeats[id] = now;
          break;
        case 2:  // heartbeat expiry
          s.heartbeats[id] = now - hb * 2;
          break;
        case 3:  // participant withdraws (connection drop)
          s.participants.erase(id);
          break;
        case 4: {  // tick: try to form a quorum
          std::string reason;
          auto members = QuorumCompute(now, s, opt, &reason);
          if (!members) break;

          CHECK(members->size() >= opt.min_replicas);  // (1)

          std::set<std::string> healthy;
          for (const auto& [hid, last] : s.heartbeats)
            if (now - last < hb) healthy.insert(hid);
          std::set<std::string> prev_ids;
          if (s.prev_quorum)
            for (const auto& m : s.prev_quorum->participants())
              prev_ids.insert(m.replica_id());
          bool shrink = false;
          for (const auto& [pid, j] : s.participants)
            if (healthy.count(pid) && j.member.shrink_only()) shrink = true;

          std::set<std::string> member_ids;
          for (const auto& m : *members) {
            member_ids.insert(m.replica_id());
            CHECK(healthy.count(m.replica_id()) == 1);          // (2)
            CHECK(s.participants.count(m.replica_id()) == 1);   // (3)
            if (shrink && s.prev_quorum)
              CHECK(prev_ids.count(m.replica_id()) == 1);       // (4)
          }
          bool fast = s.prev_quorum && !prev_ids.empty() &&
                      std::all_of(prev_ids.begin(), prev_ids.end(),
                                  [&](const std::string& p) { return member_ids.count(p); });
          if (!fast) CHECK(members->size() * 2 > healthy.size());  // (5)

          // Round rollover, as TickLocked does.
          Quorum q;
          q.set_quorum_id(++s.quorum_id);
          for (const auto& m : *members) *q.add_participants() = m;
          s.prev_quorum = q;
          s.participants.clear();
          break;
        }
      }
    }
  }
}

}  // namespace

int main() {
  TestQuorumMinReplicas();
  TestQuorumHeartbeatExpiry();
  TestQuorumJoinTimeoutStragglers();
  TestQuorumFast();
  TestQuorumShrinkOnly();
  TestQuorumSplitBrain();
  TestResultsHealthySteadyState();
  TestResultsRecovery();
  TestResultsRankStriping();
  TestResultsInitSync();
  TestResultsMultiDonor();
  TestResultsForceRecover();
  TestLighthouseE2E();
  TestManagerE2E();
  TestStoreE2E();
  TestRetryBackoff();
  TestFrameDeadlinePropagation();
  TestWireVersionMismatch();
  TestJoinDuringShrink();
  TestEvictSkipsStragglerWait();
  TestQuorumComputeDraining();
  TestDrainCooperativeHandoff();
  TestHttpAdminGate();
  TestMetricsExposition();
  TestManagerHeartbeatCarriesStatus();
  TestStragglerSentinel();
  TestStragglerAutoDrain();
  TestQuorumComputeFuzz();
  printf("all native tests passed\n");
  return 0;
}
