// Native-core unit tests.
//
// These port the semantics of the reference's Rust in-file tests — they are
// the spec for quorum math (src/lighthouse.rs:606-1038), recovery assignment
// (src/manager.rs:752-934), and the in-process Lighthouse+Manager end-to-end
// paths (src/lighthouse.rs:946-988, src/manager.rs:534-578).
#include <cassert>
#include <chrono>
#include <cstdio>
#include <string>
#include <thread>
#include <vector>

#include "lighthouse.h"
#include "manager.h"
#include "store.h"
#include "wire.h"

using namespace tpuft;

#define CHECK(cond)                                                      \
  do {                                                                   \
    if (!(cond)) {                                                       \
      fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__, __LINE__, \
              #cond);                                                    \
      exit(1);                                                           \
    }                                                                    \
  } while (0)

namespace {

QuorumMember MakeMember(const std::string& id, int64_t step, uint64_t world_size = 1,
                        bool shrink_only = false) {
  QuorumMember m;
  m.set_replica_id(id);
  m.set_address("addr-" + id + ":1");
  m.set_store_address("store-" + id + ":2");
  m.set_step(step);
  m.set_world_size(world_size);
  m.set_shrink_only(shrink_only);
  return m;
}

void Join(QuorumState* s, const QuorumMember& m, TimePoint now) {
  s->participants[m.replica_id()] = QuorumState::Joined{m, now};
  s->heartbeats[m.replica_id()] = now;
}

// --- QuorumCompute -----------------------------------------------------------

void TestQuorumMinReplicas() {
  LighthouseOpt opt;
  opt.min_replicas = 2;
  opt.join_timeout_ms = 0;  // no straggler wait
  QuorumState s;
  auto now = Clock::now();
  Join(&s, MakeMember("a", 0), now);
  std::string reason;
  CHECK(!QuorumCompute(now, s, opt, &reason).has_value());
  Join(&s, MakeMember("b", 0), now);
  auto q = QuorumCompute(now, s, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 2);
  CHECK((*q)[0].replica_id() == "a");  // sorted
}

void TestQuorumHeartbeatExpiry() {
  LighthouseOpt opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 0;
  opt.heartbeat_timeout_ms = 1000;
  QuorumState s;
  auto now = Clock::now();
  Join(&s, MakeMember("a", 0), now);
  Join(&s, MakeMember("b", 0), now);
  // b's heartbeat goes stale: it drops out of the quorum.
  s.heartbeats["b"] = now - std::chrono::milliseconds(5000);
  std::string reason;
  auto q = QuorumCompute(now, s, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 1);
  CHECK((*q)[0].replica_id() == "a");
}

void TestQuorumJoinTimeoutStragglers() {
  // A healthy replica that has not re-joined blocks quorum until
  // join_timeout elapses.
  LighthouseOpt opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 60000;
  QuorumState s;
  auto now = Clock::now();
  Join(&s, MakeMember("a", 0), now);
  Join(&s, MakeMember("b", 0), now);
  s.heartbeats["c"] = now;  // healthy but not joined
  std::string reason;
  CHECK(!QuorumCompute(now, s, opt, &reason).has_value());
  CHECK(reason.find("straggler") != std::string::npos);
  // After join_timeout, proceed without the straggler.
  auto later = now + std::chrono::milliseconds(61000);
  s.heartbeats["a"] = later;
  s.heartbeats["b"] = later;
  s.heartbeats["c"] = later;
  auto q = QuorumCompute(later, s, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 2);
}

void TestQuorumFast() {
  // All members of the previous quorum re-joined: quorum forms immediately
  // even though join_timeout has not elapsed and a new healthy replica exists.
  LighthouseOpt opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 60000;
  QuorumState s;
  auto now = Clock::now();
  Quorum prev;
  prev.set_quorum_id(1);
  *prev.add_participants() = MakeMember("a", 5);
  *prev.add_participants() = MakeMember("b", 5);
  s.prev_quorum = prev;
  Join(&s, MakeMember("a", 5), now);
  Join(&s, MakeMember("b", 5), now);
  Join(&s, MakeMember("c", 0), now);  // new joiner rides along
  std::string reason;
  auto q = QuorumCompute(now, s, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 3);
  CHECK(reason.find("fast") != std::string::npos);
}

void TestQuorumShrinkOnly() {
  // shrink_only restricts membership to previous members even when a new
  // replica joins.
  LighthouseOpt opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 0;
  QuorumState s;
  auto now = Clock::now();
  Quorum prev;
  prev.set_quorum_id(3);
  *prev.add_participants() = MakeMember("a", 5);
  *prev.add_participants() = MakeMember("b", 5);
  s.prev_quorum = prev;
  Join(&s, MakeMember("a", 5, 1, /*shrink_only=*/true), now);
  Join(&s, MakeMember("b", 5), now);
  Join(&s, MakeMember("c", 0), now);
  std::string reason;
  auto q = QuorumCompute(now, s, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 2);
  CHECK((*q)[0].replica_id() == "a");
  CHECK((*q)[1].replica_id() == "b");
}

void TestQuorumSplitBrain() {
  // Only 1 of 3 heartbeating replicas joined: no majority, no quorum, even
  // after the join timeout.
  LighthouseOpt opt;
  opt.min_replicas = 1;
  opt.join_timeout_ms = 0;
  QuorumState s;
  auto now = Clock::now();
  Join(&s, MakeMember("a", 0), now);
  s.heartbeats["b"] = now;
  s.heartbeats["c"] = now;
  std::string reason;
  CHECK(!QuorumCompute(now, s, opt, &reason).has_value());
  CHECK(reason.find("split brain") != std::string::npos);
  // 2 of 3 is a strict majority; with join_timeout=0 it proceeds.
  Join(&s, MakeMember("b", 0), now);
  auto q = QuorumCompute(now, s, opt, &reason);
  CHECK(q.has_value());
  CHECK(q->size() == 2);
}

// --- ComputeQuorumResults ----------------------------------------------------

Quorum MakeQuorum(const std::vector<QuorumMember>& members, int64_t id = 7) {
  Quorum q;
  q.set_quorum_id(id);
  for (const auto& m : members) *q.add_participants() = m;
  return q;
}

void TestResultsHealthySteadyState() {
  auto q = MakeQuorum({MakeMember("a", 10), MakeMember("b", 10)});
  ManagerQuorumResponse r;
  std::string err;
  CHECK(ComputeQuorumResults("a", 0, q, true, false, &r, &err));
  CHECK(r.quorum_id() == 7);
  CHECK(r.replica_rank() == 0);
  CHECK(r.replica_world_size() == 2);
  CHECK(r.max_step() == 10);
  CHECK(r.max_world_size() == 2);
  CHECK(r.max_replica_rank() == 0);
  CHECK(!r.heal());
  CHECK(r.recover_dst_replica_ranks_size() == 0);
}

void TestResultsRecovery() {
  // b is behind: it heals from an up-to-date member; a learns it is a source.
  auto q = MakeQuorum({MakeMember("a", 10), MakeMember("b", 4), MakeMember("c", 10)});
  ManagerQuorumResponse ra, rb;
  std::string err;
  CHECK(ComputeQuorumResults("b", 0, q, true, false, &rb, &err));
  CHECK(rb.heal());
  CHECK(rb.max_step() == 10);
  CHECK(rb.max_replica_rank() == -1);  // not in the up-to-date set
  // recovering j=0 (which is b, index 1), group_rank 0 -> src = up_to_date[0] = a(0)
  CHECK(rb.recover_src_replica_rank() == 0);
  CHECK(rb.recover_src_manager_address() == "addr-a:1");

  CHECK(ComputeQuorumResults("a", 0, q, true, false, &ra, &err));
  CHECK(!ra.heal());
  CHECK(ra.recover_dst_replica_ranks_size() == 1);
  CHECK(ra.recover_dst_replica_ranks(0) == 1);
  // a is up-to-date rank 0 of 2.
  CHECK(ra.max_world_size() == 2);
  CHECK(ra.max_replica_rank() == 0);
}

void TestResultsRankStriping() {
  // Different local ranks stripe to different recovery sources and stores.
  auto q = MakeQuorum({MakeMember("a", 10), MakeMember("b", 4), MakeMember("c", 10)});
  ManagerQuorumResponse r0, r1;
  std::string err;
  CHECK(ComputeQuorumResults("b", 0, q, true, false, &r0, &err));
  CHECK(ComputeQuorumResults("b", 1, q, true, false, &r1, &err));
  CHECK(r0.recover_src_replica_rank() == 0);  // a
  CHECK(r1.recover_src_replica_rank() == 2);  // c
  CHECK(r0.store_address() == "store-a:2");
  CHECK(r1.store_address() == "store-b:2");
}

void TestResultsInitSync() {
  // Step 0 with init_sync: everyone but participant 0 heals from it.
  auto q = MakeQuorum({MakeMember("a", 0), MakeMember("b", 0)});
  ManagerQuorumResponse ra, rb;
  std::string err;
  CHECK(ComputeQuorumResults("a", 0, q, true, false, &ra, &err));
  CHECK(ComputeQuorumResults("b", 0, q, true, false, &rb, &err));
  CHECK(!ra.heal());
  CHECK(ra.recover_dst_replica_ranks_size() == 1);
  CHECK(rb.heal());
  CHECK(rb.recover_src_replica_rank() == 0);
  // init_sync=false skips the step-0 sync (reference: src/manager.rs init_sync tests).
  ManagerQuorumResponse rb2;
  CHECK(ComputeQuorumResults("b", 0, q, false, false, &rb2, &err));
  CHECK(!rb2.heal());
}

void TestResultsForceRecover() {
  // force_recover makes an up-to-date replica heal anyway.
  auto q = MakeQuorum({MakeMember("a", 10), MakeMember("b", 10)});
  ManagerQuorumResponse r;
  std::string err;
  CHECK(ComputeQuorumResults("b", 0, q, true, true, &r, &err));
  CHECK(r.heal());
  CHECK(r.recover_src_replica_rank() == 0);
}

// --- End-to-end over real sockets -------------------------------------------

void TestLighthouseE2E() {
  LighthouseOpt opt;
  opt.bind = "127.0.0.1:0";
  opt.http_bind = "";
  opt.min_replicas = 2;
  opt.join_timeout_ms = 100;
  opt.quorum_tick_ms = 10;
  Lighthouse lh(opt);
  std::string err;
  CHECK(lh.Start(&err));

  auto join = [&](const std::string& id, LighthouseQuorumResponse* out) {
    RpcClient c(lh.address());
    CHECK(c.Connect(2000, &err) == Status::kOk);
    LighthouseQuorumRequest req;
    *req.mutable_requester() = MakeMember(id, 0);
    std::string payload, resp;
    req.SerializeToString(&payload);
    std::string cerr;
    Status st = c.Call(kLighthouseQuorum, payload, 5000, &resp, &cerr);
    CHECK(st == Status::kOk);
    CHECK(out->ParseFromString(resp));
  };

  LighthouseQuorumResponse qa, qb;
  std::thread ta([&] { join("a", &qa); });
  std::thread tb([&] { join("b", &qb); });
  ta.join();
  tb.join();
  CHECK(qa.quorum().participants_size() == 2);
  CHECK(qa.quorum().quorum_id() == qb.quorum().quorum_id());

  // Timeout path: a single joiner can't reach min_replicas.
  RpcClient c(lh.address());
  CHECK(c.Connect(2000, &err) == Status::kOk);
  LighthouseQuorumRequest req;
  *req.mutable_requester() = MakeMember("a", 1);
  std::string payload, resp, cerr;
  req.SerializeToString(&payload);
  auto t0 = Clock::now();
  Status st = c.Call(kLighthouseQuorum, payload, 300, &resp, &cerr);
  auto elapsed = std::chrono::duration_cast<std::chrono::milliseconds>(Clock::now() - t0);
  CHECK(st == Status::kDeadlineExceeded);
  CHECK(elapsed.count() < 2000);
  lh.Shutdown();
}

void TestManagerE2E() {
  LighthouseOpt lopt;
  lopt.bind = "127.0.0.1:0";
  lopt.http_bind = "";
  lopt.min_replicas = 1;
  lopt.join_timeout_ms = 50;
  lopt.quorum_tick_ms = 10;
  Lighthouse lh(lopt);
  std::string err;
  CHECK(lh.Start(&err));

  ManagerOpt mopt;
  mopt.replica_id = "group0";
  mopt.lighthouse_addr = lh.address();
  mopt.bind = "127.0.0.1:0";
  mopt.store_addr = "store0:1";
  mopt.world_size = 2;
  ManagerServer mgr(mopt);
  CHECK(mgr.Start(&err));

  // Both local ranks call quorum; the manager aggregates them into one
  // lighthouse join.
  auto call_quorum = [&](int64_t rank, ManagerQuorumResponse* out) {
    RpcClient c(mgr.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    ManagerQuorumRequest req;
    req.set_group_rank(rank);
    req.set_step(0);
    req.set_checkpoint_metadata("meta-rank" + std::to_string(rank));
    req.set_init_sync(true);
    std::string payload, resp;
    req.SerializeToString(&payload);
    Status st = c.Call(kManagerQuorum, payload, 5000, &resp, &cerr);
    if (st != Status::kOk) fprintf(stderr, "quorum rpc failed: %s\n", cerr.c_str());
    CHECK(st == Status::kOk);
    CHECK(out->ParseFromString(resp));
  };
  ManagerQuorumResponse q0, q1;
  std::thread t0([&] { call_quorum(0, &q0); });
  std::thread t1([&] { call_quorum(1, &q1); });
  t0.join();
  t1.join();
  CHECK(q0.replica_rank() == 0);
  CHECK(q0.replica_world_size() == 1);
  CHECK(!q0.heal());
  CHECK(q0.store_address() == "store0:1");
  CHECK(q1.store_address() == "store0:1");

  // Checkpoint metadata is stored per rank and served to peers.
  {
    RpcClient c(mgr.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    CheckpointMetadataRequest req;
    req.set_group_rank(1);
    std::string payload, resp;
    req.SerializeToString(&payload);
    CHECK(c.Call(kManagerCheckpointMetadata, payload, 2000, &resp, &cerr) == Status::kOk);
    CheckpointMetadataResponse out;
    CHECK(out.ParseFromString(resp));
    CHECK(out.checkpoint_metadata() == "meta-rank1");
  }

  // should_commit: all-yes commits, any-no aborts.
  auto vote = [&](int64_t rank, int64_t step, bool v, bool* decision) {
    RpcClient c(mgr.address());
    std::string cerr;
    CHECK(c.Connect(2000, &cerr) == Status::kOk);
    ShouldCommitRequest req;
    req.set_group_rank(rank);
    req.set_step(step);
    req.set_should_commit(v);
    std::string payload, resp;
    req.SerializeToString(&payload);
    CHECK(c.Call(kManagerShouldCommit, payload, 5000, &resp, &cerr) == Status::kOk);
    ShouldCommitResponse out;
    CHECK(out.ParseFromString(resp));
    *decision = out.should_commit();
  };
  bool d0 = false, d1 = false;
  std::thread v0([&] { vote(0, 1, true, &d0); });
  std::thread v1([&] { vote(1, 1, true, &d1); });
  v0.join();
  v1.join();
  CHECK(d0 && d1);
  std::thread v2([&] { vote(0, 2, true, &d0); });
  std::thread v3([&] { vote(1, 2, false, &d1); });
  v2.join();
  v3.join();
  CHECK(!d0 && !d1);
  // The same step can be re-voted after a failed round.
  std::thread v4([&] { vote(0, 2, true, &d0); });
  std::thread v5([&] { vote(1, 2, true, &d1); });
  v4.join();
  v5.join();
  CHECK(d0 && d1);

  mgr.Shutdown();
  lh.Shutdown();
}

void TestStoreE2E() {
  StoreServer store("127.0.0.1:0");
  std::string err;
  CHECK(store.Start(&err));
  RpcClient c(store.address());
  CHECK(c.Connect(2000, &err) == Status::kOk);

  StoreSetRequest set;
  set.set_key("k");
  set.set_value("v");
  std::string payload, resp, cerr;
  set.SerializeToString(&payload);
  CHECK(c.Call(kStoreSet, payload, 2000, &resp, &cerr) == Status::kOk);

  StoreGetRequest get;
  get.set_key("k");
  get.SerializeToString(&payload);
  CHECK(c.Call(kStoreGet, payload, 2000, &resp, &cerr) == Status::kOk);
  StoreGetResponse gout;
  CHECK(gout.ParseFromString(resp));
  CHECK(gout.found() && gout.value() == "v");

  // Blocking wait satisfied by a concurrent set.
  std::thread setter([&] {
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
    RpcClient c2(store.address());
    std::string e2;
    CHECK(c2.Connect(2000, &e2) == Status::kOk);
    StoreSetRequest s2;
    s2.set_key("later");
    s2.set_value("x");
    std::string p2, r2;
    s2.SerializeToString(&p2);
    CHECK(c2.Call(kStoreSet, p2, 2000, &r2, &e2) == Status::kOk);
  });
  StoreGetRequest wait_get;
  wait_get.set_key("later");
  wait_get.set_wait(true);
  wait_get.SerializeToString(&payload);
  CHECK(c.Call(kStoreGet, payload, 5000, &resp, &cerr) == Status::kOk);
  setter.join();

  // Wait timeout.
  StoreGetRequest missing;
  missing.set_key("never");
  missing.set_wait(true);
  missing.SerializeToString(&payload);
  CHECK(c.Call(kStoreGet, payload, 200, &resp, &cerr) == Status::kDeadlineExceeded);

  // Atomic add.
  StoreAddRequest add;
  add.set_key("ctr");
  add.set_delta(5);
  add.SerializeToString(&payload);
  CHECK(c.Call(kStoreAdd, payload, 2000, &resp, &cerr) == Status::kOk);
  StoreAddResponse aout;
  CHECK(aout.ParseFromString(resp));
  CHECK(aout.value() == 5);
  store.Shutdown();
}

}  // namespace

int main() {
  TestQuorumMinReplicas();
  TestQuorumHeartbeatExpiry();
  TestQuorumJoinTimeoutStragglers();
  TestQuorumFast();
  TestQuorumShrinkOnly();
  TestQuorumSplitBrain();
  TestResultsHealthySteadyState();
  TestResultsRecovery();
  TestResultsRankStriping();
  TestResultsInitSync();
  TestResultsForceRecover();
  TestLighthouseE2E();
  TestManagerE2E();
  TestStoreE2E();
  printf("all native tests passed\n");
  return 0;
}
