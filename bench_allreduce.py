"""Allreduce data-plane benchmarks: the striped multi-lane ring + pipelined
bucket pipeline, measured end to end.

Three sections, written as one JSON artifact (``ALLREDUCE_BENCH.json``):

  lanes          — 2-rank TCPCollective under a shaped link
                   (``TPUFT_SHAPED_LINK``): a GradientAverager-style stream
                   of bucket allreduces for 1/2/4 lanes; GB/s = payload /
                   wall.  The per-peer LinkShaper budget is SHARED across
                   lanes (lanes cannot widen the modeled link), so lane
                   speedups here come only from overlap: stripe k's local
                   sum and encode/decode under stripe k+1's serialization,
                   bucket-to-bucket wire overlap, and per-frame half-RTT
                   hiding — the honest physics of parallel TCP streams on
                   one bottleneck path.  Each rank runs in its OWN
                   subprocess (the deployment shape: one process per
                   replica group) — in-process thread ranks share a GIL
                   and understate multi-lane overlap.

  e2e            — 2 full replica groups (real lighthouse + Managers, in
                   threads) training a synthetic step loop; pipelined
                   GradientAverager (per-bucket D2H + issue) vs the
                   monolithic reference path (one blocking fetch, then pack)
                   on the same shaped link and lane count — steps/s and
                   committed counts, plus the Manager's own
                   ``allreduce_gb_per_s`` step_summary telemetry.  The
                   ``--device-prep`` A/B adds the device-resident wire-prep
                   trial (on-device bf16 cast: the D2H fetch moves wire
                   bytes, ~half of f32) and a sharded-fetch trial on a
                   multi-device worker platform (``--sharded-devices``);
                   every e2e record carries ``d2h_bytes`` / ``h2d_bytes``
                   / ``wire_bytes`` / ``fetch_slices`` from the averager's
                   transfer accounting.

  peer_kill      — 3 replica groups, lanes > 1: one group dies mid-step
                   (collective aborted + manager gone).  The survivors'
                   in-flight allreduce must LATCH the error (not raise),
                   ``should_commit`` must fail cleanly, and the next quorum
                   must rebuild every lane against the shrunken world with
                   the old lane sockets closed (no fd leaks).

Run as
  python bench_allreduce.py [--mb 64] [--lanes 1 2 4] [--mbps 400]
                            [--rtt-ms 20] [--out ALLREDUCE_BENCH.json]
  python bench_allreduce.py --quick      # tier-1 smoke (small dict, 1 vs 2)
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from typing import Any, Dict, List, Optional

import numpy as np


def _shaped(mbps: float, rtt_ms: float):
    """Context manager setting TPUFT_SHAPED_LINK for the block."""
    import contextlib

    @contextlib.contextmanager
    def ctx():
        prior = os.environ.get("TPUFT_SHAPED_LINK")
        if mbps > 0:
            os.environ["TPUFT_SHAPED_LINK"] = f"{mbps}:{rtt_ms}"
        try:
            yield
        finally:
            if mbps > 0:
                if prior is None:
                    del os.environ["TPUFT_SHAPED_LINK"]
                else:
                    os.environ["TPUFT_SHAPED_LINK"] = prior

    return ctx()


def make_buckets(total_bytes: int, n_buckets: int) -> List[np.ndarray]:
    per = max(1, total_bytes // n_buckets // 4)
    return [np.full((per,), float(i), dtype=np.float32) for i in range(n_buckets)]


# ---------------------------------------------------------------------------
# Section 1: collective-level lane sweep
# ---------------------------------------------------------------------------


def _lane_rank_body(
    collective, rank: int, nbytes: int, n_buckets: int, timeout: float,
    world: int = 2,
) -> Dict[str, Any]:
    """One rank's bucket stream: issue every bucket, then drain — the
    GradientAverager traffic shape.  Shared by the threaded (--quick) and
    subprocess drivers."""
    buckets = make_buckets(nbytes, n_buckets)
    t0 = time.perf_counter()
    # The scaled bucket is a temporary — donate it so the native engine
    # reduces in place over the caller's buffer (zero working-buffer copy);
    # the Python engine ignores the hint, so the A/B stays same-workload.
    works = [
        collective.allreduce([b * (rank + 1)], op="sum", donate=True)
        for b in buckets
    ]
    outs = [w.wait(timeout=timeout) for w in works]
    wall = time.perf_counter() - t0
    expected_last = (n_buckets - 1) * world * (world + 1) / 2.0
    assert float(np.asarray(outs[0][0])[0]) == 0.0
    # Sanity tolerance scales with the sum: shaped links auto-select the
    # bf16 wire, whose per-hop quantization ulp grows with the magnitude
    # (at world 32 the bucket sum is ~5e2 and one bf16 ulp is ~2 — a fixed
    # 0.5 would flag correct arithmetic).
    tol = max(0.5, 0.02 * expected_last)
    assert abs(float(np.asarray(outs[-1][0])[0]) - expected_last) < tol
    return {"wall_s": wall, "lane_stats": collective.lane_stats(),
            "topology": collective.topology,
            "transport": collective.ring_transport}


def _lane_worker(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Subprocess entry for one lane-sweep rank (--worker lanes)."""
    from torchft_tpu.collectives import TCPCollective

    world = int(cfg.get("world", 2))
    c = TCPCollective(
        timeout=cfg["timeout"], wire_dtype=cfg["wire_dtype"], lanes=cfg["lanes"],
        topology=cfg.get("topology"), engine=cfg.get("engine"),
        transport=cfg.get("transport"),
    )
    try:
        c.configure(cfg["store"], cfg["rank"], world)
        return _lane_rank_body(
            c, cfg["rank"], cfg["nbytes"], cfg["n_buckets"], cfg["timeout"],
            world=world,
        )
    finally:
        c.shutdown()


def _spawn_workers(kind: str, cfgs: List[Dict[str, Any]], timeout: float) -> List[dict]:
    """Runs one worker subprocess per cfg (``--worker`` re-entry into this
    file), each writing its JSON result to a temp file — one OS process per
    rank, so lane worker threads never share a GIL across ranks."""
    import subprocess
    import sys
    import tempfile

    procs = []
    outs = []
    for cfg in cfgs:
        f = tempfile.NamedTemporaryFile(
            mode="w", suffix=".json", prefix="tpuft_bench_", delete=False
        )
        f.close()
        outs.append(f.name)
        cfg = dict(cfg, out=f.name)
        procs.append(
            subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--worker", kind, "--cfg", json.dumps(cfg)],
                env=dict(os.environ, JAX_PLATFORMS="cpu"),
            )
        )
    results = []
    try:
        for p, path in zip(procs, outs):
            rc = p.wait(timeout=timeout)
            with open(path) as fh:
                raw = fh.read()
            if rc != 0 or not raw.strip():
                raise RuntimeError(f"{kind} worker failed (rc={rc})")
            results.append(json.loads(raw))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for path in outs:
            try:
                os.unlink(path)
            except OSError:
                pass
    return results


def bench_lanes(
    payload_mb: float,
    lanes: int,
    mbps: float,
    rtt_ms: float,
    n_buckets: int = 8,
    wire_dtype: str = "auto",
    timeout: float = 300.0,
    procs: bool = True,
    trials: int = 1,
    world: int = 2,
    topology: Optional[str] = None,
    engine: Optional[str] = None,
    transport: Optional[str] = None,
) -> Dict[str, Any]:
    """``world``-rank bucketed allreduce stream at the given lane count and
    topology under the shaped link.  ``procs=True`` (the artifact path)
    runs each rank in its own subprocess; ``procs=False`` (--quick) keeps
    threads for speed.  ``trials`` > 1 reports the BEST wall of N runs —
    the modeled link is deterministic, so the best trial is the one least
    polluted by OS scheduler noise (the 2-core CI hosts this runs on
    context-switch a dozen bench threads; a single trial can lose 30% to an
    unlucky schedule).  ``topology`` pins the cross-group ring layout
    ("ring"/"ring2d"); None keeps the collective's default.  ``engine``
    pins the ring hot-loop engine ("py"/"native" — the A/B the engine
    sweep records); None keeps the collective's default (auto).  Returns
    wall + GB/s + lane byte counters (per-tier under ring2d) + the engine
    the configuration actually resolved to."""
    from torchft_tpu._native import StoreServer

    nbytes = int(payload_mb * (1 << 20))
    store = StoreServer(bind="127.0.0.1:0")
    per_rank: List[dict] = []
    walls: List[float] = []
    try:
        with _shaped(mbps, rtt_ms):
            if procs:
                for trial in range(max(1, trials)):
                    prefix = (
                        f"{store.address()}/lanes{lanes}_{wire_dtype}"
                        f"_{topology or 'default'}_{engine or 'auto'}"
                        f"_{transport or 'default'}_w{world}_t{trial}"
                    )
                    cfgs = [
                        {"store": prefix, "rank": r, "lanes": lanes,
                         "nbytes": nbytes, "n_buckets": n_buckets,
                         "wire_dtype": wire_dtype, "timeout": timeout,
                         "world": world, "topology": topology,
                         "engine": engine, "transport": transport}
                        for r in range(world)
                    ]
                    attempt = _spawn_workers("lanes", cfgs, timeout + 60)
                    wall = max(r["wall_s"] for r in attempt)
                    if not per_rank or wall < max(r["wall_s"] for r in per_rank):
                        per_rank = attempt
                    walls.append(wall)
            else:
                from torchft_tpu.collectives import TCPCollective

                for trial in range(max(1, trials)):
                    prefix = (
                        f"{store.address()}/lanes{lanes}_{wire_dtype}"
                        f"_{topology or 'default'}_{engine or 'auto'}"
                        f"_{transport or 'default'}_w{world}_t{trial}"
                    )
                    cols = [
                        TCPCollective(timeout=timeout, wire_dtype=wire_dtype,
                                      lanes=lanes, topology=topology,
                                      engine=engine, transport=transport)
                        for _ in range(world)
                    ]
                    results: Dict[int, dict] = {}
                    errors: List[BaseException] = []
                    try:
                        threads = [
                            threading.Thread(
                                target=cols[r].configure, args=(prefix, r, world)
                            )
                            for r in range(world)
                        ]
                        for t in threads:
                            t.start()
                        for t in threads:
                            t.join()

                        def run(rank: int, cols=cols, results=results,
                                errors=errors) -> None:
                            try:
                                results[rank] = _lane_rank_body(
                                    cols[rank], rank, nbytes, n_buckets,
                                    timeout, world=world,
                                )
                            except BaseException as e:  # noqa: BLE001
                                errors.append(e)

                        rs = [threading.Thread(target=run, args=(r,))
                              for r in range(world)]
                        for t in rs:
                            t.start()
                        for t in rs:
                            t.join()
                        if errors:
                            raise errors[0]
                    finally:
                        for c in cols:
                            c.shutdown()
                    attempt = [results[r] for r in range(world)]
                    wall = max(r["wall_s"] for r in attempt)
                    if not per_rank or wall < max(r["wall_s"] for r in per_rank):
                        per_rank = attempt
                    walls.append(wall)
    finally:
        store.shutdown()
    wall = max(r["wall_s"] for r in per_rank)
    actual = sum(b.nbytes for b in make_buckets(nbytes, n_buckets))
    out = {
        "section": "lanes",
        "lanes": lanes,
        "world": world,
        "topology": per_rank[0].get("topology", "ring"),
        # The ring hot-loop engine this configuration RESOLVED to ("py" or
        # "native") — requested "native" on a stale .so degrades to "py"
        # and the record says so, per the no-silent-fallback contract.
        "engine": per_rank[0]["lane_stats"].get("engine", "py"),
        # The ring-lane transport that actually ran ("shm" only when the
        # same-host handshake armed at least one segment) — requested shm
        # that degraded to tcp must land under the truth.
        "transport": per_rank[0].get("transport", "tcp"),
        "payload_mb": round(actual / (1 << 20), 2),
        "buckets": n_buckets,
        "wire_dtype": wire_dtype,
        "link": {"mbps": mbps, "rtt_ms": rtt_ms},
        "ranks": "subprocess" if procs else "threads",
        "wall_s": round(wall, 3),
        "gb_per_s": round(actual / 1e9 / wall, 4),
        # Per-lane wire bytes from rank 0 (striping balance evidence).
        "lane_bytes_sent": per_rank[0]["lane_stats"].get("sent"),
    }
    tiers = per_rank[0]["lane_stats"].get("tiers")
    if tiers:
        # Per-tier byte attribution under ring2d (row vs column traffic).
        out["tier_bytes_sent"] = {
            name: sum(t["sent"]) for name, t in tiers.items()
        }
    if len(walls) > 1:
        out["trial_walls_s"] = [round(w, 3) for w in walls]
    return out


def check_engine_parity(
    n_elems: int = 1 << 14, lanes: int = 2, timeout: float = 60.0
) -> Optional[bool]:
    """Bitwise engine parity on live rings: the SAME deterministic payload
    allreduced by a 2-rank py-engine pair and a 2-rank native-engine pair
    (f32 raw, bf16 wire, and the int8 codec) must produce IDENTICAL bits —
    the contract that lets "auto" switch engines without a numerics review.
    Returns None when the native engine is unavailable (nothing to
    compare), else the parity verdict.  The exhaustive topology x codec x
    lanes matrix lives in tests/test_ring_engine.py; this is the live
    artifact-level pin."""
    from torchft_tpu._native import StoreServer, ring_engine_available
    from torchft_tpu.collectives import TCPCollective

    if not ring_engine_available():
        return None
    rng = np.random.default_rng(1234)
    data = [
        (rng.standard_normal(n_elems) * (r + 1)).astype(np.float32)
        for r in range(2)
    ]
    outs: Dict[str, List[np.ndarray]] = {}
    store = StoreServer(bind="127.0.0.1:0")
    try:
        for engine in ("py", "native"):
            cols = [
                TCPCollective(timeout=timeout, wire_dtype="bf16", lanes=lanes,
                              engine=engine)
                for _ in range(2)
            ]
            results: Dict[int, List[np.ndarray]] = {}
            errors: List[BaseException] = []

            def run(rank: int, cols=cols, results=results, errors=errors,
                    engine=engine) -> None:
                try:
                    c = cols[rank]
                    c.configure(f"{store.address()}/parity_{engine}", rank, 2)
                    got: List[np.ndarray] = []
                    # f32 raw framing (compression off), the bf16 wire, and
                    # the int8 codec — one output set per hop codec.
                    got.append(c.allreduce(
                        [data[rank]], op="sum", allow_wire_compression=False
                    ).wait(timeout=timeout)[0])
                    got.append(c.allreduce(
                        [data[rank]], op="avg"
                    ).wait(timeout=timeout)[0])
                    got.append(c.allreduce(
                        [data[rank]], op="sum", wire_codec="int8"
                    ).wait(timeout=timeout)[0])
                    results[rank] = got
                except BaseException as e:  # noqa: BLE001 — re-raised
                    errors.append(e)

            threads = [threading.Thread(target=run, args=(r,)) for r in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            # Read BEFORE shutdown — abort clears the engine handle, so a
            # post-shutdown ring_engine always reports "py".
            resolved = cols[0].ring_engine
            for c in cols:
                c.shutdown()
            if errors:
                raise errors[0]
            if resolved != engine:
                return False  # requested engine did not run — not a parity proof
            outs[engine] = results[0]
    finally:
        store.shutdown()
    return all(
        a.dtype == b.dtype
        and a.shape == b.shape
        and bool((a.view(np.uint32) == b.view(np.uint32)).all())
        for a, b in zip(outs["py"], outs["native"])
    )


def run_engine_quick(
    payload_mb: float = 8.0, lanes: int = 2, trials: int = 3
) -> Dict[str, Any]:
    """The engine A/B smoke (``--engine both`` at a small unshaped-loopback
    cell, threads): one py cell, one native cell, plus the live bitwise
    parity pin.  Wired into
    tests/test_bench_contract.py::test_ring_engine_quick_smoke."""
    from torchft_tpu._native import ring_engine_available

    cells = [
        bench_lanes(payload_mb=payload_mb, lanes=lanes, mbps=0.0, rtt_ms=0.0,
                    n_buckets=4, timeout=120.0, procs=False, trials=trials,
                    engine="py")
    ]
    native_available = ring_engine_available()
    if native_available:
        cells.append(
            bench_lanes(payload_mb=payload_mb, lanes=lanes, mbps=0.0,
                        rtt_ms=0.0, n_buckets=4, timeout=120.0, procs=False,
                        trials=trials, engine="native")
        )
    by_engine = {c["engine"]: c for c in cells}
    out: Dict[str, Any] = {
        "section": "ring_engine",
        "native_available": native_available,
        "cells": cells,
        "parity_bitwise": check_engine_parity(),
    }
    if "py" in by_engine and "native" in by_engine:
        out["native_loopback_ok"] = (
            by_engine["native"]["gb_per_s"] >= by_engine["py"]["gb_per_s"]
        )
        out["native_loopback_speedup"] = round(
            by_engine["native"]["gb_per_s"] / by_engine["py"]["gb_per_s"], 2
        )
    return out


def check_transport_parity(
    n_elems: int = 1 << 14, lanes: int = 2, timeout: float = 60.0
) -> bool:
    """Bitwise transport parity on live rings: the SAME deterministic
    payload allreduced by a tcp pair and an shm pair (f32 raw, the int8
    codec, and the int4 codec) must produce IDENTICAL bits — the shm lane
    replaces the byte PIPE under the frame protocol, never the arithmetic,
    so any divergence is a framing bug."""
    from torchft_tpu._native import StoreServer
    from torchft_tpu.collectives import TCPCollective

    rng = np.random.default_rng(4321)
    data = [
        (rng.standard_normal(n_elems) * (r + 1)).astype(np.float32)
        for r in range(2)
    ]
    outs: Dict[str, List[np.ndarray]] = {}
    store = StoreServer(bind="127.0.0.1:0")
    try:
        for transport in ("tcp", "shm"):
            cols = [
                TCPCollective(timeout=timeout, lanes=lanes,
                              transport=transport)
                for _ in range(2)
            ]
            results: Dict[int, List[np.ndarray]] = {}
            errors: List[BaseException] = []

            def run(rank: int, cols=cols, results=results, errors=errors,
                    transport=transport) -> None:
                try:
                    c = cols[rank]
                    c.configure(
                        f"{store.address()}/tparity_{transport}", rank, 2
                    )
                    got: List[np.ndarray] = []
                    got.append(c.allreduce(
                        [data[rank]], op="sum", allow_wire_compression=False
                    ).wait(timeout=timeout)[0])
                    got.append(c.allreduce(
                        [data[rank]], op="sum", wire_codec="int8"
                    ).wait(timeout=timeout)[0])
                    got.append(c.allreduce(
                        [data[rank]], op="sum", wire_codec="int4"
                    ).wait(timeout=timeout)[0])
                    results[rank] = got
                except BaseException as e:  # noqa: BLE001 — re-raised
                    errors.append(e)

            threads = [threading.Thread(target=run, args=(r,)) for r in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            resolved = cols[0].ring_transport
            for c in cols:
                c.shutdown()
            if errors:
                raise errors[0]
            if resolved != transport:
                return False  # requested transport did not arm — not a proof
            outs[transport] = results[0]
    finally:
        store.shutdown()
    return all(
        a.dtype == b.dtype
        and a.shape == b.shape
        and bool((a.view(np.uint32) == b.view(np.uint32)).all())
        for a, b in zip(outs["tcp"], outs["shm"])
    )


def check_multi_stripe(
    n_elems: int = 1 << 16, lanes: int = 2, chunk_bytes: int = 32 << 10,
    ops: int = 4, timeout: float = 60.0,
) -> Optional[Dict[str, Any]]:
    """Pins the one-call native multi-stripe entry: a striped allreduce
    (many stripes per op at this chunk size) must cross the C API ONCE per
    op (``tf_ring_pass_multi``), not once per stripe — the per-stripe
    ctypes round-trips were pure Python overhead the batch entry removed.
    Counts ``RingEngine.pass_calls`` on rank 0 across ``ops`` back-to-back
    allreduces.  None when the native engine is unavailable."""
    from torchft_tpu._native import StoreServer, ring_engine_available
    from torchft_tpu.collectives import TCPCollective

    if not ring_engine_available():
        return None
    nstripes = max(1, (n_elems * 4 + chunk_bytes - 1) // chunk_bytes)
    store = StoreServer(bind="127.0.0.1:0")
    counts: Dict[int, int] = {}
    errors: List[BaseException] = []
    try:
        cols = [
            TCPCollective(timeout=timeout, lanes=lanes,
                          chunk_bytes=chunk_bytes, engine="native")
            for _ in range(2)
        ]

        def run(rank: int) -> None:
            try:
                c = cols[rank]
                c.configure(f"{store.address()}/multistripe", rank, 2)
                if c.ring_engine != "native":
                    return
                x = np.arange(n_elems, dtype=np.float32) * (rank + 1)
                for _ in range(ops):
                    c.allreduce([x], op="sum").wait(timeout=timeout)
                counts[rank] = c._engine.pass_calls
            except BaseException as e:  # noqa: BLE001 — re-raised
                errors.append(e)

        threads = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for c in cols:
            c.shutdown()
        if errors:
            raise errors[0]
    finally:
        store.shutdown()
    if 0 not in counts:
        return None  # native engine did not resolve
    return {
        "section": "multi_stripe",
        "ops": ops,
        "stripes_per_op": nstripes,
        "pass_calls": counts[0],
        "one_call_per_op": counts[0] == ops,
    }


def run_transport_quick(
    payload_mb: float = 4.0, lanes: int = 2, trials: int = 3
) -> Dict[str, Any]:
    """The same-host transport A/B (``--transport both`` at a small
    unshaped-loopback cell, threads): one tcp cell, one shm cell, the live
    bitwise parity pin, and the one-call multi-stripe pin.  Wired into
    tests/test_bench_contract.py::test_transport_quick_smoke.  shm moves
    stripe frames through a lock-free SPSC ring in /dev/shm instead of the
    kernel socket path — same frames, no syscalls per hop.

    The record carries ``cpu_count`` for the same honesty reason the
    engine-thread curve does: on a single-core host both transports
    bottleneck on scheduler alternation (loopback TCP and the shm ring
    each move bytes with two copies), so the A/B ratio there is noise
    around 1.0 rather than a transport signal — consumers should only
    read ``shm_ok`` as a regression gate when ``cpu_count > 1``."""
    cells = [
        bench_lanes(payload_mb=payload_mb, lanes=lanes, mbps=0.0, rtt_ms=0.0,
                    n_buckets=4, timeout=120.0, procs=False, trials=trials,
                    transport=t)
        for t in ("tcp", "shm")
    ]
    by_transport = {c["transport"]: c for c in cells}
    out: Dict[str, Any] = {
        "section": "transport",
        "cpu_count": os.cpu_count(),
        "cells": cells,
        "parity_bitwise": check_transport_parity(lanes=lanes),
        "multi_stripe": check_multi_stripe(lanes=lanes),
    }
    if "tcp" in by_transport and "shm" in by_transport:
        out["shm_ok"] = (
            by_transport["shm"]["gb_per_s"] >= by_transport["tcp"]["gb_per_s"]
        )
        out["shm_speedup"] = round(
            by_transport["shm"]["gb_per_s"] / by_transport["tcp"]["gb_per_s"], 2
        )
    return out


def bench_engine_threads(
    payload_mb: float = 4.0, lane_counts=(1, 2, 4), trials: int = 2,
) -> Dict[str, Any]:
    """GIL-liberation curve: the same THREADED 2-rank bucket stream at
    rising lane counts, Python engine vs native engine.  Both ranks and
    all lane workers share one process here, so the Python engine's lanes
    serialize on the GIL while the native engine's C++ lane threads run
    free — the native curve should hold or rise with lanes where the py
    curve flattens.  On a 1-core container BOTH flatten (nothing to run
    parallel on); the record carries ``cpu_count`` so readers can tell
    "GIL-bound" from "core-bound" honestly."""
    from torchft_tpu._native import ring_engine_available

    cells: List[Dict[str, Any]] = []
    engines = ["py"] + (["native"] if ring_engine_available() else [])
    for eng in engines:
        for lanes in lane_counts:
            r = bench_lanes(payload_mb=payload_mb, lanes=lanes, mbps=0.0,
                            rtt_ms=0.0, n_buckets=4, timeout=120.0,
                            procs=False, trials=trials, engine=eng)
            r["section"] = "engine_threads"
            cells.append(r)
    curve: Dict[str, Dict[str, float]] = {}
    for c in cells:
        curve.setdefault(c["engine"], {})[str(c["lanes"])] = c["gb_per_s"]
    return {
        "section": "engine_threads",
        "cpu_count": os.cpu_count(),
        "cells": cells,
        "gb_per_s": curve,
    }


# ---------------------------------------------------------------------------
# Section 2: end-to-end pipelined vs monolithic steps/s
# ---------------------------------------------------------------------------


def _grad_tree(total_bytes: int, n_leaves: int) -> Dict[str, Any]:
    """A jax pytree of f32 gradient-like leaves (device-backed so the
    pipelined D2H path does real work)."""
    import jax.numpy as jnp

    per = max(1, total_bytes // n_leaves // 4)
    return {
        f"layer_{i}.grad": jnp.full((per,), float(i % 7), dtype=jnp.float32)
        for i in range(n_leaves)
    }


def _make_grad_fn(compute_iters: int):
    """Per-leaf jitted 'backward' stand-in: each leaf's gradient is its own
    XLA execution, so leaves land asynchronously in issue order — the shape
    real per-layer backward has, and the overlap the pipelined bucket path
    exists to exploit (bucket 0 on the wire while leaf k is still
    computing).  ``compute_iters`` scales the per-leaf compute cost."""
    import jax
    import jax.numpy as jnp

    def leaf_grad(v, seed):
        x = v * seed
        for _ in range(compute_iters):
            x = jnp.sin(x) * 1.0001 + jnp.cos(x) * 0.0001
        return x

    jitted = jax.jit(leaf_grad)

    def grad_step(params: Dict[str, Any], seed: float) -> Dict[str, Any]:
        return {k: jitted(v, seed) for k, v in params.items()}

    return grad_step


def _e2e_group_body(
    lighthouse_addr: str,
    gid: int,
    lanes: int,
    pipelined: bool,
    steps: int,
    nbytes: int,
    n_leaves: int,
    bucket_mb: float,
    timeout_s: float,
    compute_iters: int = 0,
    device_prep: bool = False,
    sharded: bool = False,
    wire_dtype: str = "auto",
) -> Dict[str, Any]:
    """One replica group's training loop: compute per-leaf grads (when
    ``compute_iters`` > 0) -> start_quorum -> averager.allreduce(grads) ->
    should_commit, `steps` times.  Shared by the threaded (--quick) and
    subprocess drivers; the quorum round itself aligns group start across
    processes.  ``device_prep``/``sharded`` select the averager's
    device-resident wire prep and sharding-aware fetch modes (the A/B the
    ``--device-prep`` sweep measures); per-step d2h/h2d/wire bytes come
    from the averager's transfer accounting."""
    from torchft_tpu.collectives import TCPCollective
    from torchft_tpu.ddp import GradientAverager
    from torchft_tpu.manager import Manager

    collective = TCPCollective(timeout=timeout_s, lanes=lanes, wire_dtype=wire_dtype)
    manager = Manager(
        collective=collective,
        load_state_dict=None,
        state_dict=None,
        min_replica_size=2,
        use_async_quorum=True,
        timeout=timedelta(seconds=timeout_s),
        quorum_timeout=timedelta(seconds=timeout_s),
        rank=0,
        world_size=1,
        replica_id=f"g{gid}",
        lighthouse_addr=lighthouse_addr,
        init_sync=False,  # no transport; groups start identical
    )
    try:
        averager = GradientAverager(
            manager,
            bucket_bytes=int(bucket_mb * (1 << 20)),
            pipelined=pipelined,
            device_wire_prep=device_prep,
            sharded_fetch=sharded,
        )
        params = _grad_tree(nbytes, n_leaves)
        grad_fn = _make_grad_fn(compute_iters) if compute_iters else None
        if grad_fn is not None:
            # Compile + warm outside the timed window.
            import jax

            jax.block_until_ready(grad_fn(params, 1.0))
        committed = 0
        gbps = 0.0
        xfer = {"d2h_bytes": 0, "h2d_bytes": 0, "wire_bytes": 0, "slices": 0}
        slices_per_bucket = 0
        # First quorum outside the timed window: join/rendezvous cost is
        # startup, not steady-state data-plane throughput.
        manager.start_quorum()
        t0 = time.perf_counter()
        for step in range(steps):
            if step > 0:
                manager.start_quorum()
            # Fresh per-leaf gradient computation each step: leaves land
            # asynchronously, so the pipelined path puts bucket 0 on the
            # wire while later leaves are still computing — the monolithic
            # path must wait for the whole tree before the first byte moves.
            grads = grad_fn(params, 1.0 + 0.1 * step) if grad_fn else params
            averager.allreduce(grads)
            for k in xfer:
                xfer[k] += int(averager.last_stats.get(k, 0))
            ndev_buckets = int(averager.last_stats.get("device_buckets", 0))
            if ndev_buckets:
                # Measured shard factor — slices each bucket actually split
                # into this step (not the CLI's requested device count).
                slices_per_bucket = (
                    int(averager.last_stats.get("slices", 0)) // ndev_buckets
                )
            if manager.should_commit():
                committed += 1
            gbps = max(gbps, manager._ar_gbps)
        wall = time.perf_counter() - t0
        return {"committed": committed, "wall_s": wall, "gbps": gbps,
                "slices_per_bucket": slices_per_bucket, **xfer}
    finally:
        manager.shutdown()


def _e2e_worker(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Subprocess entry for one e2e replica group (--worker e2e)."""
    if cfg.get("virtual_devices"):
        # Must land before the first jax import: the sharded-fetch trial
        # needs a multi-device CPU platform in each worker process.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={cfg['virtual_devices']}"
            ).strip()
    return _e2e_group_body(
        cfg["lighthouse"], cfg["gid"], cfg["lanes"], cfg["pipelined"],
        cfg["steps"], cfg["nbytes"], cfg["n_leaves"], cfg["bucket_mb"],
        cfg["timeout_s"], cfg.get("compute_iters", 0),
        cfg.get("device_prep", False), cfg.get("sharded", False),
        cfg.get("wire_dtype", "auto"),
    )


def bench_e2e(
    lanes: int,
    pipelined: bool,
    steps: int,
    grads_mb: float,
    n_leaves: int,
    mbps: float,
    rtt_ms: float,
    bucket_mb: float = 4.0,
    timeout_s: float = 120.0,
    procs: bool = True,
    compute_iters: int = 0,
    trials: int = 1,
    device_prep: bool = False,
    sharded: bool = False,
    wire_dtype: str = "auto",
    virtual_devices: int = 0,
) -> Dict[str, Any]:
    """2 replica groups, real lighthouse + Managers; measures committed
    steps/s for the pipelined vs monolithic bucket path.  ``procs=True``
    (the artifact path) runs each group in its own subprocess; --quick
    keeps threads.  ``trials`` > 1 keeps the best (fastest-wall) trial —
    same scheduler-noise rationale as :func:`bench_lanes`: single e2e
    trials on a 2-core shared host vary by ±30%, far more than the
    pipelined-vs-monolithic effect being measured."""
    from torchft_tpu._native import LighthouseServer

    nbytes = int(grads_mb * (1 << 20))
    per_group: List[dict] = []
    walls: List[float] = []
    with _shaped(mbps, rtt_ms):
        if procs:
            for _trial in range(max(1, trials)):
                lighthouse = LighthouseServer(
                    bind="127.0.0.1:0", min_replicas=2,
                    join_timeout_ms=5000, quorum_tick_ms=20,
                )
                try:
                    cfgs = [
                        {"lighthouse": lighthouse.address(), "gid": g,
                         "lanes": lanes, "pipelined": pipelined,
                         "steps": steps, "nbytes": nbytes,
                         "n_leaves": n_leaves, "bucket_mb": bucket_mb,
                         "timeout_s": timeout_s,
                         "compute_iters": compute_iters,
                         "device_prep": device_prep, "sharded": sharded,
                         "wire_dtype": wire_dtype,
                         "virtual_devices": virtual_devices}
                        for g in range(2)
                    ]
                    attempt = _spawn_workers("e2e", cfgs, timeout_s + 120)
                finally:
                    lighthouse.shutdown()
                wall = max(r["wall_s"] for r in attempt)
                if not per_group or wall < max(r["wall_s"] for r in per_group):
                    per_group = attempt
                walls.append(wall)
        else:
            lighthouse = LighthouseServer(
                bind="127.0.0.1:0", min_replicas=2,
                join_timeout_ms=5000, quorum_tick_ms=20,
            )
            try:
                results: Dict[int, dict] = {}
                errors: List[BaseException] = []
                start_barrier = threading.Barrier(2)

                def group(gid: int) -> None:
                    try:
                        start_barrier.wait(timeout=timeout_s)
                        results[gid] = _e2e_group_body(
                            lighthouse.address(), gid, lanes, pipelined,
                            steps, nbytes, n_leaves, bucket_mb, timeout_s,
                            compute_iters, device_prep, sharded, wire_dtype,
                        )
                    except BaseException as e:  # noqa: BLE001 — re-raised
                        errors.append(e)

                threads = [
                    threading.Thread(target=group, args=(g,)) for g in range(2)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                if errors:
                    raise errors[0]
                per_group = [results[g] for g in range(2)]
            finally:
                lighthouse.shutdown()
    wall = max(r["wall_s"] for r in per_group)
    committed = min(r["committed"] for r in per_group)
    gbps_seen = [r["gbps"] for r in per_group if r["gbps"] > 0]
    mode = "pipelined" if pipelined else "monolithic"
    if device_prep:
        mode += "+device_prep"
    if sharded:
        mode += "+sharded"
    out = {
        "section": "e2e",
        "mode": mode,
        "device_prep": device_prep,
        "sharded_fetch": sharded,
        "wire_dtype": wire_dtype,
        # Per-host transfer accounting over the whole kept trial (group 0's
        # view; groups are symmetric): D2H fetch bytes, H2D scatter-back
        # bytes, and the payload bytes handed to the ring — with device
        # wire prep the d2h side reads wire (bf16) bytes, the ~2x the
        # artifact pins.
        "d2h_bytes": per_group[0].get("d2h_bytes", 0),
        "h2d_bytes": per_group[0].get("h2d_bytes", 0),
        "wire_bytes": per_group[0].get("wire_bytes", 0),
        "fetch_slices": per_group[0].get("slices", 0),
        "slices_per_bucket": per_group[0].get("slices_per_bucket", 0),
        "lanes": lanes,
        "grads_mb": grads_mb,
        "leaves": n_leaves,
        "bucket_mb": bucket_mb,
        "compute_iters": compute_iters,
        "link": {"mbps": mbps, "rtt_ms": rtt_ms},
        "ranks": "subprocess" if procs else "threads",
        "steps": steps,
        "committed": committed,
        "wall_s": round(wall, 3),
        "steps_per_s": round(committed / wall, 4) if wall > 0 else None,
        "allreduce_gb_per_s": round(max(gbps_seen), 4) if gbps_seen else None,
    }
    if len(walls) > 1:
        out["trial_walls_s"] = [round(w, 3) for w in walls]
    return out


# ---------------------------------------------------------------------------
# Section 3: mid-allreduce peer kill
# ---------------------------------------------------------------------------


def bench_peer_kill(
    lanes: int = 2,
    grads_mb: float = 16.0,
    mbps: float = 200.0,
    rtt_ms: float = 10.0,
    timeout_s: float = 60.0,
) -> Dict[str, Any]:
    """3 replica groups; group 2 dies mid-allreduce at step 1 (collective
    abort + manager shutdown, the in-process stand-in for kill -9).  Proves:
    survivors LATCH the error (no raise into the loop), should_commit fails
    cleanly, and the next quorum rebuilds every lane with the old lane
    sockets closed."""
    from torchft_tpu._native import LighthouseServer
    from torchft_tpu.checkpointing.http_transport import HTTPTransport
    from torchft_tpu.collectives import TCPCollective
    from torchft_tpu.ddp import GradientAverager
    from torchft_tpu.manager import Manager

    lighthouse = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=2, join_timeout_ms=1000,
        quorum_tick_ms=20, heartbeat_timeout_ms=1000,
    )
    nbytes = int(grads_mb * (1 << 20))
    evidence: Dict[str, Any] = {}
    errors: List[BaseException] = []
    barrier = threading.Barrier(3)
    victim_killed = threading.Event()

    def group(gid: int) -> None:
        manager = None
        collective = None
        try:
            collective = TCPCollective(timeout=timeout_s, lanes=lanes)
            # A real checkpoint transport + state dict: the survivors' retry
            # loops run independently, so one may commit a step the other
            # failed — the next quorum then assigns a heal, which must work
            # for the cluster to reconverge (the deployment shape).
            state: Dict[str, Any] = {"tensor": np.zeros(4, dtype=np.float32)}
            transport = HTTPTransport(timeout=timeout_s)
            manager = Manager(
                collective=collective,
                load_state_dict=lambda sd: state.update(sd),
                state_dict=lambda: dict(state),
                min_replica_size=2,
                use_async_quorum=True,
                timeout=timedelta(seconds=timeout_s),
                quorum_timeout=timedelta(seconds=timeout_s),
                rank=0,
                world_size=1,
                replica_id=f"k{gid}",
                lighthouse_addr=lighthouse.address(),
                checkpoint_transport=transport,
                init_sync=False,  # groups start identical
            )
            averager = GradientAverager(manager, bucket_bytes=4 << 20)
            grads = _grad_tree(nbytes, 8)
            barrier.wait(timeout=timeout_s)

            # Step 0: everyone commits (healthy 3-way quorum, all lanes up).
            manager.start_quorum()
            averager.allreduce(grads)
            ok0 = manager.should_commit()
            if gid == 0:
                evidence["step0_committed"] = ok0
                evidence["lanes_before"] = collective.lane_stats()["lanes"]

            if gid == 2:
                # The victim dies "mid-step": its sockets go away while the
                # survivors' stripes are in flight.
                def die() -> None:
                    evidence["kill_ts"] = time.time()
                    collective.abort()
                    victim_killed.set()

                threading.Timer(0.3, die).start()
                manager.start_quorum()
                averager.allreduce(grads)  # fails locally too; latched
                manager.should_commit()
                manager.shutdown()
                manager = None
                return

            # Survivors: step 1 overlaps the victim's death.
            old_next = list(collective._next_lanes)
            old_prev = list(collective._prev_lanes)
            manager.start_quorum()
            averager.allreduce(grads)  # must latch, not raise
            latched = manager.errored() is not None or collective.errored() is not None
            committed = manager.should_commit()
            if gid == 0:
                evidence["victim_kill_fired"] = victim_killed.is_set()
                evidence["step1_error_latched"] = bool(latched)
                evidence["step1_committed"] = committed

            # Next quorum: lighthouse drops the victim (heartbeat timeout),
            # survivors reconfigure as a 2-world with every lane rebuilt.
            deadline = time.monotonic() + timeout_s
            recovered = False
            while time.monotonic() < deadline and not recovered:
                manager.start_quorum()
                averager.allreduce(grads)
                recovered = manager.should_commit()
            if gid == 0:
                stats = collective.lane_stats()
                evidence["recovered_committed"] = recovered
                evidence["lanes_after"] = stats["lanes"]
                evidence["lanes_rebuilt"] = (
                    len(stats["sent"]) == lanes and len(stats["recv"]) == lanes
                )
                # No leaked sockets: abort()/configure closed every old lane
                # (closed sockets report fileno -1).
                evidence["old_lane_sockets_closed"] = all(
                    p.sock.fileno() == -1 for p in old_next + old_prev
                )
                # Fault-window hop bracketing: the sampled hop timeline is
                # the black box a post-mortem reads, so it must hold
                # records from BOTH sides of the kill — the pre-fault hops
                # banked when abort() tore the generation down AND hops
                # from the rebuilt lanes — or the window of interest is
                # exactly the part the recorder lost.
                hop_ts = [
                    r.get("ts", 0.0) for r in collective.hop_records()
                ]
                kill_ts = evidence.get("kill_ts")
                evidence["hop_timeline_records"] = len(hop_ts)
                evidence["hop_timeline_brackets_fault"] = bool(
                    hop_ts and kill_ts and min(hop_ts) < kill_ts < max(hop_ts)
                )
        except BaseException as e:  # noqa: BLE001 — re-raised below
            errors.append(e)
        finally:
            if manager is not None:
                manager.shutdown()

    with _shaped(mbps, rtt_ms):
        threads = [threading.Thread(target=group, args=(g,)) for g in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    lighthouse.shutdown()
    if errors:
        raise errors[0]
    evidence.update(
        {
            "section": "peer_kill",
            "lanes": lanes,
            "grads_mb": grads_mb,
            "ok": bool(
                evidence.get("step0_committed")
                and evidence.get("victim_kill_fired")
                and evidence.get("step1_error_latched")
                and evidence.get("step1_committed") is False
                and evidence.get("recovered_committed")
                and evidence.get("lanes_rebuilt")
                and evidence.get("old_lane_sockets_closed")
                and evidence.get("hop_timeline_brackets_fault")
            ),
        }
    )
    return evidence


# ---------------------------------------------------------------------------
# Drivers
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# Section: slow-link sentinel cell (data-plane flight recorder, PR 14)
# ---------------------------------------------------------------------------


def _scoped_env(overrides: Dict[str, Optional[str]]):
    """Context manager applying env overrides for the block (None = unset)."""
    import contextlib

    @contextlib.contextmanager
    def ctx():
        prior = {k: os.environ.get(k) for k in overrides}
        for k, v in overrides.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        try:
            yield
        finally:
            for k, v in prior.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    return ctx()


def bench_recorder_overhead(trials: int = 5, payload_mb: float = 2.0) -> Dict[str, Any]:
    """Hop-recorder cost guard: the same unshaped loopback bucket stream
    with the hop timeline ON (TPUFT_HOP_SAMPLE=1, the default) vs OFF (0).
    Unshaped because a modeled link hides microsecond recorder costs under
    millisecond pacing sleeps; loopback wall IS engine cost here.
    Best-of-N per side (scheduler noise on 1-2 core hosts dominates single
    trials).  ``impact`` = off-throughput / on-throughput; the committed
    artifact pins it under the <2%-overhead budget."""
    # Paired A/B: each trial runs off-then-on back to back and contributes
    # one off/on throughput RATIO; the reported impact is the MEDIAN of
    # those paired ratios.  Two back-to-back best-of-N blocks measure the
    # host's drift (page cache, scheduler settling), not the microsecond
    # recorder cost — pairing cancels slow drift, the median rejects the
    # occasional trial a context-switch storm ruins.
    ratios: List[float] = []
    best: Dict[str, float] = {"on": 0.0, "off": 0.0}
    for _ in range(trials):
        pair: Dict[str, float] = {}
        for label, sample in (("off", "0"), ("on", "1")):
            with _scoped_env({"TPUFT_HOP_SAMPLE": sample}):
                r = bench_lanes(payload_mb, 2, 0.0, 0.0, n_buckets=4,
                                timeout=60.0, procs=False, trials=1)
            pair[label] = r["gb_per_s"]
            best[label] = max(best[label], r["gb_per_s"])
        if pair["on"]:
            ratios.append(pair["off"] / pair["on"])
    ratios.sort()
    out: Dict[str, Any] = {
        "on_gb_per_s": round(best["on"], 4),
        "off_gb_per_s": round(best["off"], 4),
        "trials": trials,
    }
    out["impact"] = (
        round(ratios[len(ratios) // 2], 4) if ratios else None
    )
    return out


def _link_group_loop(
    gid: int,
    groups: int,
    lighthouse_addr: str,
    steps: int,
    payload_elems: int,
    degrade_at: Optional[int],
    degrade_mbps: float,
    rtt_ms: float,
    engine: Optional[str],
    out: Dict[str, Any],
) -> None:
    """One replica group of the link cell: real Manager + shaped
    TCPCollective, a commit loop moving one gradient payload per round.
    Group 0 is the victim: at round ``degrade_at`` it re-shapes its OWN
    outbound (next-direction) link ``degrade_mbps`` — the modeled analogue
    of the physical edge victim->successor degrading — with no
    reconfigure, which is exactly why the straggler sentinel cannot see
    it and the slow-link sentinel must."""
    from datetime import timedelta

    from torchft_tpu.checkpointing.http_transport import HTTPTransport
    from torchft_tpu.collectives import TCPCollective
    from torchft_tpu.manager import Manager

    state = {"w": np.zeros(8, dtype=np.float32)}
    collective = TCPCollective(timeout=30.0, lanes=2, engine=engine)
    manager = Manager(
        collective=collective,
        load_state_dict=lambda sd: state.update(sd),
        state_dict=lambda: dict(state),
        min_replica_size=groups,
        rank=0,
        world_size=1,
        replica_id=f"link{gid}",
        lighthouse_addr=lighthouse_addr,
        quorum_timeout=timedelta(seconds=60.0),
        timeout=timedelta(seconds=30.0),
        connect_timeout=timedelta(seconds=15.0),
        checkpoint_transport=HTTPTransport(timeout=30.0),
        init_sync=False,
    )
    payload = np.full((payload_elems,), 0.5 + gid, dtype=np.float32)
    commits: List[float] = []
    failed = 0
    degraded_ts: Optional[float] = None
    try:
        for step in range(steps):
            try:
                manager.start_quorum()
                fut = manager.allreduce(payload.copy())
                fut.result()
                if manager.should_commit():
                    commits.append(time.time())
                else:
                    failed += 1
            except Exception:  # noqa: BLE001 — recoverable control faults
                failed += 1
            if degrade_at is not None and gid == 0 and step + 1 == degrade_at:
                collective.set_link_shaping(degrade_mbps, rtt_ms)
                degraded_ts = time.time()
                manager.metrics.emit(
                    "link_shaped", mbps=degrade_mbps, rtt_ms=rtt_ms,
                    group=gid, step=step,
                )
        out["hop_records"] = collective.hop_records()
        out["lane_totals"] = collective.lane_totals()
    finally:
        out["replica_id"] = manager.replica_id()
        out["commits"] = commits
        out["failed"] = failed
        out["degraded_ts"] = degraded_ts
        manager.shutdown()


def _link_cell(
    groups: int,
    steps: int,
    payload_elems: int,
    mbps: float,
    rtt_ms: float,
    degrade_at: Optional[int],
    degrade_factor: float,
    engine: Optional[str],
    workdir: str,
    tag: str,
) -> Dict[str, Any]:
    """One live sentinel cell (healthy control when degrade_at is None):
    in-process native lighthouse + ``groups`` threaded real Managers whose
    heartbeats carry the link-health EWMAs; returns commit timelines, the
    lighthouse's link gauges/alerts, and the metrics-stream path for the
    attribution rollup."""
    import threading
    import urllib.request

    from torchft_tpu._native import LighthouseServer
    from torchft_tpu.metrics import MetricsLogger

    metrics_path = os.path.join(workdir, f"metrics_{tag}.jsonl")
    overrides = {
        "TPUFT_SHAPED_LINK": f"{mbps}:{rtt_ms}",
        "TPUFT_METRICS_PATH": metrics_path,
        # Tight sentinel tuning for a bounded cell: 2-step grace both
        # directions, 2-observation warmup, ratio 3 (the injected 10x
        # degradation scores ~10x below median — far past threshold).
        "TPUFT_LINK_RATIO": "3.0",
        "TPUFT_LINK_GRACE_STEPS": "2",
        "TPUFT_LINK_WARMUP_STEPS": "2",
        "TPUFT_LINK_AUTO_DRAIN": None,
        "TPUFT_HOP_SAMPLE": "1",
    }
    with _scoped_env(overrides):
        lighthouse = LighthouseServer(
            bind="127.0.0.1:0", min_replicas=groups, join_timeout_ms=10000,
            quorum_tick_ms=50, heartbeat_timeout_ms=5000,
        )
        driver_log = MetricsLogger(metrics_path, replica_id="bench-driver")
        outs: List[Dict[str, Any]] = [{} for _ in range(groups)]
        threads = [
            threading.Thread(
                target=_link_group_loop,
                args=(g, groups, lighthouse.address(), steps, payload_elems,
                      degrade_at, mbps / degrade_factor, rtt_ms, engine,
                      outs[g]),
                name=f"linkcell-{g}",
            )
            for g in range(groups)
        ]
        alerts_seen: List[dict] = []
        stop_poll = threading.Event()
        http = lighthouse.http_address()
        port = http.rsplit(":", 1)[1]

        def get_json(path: str) -> Optional[dict]:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5
                ) as resp:
                    return json.loads(resp.read().decode())
            except Exception:  # noqa: BLE001 — poller
                return None

        # Incident auto-capture: the alert raise also records a trigger on
        # /incident.json; bundle the live evidence the moment it appears
        # (the slow-link cell's half of the cross-plane capture contract).
        from torchft_tpu.obs import incident as obs_incident

        incident_watch = obs_incident.IncidentWatcher(f"http://127.0.0.1:{port}")
        incident_bundles: List[str] = []

        def poll_alerts() -> None:
            seen_ids = set()
            while not stop_poll.is_set():
                doc = get_json("/alerts.json")
                if doc:
                    for a in doc.get("alerts", []):
                        if a.get("kind") == "slow_link" and a["id"] not in seen_ids:
                            seen_ids.add(a["id"])
                            a = dict(a)
                            a["observed_ts"] = time.time()
                            alerts_seen.append(a)
                            driver_log.emit(
                                "link_alert", alert_id=a["id"],
                                src_replica_id=a.get("src_replica_id"),
                                alert_replica_id=a.get("replica_id"),
                                gbps=a.get("gbps"),
                            )
                for trig in incident_watch.poll():
                    try:
                        bundle = obs_incident.capture_bundle(
                            workdir, f"http://127.0.0.1:{port}", trig,
                            metrics_paths=[metrics_path],
                        )
                    except OSError:
                        # Transient capture failure: re-queue so the next
                        # poll tick retries.
                        incident_watch.unsee(trig.get("id"))
                        continue
                    if bundle not in incident_bundles:
                        incident_bundles.append(bundle)
                    driver_log.emit(
                        "incident_captured",
                        bundle=os.path.basename(bundle),
                        reason=trig.get("reason"),
                        incident_replica=trig.get("replica_id"),
                        incident_id=trig.get("id"),
                    )
                stop_poll.wait(0.2)

        poller = threading.Thread(target=poll_alerts, name="linkcell-poll")
        try:
            for t in threads:
                t.start()
            poller.start()
            for t in threads:
                t.join(timeout=600)
        finally:
            stop_poll.set()
            poller.join(timeout=5)
            metrics_text = None
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                ) as resp:
                    metrics_text = resp.read().decode()
            except Exception:  # noqa: BLE001
                pass
            driver_log.close()
            lighthouse.shutdown()
    link_gauges = {}
    if metrics_text:
        for line in metrics_text.splitlines():
            if line.startswith("tpuft_link") and not line.startswith("#"):
                name, _, value = line.rpartition(" ")
                link_gauges[name] = float(value)
    return {
        "groups": outs,
        "alerts": alerts_seen,
        "link_gauges": link_gauges,
        "metrics_path": metrics_path,
        "incident_bundles": incident_bundles,
    }


def run_link(
    groups: int = 3,
    steps: int = 30,
    payload_kb: int = 512,
    mbps: float = 100.0,
    rtt_ms: float = 5.0,
    degrade_at: int = 10,
    degrade_factor: float = 10.0,
    engine: Optional[str] = None,
    overhead_trials: int = 11,
    quick: bool = False,
    workdir: Optional[str] = None,
) -> Dict[str, Any]:
    """The slow-link sentinel cell (docs/architecture.md "Data-plane
    observability"):

    * ``healthy`` — the control run: same cluster, no fault; MUST raise
      zero slow_link alerts, and its round pace is the added-wall
      baseline.
    * ``degraded`` — at round ``degrade_at`` the victim's outbound link is
      re-shaped ``degrade_factor``x slower mid-run (no reconfigure, no
      process fault: invisible to heartbeat timeouts AND to the straggler
      sentinel's wall-minus-waits signal, which equalizes across the
      lockstep ring).  The cell measures detection latency in victim
      commit rounds and runs obs.report.link_attribution over both runs'
      step_summary streams: the ADDED wall must land in the
      wire/shaping/stall buckets, not combine.
    * ``overhead`` — the hop recorder's own cost on unshaped loopback
      (timeline on vs off), pinning the <2% budget.
    """
    import shutil
    import tempfile

    from torchft_tpu.obs.report import link_attribution, read_events

    own_workdir = workdir is None
    if own_workdir:
        workdir = tempfile.mkdtemp(prefix="tpuft_link_")
    overhead_mb = 24.0
    if quick:
        groups, steps, payload_kb = 3, 14, 192
        mbps, rtt_ms, degrade_at = 60.0, 4.0, 5
        overhead_trials, overhead_mb = 3, 2.0
    payload_elems = payload_kb * 1024 // 4
    try:
        healthy = _link_cell(
            groups, steps, payload_elems, mbps, rtt_ms, None, degrade_factor,
            engine, workdir, "healthy",
        )
        degraded = _link_cell(
            groups, steps, payload_elems, mbps, rtt_ms, degrade_at,
            degrade_factor, engine, workdir, "degraded",
        )
        overhead = bench_recorder_overhead(
            trials=overhead_trials, payload_mb=overhead_mb
        )

        def cell_summary(cell: Dict[str, Any]) -> Dict[str, Any]:
            events = read_events([cell["metrics_path"]])
            attr = link_attribution(events)
            commits = [len(g.get("commits") or []) for g in cell["groups"]]
            return {
                "commits": commits,
                "failed": [g.get("failed", 0) for g in cell["groups"]],
                "link_alerts": len(cell["alerts"]),
                "attribution": attr,
                "link_gauges": {
                    k: v for k, v in cell["link_gauges"].items()
                    if "state" in k or "ratio" in k
                },
            }

        h, d = cell_summary(healthy), cell_summary(degraded)
        victim = degraded["groups"][0]
        victim_rid = str(victim.get("replica_id", ""))
        degraded_ts = victim.get("degraded_ts")
        detection_rounds = None
        detected = bool(degraded["alerts"])
        if detected and degraded_ts:
            raise_s = degraded["alerts"][0]["raised_ms"] / 1000.0
            detection_rounds = sum(
                1 for ts in victim.get("commits") or []
                if degraded_ts <= ts <= raise_s
            )
        # Fault-window hop bracketing: the victim's sampled hop timeline
        # must carry records from before AND after the mid-run re-shaping
        # — the shape change never tears a lane down, so a timeline gap
        # around the fault would mean the sampler (not the fault) went
        # quiet exactly when the post-mortem needs it.
        victim_hop_ts = [
            r.get("ts", 0.0) for r in victim.get("hop_records") or []
        ]
        hop_brackets_fault = bool(
            victim_hop_ts
            and degraded_ts
            and min(victim_hop_ts) < degraded_ts < max(victim_hop_ts)
        )
        # The alert must name the right EDGE: reported by the victim (the
        # sender whose send-blocked time exploded), alerting its ring
        # successor (the endpoint whose inbound path degraded).
        src_ok = bool(
            degraded["alerts"]
            and str(degraded["alerts"][0].get("src_replica_id", ""))
            == victim_rid
        )
        # Added-wall attribution: per-bucket growth of the degraded run
        # over the healthy control (same round count) — the fault's cost
        # must land on the wire/shaping/stall side, not combine.
        added = {}
        for k in ("wire_s", "stall_s", "combine_s", "shaping_s"):
            added[k] = round(
                d["attribution"]["totals"][k] - h["attribution"]["totals"][k], 4
            )
        added_total = sum(added.values())
        added_wire_stall_fraction = (
            round(
                (added["wire_s"] + added["stall_s"] + added["shaping_s"])
                / added_total,
                4,
            )
            if added_total > 0
            else None
        )
        frac = d["attribution"]["fractions"]
        fraction_sum = round(
            sum(v for v in frac.values() if v is not None), 4
        )
        # Incident auto-capture verdict: the degraded cell's slow_link
        # trigger must have produced a bundle whose verdict names the
        # injected edge (victim group as the sender).
        from torchft_tpu.obs import incident as obs_incident

        incident_verdict = None
        incident_ok = False
        victim_group = victim_rid.split(":", 1)[0]
        degraded_events = (
            read_events([degraded["metrics_path"]])
            if degraded.get("incident_bundles")
            else []
        )
        for bundle in degraded.get("incident_bundles", []):
            try:
                manifest = obs_incident.finalize_bundle(
                    bundle, workdir, events=degraded_events,
                )
            except (OSError, ValueError):
                continue
            v = manifest.get("verdict", {})
            if v.get("kind") == "slow_link" and v.get("replica") == victim_group:
                incident_verdict = v
                incident_ok = True
        return {
            "section": "link",
            "quick": quick,
            "config": {
                "groups": groups, "steps": steps, "payload_kb": payload_kb,
                "mbps": mbps, "rtt_ms": rtt_ms, "degrade_at": degrade_at,
                "degrade_factor": degrade_factor,
            },
            "healthy": h,
            "degraded": d,
            "detected": detected,
            "detection_rounds": detection_rounds,
            "hop_timeline_records": len(victim_hop_ts),
            "hop_timeline_brackets_fault": hop_brackets_fault,
            "alert_src_is_victim": src_ok,
            "victim": victim_rid,
            "alert": (degraded["alerts"][0] if degraded["alerts"] else None),
            "added_wall": added,
            "added_wire_stall_fraction": added_wire_stall_fraction,
            "attribution_fraction_sum": fraction_sum,
            "incident_verdict": incident_verdict,
            "incident_ok": incident_ok,
            "overhead": overhead,
            "ok": bool(
                detected
                and h["link_alerts"] == 0
                and (detection_rounds is None or detection_rounds <= 10)
                and incident_ok
                and hop_brackets_fault
            ),
        }
    finally:
        if own_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


def run_quick() -> Dict[str, Any]:
    """Tier-1 smoke (``--quick``): small payloads, 1 vs 2 lanes at the
    collective level, pipelined vs monolithic commit counts end to end,
    plus the device-wire-prep A/B (bf16 wire so the cast has something to
    halve; sharded fetch engages when the process has multiple local
    devices, e.g. under the test suite's forced 8-device CPU platform).
    Wired into tests/test_bench_contract.py::test_allreduce_quick_smoke
    and ::test_device_prep_quick_smoke."""
    lanes_results = [
        bench_lanes(payload_mb=2.0, lanes=l, mbps=0.0, rtt_ms=0.0,
                    n_buckets=4, timeout=60.0, procs=False)
        for l in (1, 2)
    ]
    e2e_results = [
        bench_e2e(lanes=2, pipelined=p, steps=3, grads_mb=2.0, n_leaves=8,
                  mbps=0.0, rtt_ms=0.0, bucket_mb=0.5, timeout_s=60.0,
                  procs=False)
        for p in (True, False)
    ]
    prep_results = [
        bench_e2e(lanes=2, pipelined=True, steps=3, grads_mb=2.0, n_leaves=8,
                  mbps=0.0, rtt_ms=0.0, bucket_mb=0.5, timeout_s=60.0,
                  procs=False, device_prep=prep, sharded=shard,
                  wire_dtype="bf16")
        for prep, shard in ((False, False), (True, False), (True, True))
    ]
    pipe = next(r for r in e2e_results if r["mode"] == "pipelined")
    mono = next(r for r in e2e_results if r["mode"] == "monolithic")
    host_cast = prep_results[0]
    dev_prep = prep_results[1]
    dev_sharded = prep_results[2]
    return {
        "quick": True,
        "lanes": lanes_results,
        "e2e": e2e_results,
        "device_prep": prep_results,
        "pipelined_commits_ok": pipe["committed"] >= mono["committed"],
        "device_prep_commits_ok": (
            dev_prep["committed"] >= host_cast["committed"]
            and dev_sharded["committed"] >= host_cast["committed"]
        ),
        "d2h_reduction": (
            round(host_cast["d2h_bytes"] / dev_prep["d2h_bytes"], 3)
            if dev_prep["d2h_bytes"]
            else None
        ),
        "sharded_fetch_slices": dev_sharded["fetch_slices"],
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--worker", choices=["lanes", "e2e"], default=None,
        help="internal: run one rank/group body and write JSON to --cfg's 'out'",
    )
    parser.add_argument("--cfg", default=None, help="internal: worker JSON config")
    parser.add_argument("--mb", type=float, default=64.0, help="allreduce payload")
    parser.add_argument("--lanes", type=int, nargs="*", default=[1, 2, 4])
    parser.add_argument("--buckets", type=int, default=8)
    parser.add_argument(
        "--mbps", type=float, default=400.0,
        help="shaped per-peer link bandwidth (shared across lanes)",
    )
    parser.add_argument("--rtt-ms", type=float, default=20.0)
    parser.add_argument(
        "--trials", type=int, default=3,
        help="lane-sweep trials per lane count (best wall wins; scheduler "
        "noise on small shared hosts costs a single trial up to 30%%)",
    )
    parser.add_argument(
        "--engine", choices=["py", "native", "both"], default="both",
        help="ring hot-loop engine A/B: 'both' runs every lane cell under "
        "the Python engine AND the native GIL-free engine (plus an "
        "unshaped-loopback engine section and a live bitwise parity pin); "
        "'py'/'native' pin one side",
    )
    parser.add_argument(
        "--transport", choices=["tcp", "shm", "both"], default="both",
        help="ring-lane transport A/B: 'both' adds a tcp-vs-shm section "
        "(same-host SPSC shm ring vs the kernel socket path, bitwise "
        "parity pin, one-call multi-stripe pin, GIL-liberation thread "
        "sweep); 'tcp'/'shm' pin the transport for every cell",
    )
    parser.add_argument(
        "--topology", choices=["ring", "ring2d", "both"], default="both",
        help="cross-group topology A/B: 'both' adds a flat-vs-ring2d sweep "
        "at --topo-world ranks on the same shaped link (the per-topology "
        "records the artifact quotes); 'ring'/'ring2d' pin one side",
    )
    parser.add_argument(
        "--topo-world", type=int, default=4,
        help="rank count for the topology A/B (ring2d needs a non-prime "
        "world >= 4; the flat ring's 2(N-1) hop latency is what the 2D "
        "grid undercuts)",
    )
    parser.add_argument(
        "--topo-mb", type=float, default=8.0,
        help="payload for the topology A/B (latency-bound regime: small "
        "enough that per-hop RTT, not serialization, dominates)",
    )
    parser.add_argument("--e2e-steps", type=int, default=6)
    parser.add_argument("--e2e-mb", type=float, default=12.0)
    parser.add_argument("--e2e-leaves", type=int, default=16)
    parser.add_argument("--e2e-bucket-mb", type=float, default=3.0)
    parser.add_argument(
        "--e2e-lanes", type=int, default=2,
        help="ring lanes for the e2e section (coarser than the lane sweep: "
        "on small shared hosts many tiny lane frames lose their overlap to "
        "scheduler latency, so the pipelined-vs-monolithic A/B runs at the "
        "granularity a 2-core host can actually schedule)",
    )
    parser.add_argument(
        "--e2e-compute-iters", type=int, default=10,
        help="per-leaf jitted compute iterations (0 = pre-materialized grads)",
    )
    parser.add_argument(
        "--device-prep", choices=["on", "off", "both"], default="both",
        help="device-resident wire prep for the e2e section: 'both' runs "
        "the pipelined trial with the on-TPU bf16 cast AND the host-cast "
        "reference (the A/B the artifact quotes); 'on'/'off' pin one side",
    )
    parser.add_argument(
        "--sharded-devices", type=int, default=4,
        help="virtual devices per e2e worker for the sharded-fetch trial "
        "(0 disables the trial)",
    )
    parser.add_argument("--quick", action="store_true")
    parser.add_argument(
        "--link", action="store_true",
        help="run ONLY the slow-link sentinel cell (healthy control + "
        "mid-run 10x degraded edge + recorder-overhead guard) and merge "
        "its record into --out under the 'link' key",
    )
    parser.add_argument(
        "--link-quick", action="store_true",
        help="with --link: the small tier-1 configuration",
    )
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    if args.worker:
        cfg = json.loads(args.cfg)
        body = {"lanes": _lane_worker, "e2e": _e2e_worker}[args.worker]
        result = body(cfg)
        with open(cfg["out"], "w") as f:
            json.dump(result, f)
        return

    if args.quick:
        payload = run_quick()
        print(json.dumps(payload), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(payload, f, indent=1)
        return

    if args.link:
        link = run_link(quick=args.link_quick)
        print(json.dumps(link), flush=True)
        if args.out:
            # Merge into the existing artifact: the link cell is additive —
            # regenerating the full lane/e2e/topology sweeps to add one
            # sentinel cell would churn every other number.
            doc: Dict[str, Any] = {}
            if os.path.exists(args.out):
                with open(args.out) as f:
                    doc = json.load(f)
            doc["link"] = link
            with open(args.out, "w") as f:
                json.dump(doc, f, indent=1)
        return

    results: List[Dict[str, Any]] = []
    engines = ["py", "native"] if args.engine == "both" else [args.engine]
    # lane_gbps[engine][lanes]; the flat summary keys quote the engine the
    # deployment default (auto) runs — native when available.
    lane_gbps: Dict[str, Dict[int, float]] = {e: {} for e in engines}
    pinned_transport = None if args.transport == "both" else args.transport
    for l in args.lanes:
        for eng in engines:
            r = bench_lanes(args.mb, l, args.mbps, args.rtt_ms, args.buckets,
                            trials=args.trials, engine=eng,
                            transport=pinned_transport)
            # Key by the engine that actually RAN: a stale .so degrades a
            # requested native cell to py (one warning) and the record must
            # land under the truth, not crash the sweep.
            lane_gbps.setdefault(r["engine"], {})[l] = r["gb_per_s"]
            results.append(r)
            print(json.dumps(r), flush=True)

    # Engine loopback A/B: the same bucket stream UNSHAPED (mbps=0) — no
    # modeled link, so the wall is pure engine cost: GIL + per-stripe
    # copies for the Python engine, scatter-gather C++ for the native one.
    # This is the ceiling every shaped number saturates against.
    engine_loopback: Dict[str, float] = {}
    if args.engine == "both":
        for eng in engines:
            r = bench_lanes(args.mb, 4, 0.0, 0.0, args.buckets,
                            trials=args.trials, engine=eng)
            r["section"] = "engine_loopback"
            engine_loopback[r["engine"]] = r["gb_per_s"]
            results.append(r)
            print(json.dumps(r), flush=True)
        parity = check_engine_parity()
        results.append({"section": "engine_parity", "parity_bitwise": parity})
        print(json.dumps(results[-1]), flush=True)

    # Transport A/B: tcp vs same-host shm lanes on the unshaped loopback
    # (a shaped link would bury the syscall cost the shm path removes),
    # plus the bitwise parity pin, the one-call multi-stripe pin, and the
    # GIL-liberation thread sweep.
    transport_section: Optional[Dict[str, Any]] = None
    if args.transport == "both":
        transport_section = run_transport_quick(
            payload_mb=min(args.mb, 16.0), trials=args.trials
        )
        results.append(transport_section)
        print(json.dumps(transport_section), flush=True)
        r = bench_engine_threads(
            payload_mb=min(args.mb, 8.0), trials=max(1, args.trials - 1)
        )
        results.append(r)
        print(json.dumps(r), flush=True)

    # Topology A/B: the same bucket stream at --topo-world ranks, flat ring
    # vs 2D ring-of-rings, on the same shaped link.  Paired same-host
    # best-of-N trials; GB/s from the identical payload/wall arithmetic so
    # the records compare directly.
    topo_gbps: Dict[str, float] = {}
    topo_selection = (
        ["ring", "ring2d"] if args.topology == "both" else [args.topology]
    )
    for topo in topo_selection:
        r = bench_lanes(args.topo_mb, 2, args.mbps, args.rtt_ms,
                        n_buckets=max(2, args.buckets // 2),
                        trials=args.trials, world=args.topo_world,
                        topology=topo)
        r["section"] = "topology"
        r["requested_topology"] = topo
        if r["topology"] != topo:
            # ring2d degrades at primes / worlds < 4: the "A/B" would then
            # be two identical flat-ring trials silently keyed as one —
            # surface it instead of recording a speedup that never ran.
            import sys as _sys

            print(
                f"warning: requested topology {topo!r} resolved to "
                f"{r['topology']!r} at world {args.topo_world} (no 2D grid)"
                " — topology A/B skipped for this side",
                file=_sys.stderr, flush=True,
            )
        else:
            topo_gbps[topo] = r["gb_per_s"]
        results.append(r)
        print(json.dumps(r), flush=True)

    e2e: List[Dict[str, Any]] = []
    # The e2e matrix: monolithic reference, pipelined host-cast, pipelined
    # device-prep (same trial setup — only the wire-prep locus moves), and
    # a sharded-fetch trial on a multi-device worker platform.
    trial_modes: List[Dict[str, Any]] = [dict(pipelined=False)]
    if args.device_prep in ("off", "both"):
        trial_modes.append(dict(pipelined=True, device_prep=False))
    if args.device_prep in ("on", "both"):
        trial_modes.append(dict(pipelined=True, device_prep=True))
        if args.sharded_devices:
            trial_modes.append(
                dict(pipelined=True, device_prep=True, sharded=True,
                     virtual_devices=args.sharded_devices)
            )
    for mode_kw in trial_modes:
        r = bench_e2e(
            lanes=args.e2e_lanes, steps=args.e2e_steps,
            grads_mb=args.e2e_mb, n_leaves=args.e2e_leaves,
            mbps=args.mbps, rtt_ms=args.rtt_ms, bucket_mb=args.e2e_bucket_mb,
            compute_iters=args.e2e_compute_iters, trials=args.trials,
            **mode_kw,
        )
        e2e.append(r)
        results.append(r)
        print(json.dumps(r), flush=True)

    kill = bench_peer_kill(lanes=2)
    results.append(kill)
    print(json.dumps(kill), flush=True)

    def find(mode: str) -> Optional[Dict[str, Any]]:
        return next((r for r in e2e if r["mode"] == mode), None)

    pipe = find("pipelined")
    mono = find("monolithic")
    prep = find("pipelined+device_prep")
    sharded = find("pipelined+device_prep+sharded")
    # The flat lane keys quote what the deployment default (auto) runs:
    # the native engine when its cells exist, the Python engine otherwise.
    main_engine = (
        "native" if lane_gbps.get("native") else
        next(e for e in engines if lane_gbps.get(e))
    )
    main_lanes = lane_gbps[main_engine]
    summary: Dict[str, Any] = {
        "link": {"mbps": args.mbps, "rtt_ms": args.rtt_ms},
        "payload_mb": args.mb,
        "engine": main_engine,
        "lane_gb_per_s": {str(l): g for l, g in sorted(main_lanes.items())},
        "monolithic_steps_per_s": mono["steps_per_s"] if mono else None,
        "peer_kill_ok": kill["ok"],
    }
    if "py" in lane_gbps and main_engine != "py":
        # The Python-engine reference cells (comparable to the pre-native
        # artifacts) plus the shaped native-over-py ceiling ratio.
        summary["lane_gb_per_s_py"] = {
            str(l): g for l, g in sorted(lane_gbps["py"].items())
        }
        shared = [
            l for l in main_lanes
            if l in lane_gbps["py"] and lane_gbps["py"][l]
        ]
        if shared:
            top = max(shared)
            summary["shaped_native_over_py"] = round(
                main_lanes[top] / lane_gbps["py"][top], 3
            )
    if engine_loopback:
        summary["engine_loopback_gb_per_s"] = dict(sorted(engine_loopback.items()))
        if engine_loopback.get("py"):
            summary["native_loopback_speedup"] = round(
                engine_loopback.get("native", 0.0) / engine_loopback["py"], 2
            )
    if args.engine == "both":
        summary["engine_parity_bitwise"] = parity
    if transport_section is not None:
        summary["transport_parity_bitwise"] = transport_section["parity_bitwise"]
        if "shm_speedup" in transport_section:
            summary["shm_speedup"] = transport_section["shm_speedup"]
        ms = transport_section.get("multi_stripe")
        if ms is not None:
            summary["multi_stripe_one_call_per_op"] = ms["one_call_per_op"]
    if pipe:
        summary["pipelined_steps_per_s"] = pipe["steps_per_s"]
        if mono and mono["steps_per_s"]:
            summary["pipelined_speedup"] = round(
                pipe["steps_per_s"] / mono["steps_per_s"], 3
            )
    if prep:
        summary["device_prep_steps_per_s"] = prep["steps_per_s"]
        summary["device_prep_d2h_bytes"] = prep["d2h_bytes"]
        if pipe:
            summary["host_cast_d2h_bytes"] = pipe["d2h_bytes"]
            if prep["d2h_bytes"]:
                summary["d2h_reduction"] = round(
                    pipe["d2h_bytes"] / prep["d2h_bytes"], 3
                )
    if sharded:
        summary["sharded_steps_per_s"] = sharded["steps_per_s"]
        summary["sharded_fetch_slices"] = sharded["fetch_slices"]
        if sharded["slices_per_bucket"]:
            # Per-slice fetch granularity: on a multi-host group each host
            # pulls only its addressable slices, so per-host bytes shrink
            # by the shard factor; on this single-host bench the factor
            # shows up as the MEASURED slice count per bucket (not the
            # requested --sharded-devices, which an inherited XLA_FLAGS
            # can override in the workers).
            summary["shard_factor"] = sharded["slices_per_bucket"]
    if 1 in main_lanes and 4 in main_lanes:
        summary["speedup_4_lanes"] = round(main_lanes[4] / main_lanes[1], 2)
    if 1 in main_lanes and 2 in main_lanes:
        summary["speedup_2_lanes"] = round(main_lanes[2] / main_lanes[1], 2)
    if topo_gbps:
        summary["topology_gb_per_s"] = {
            t: g for t, g in sorted(topo_gbps.items())
        }
        summary["topology_world"] = args.topo_world
        if "ring" in topo_gbps and "ring2d" in topo_gbps and topo_gbps["ring"]:
            summary["ring2d_speedup"] = round(
                topo_gbps["ring2d"] / topo_gbps["ring"], 3
            )
    print(json.dumps({"summary": summary}), flush=True)
    if args.out:
        # The full sweep replaces results+summary but must not drop the
        # additive cells other invocations merge in (--link writes
        # doc["link"]); the artifact is one document with two writers.
        doc: Dict[str, Any] = {}
        if os.path.exists(args.out):
            try:
                with open(args.out) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                doc = {}
        doc["results"] = results
        doc["summary"] = summary
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)


if __name__ == "__main__":
    main()
