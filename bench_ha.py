"""HA lighthouse failover benchmark: SIGKILL the leader mid-run, measure
the takeover.

The scenario (``bench.py --scenario lighthouse-failover`` -> HA_BENCH.json):

- N lighthouse replica processes (``python -m torchft_tpu.lighthouse_cli
  --lease-file ...``) share a lease file; one wins the election and serves,
  the rest are warm standbys receiving continuous state replication;
- G replica-group worker processes run the REAL Manager control loop
  (quorum -> step -> two-phase commit vote) against the full
  comma-separated ``TPUFT_LIGHTHOUSE`` address list.  Workers are
  JAX-free: the scenario measures the CONTROL plane, so each "step" is a
  short sleep — hundreds of commits per window instead of a handful;
- mid-window the driver SIGKILLs the current leader (found via the lease
  file) and records: takeover latency (lease-file epoch bump + the
  ``lighthouse_failover`` event the winning standby writes into the obs
  stream), per-group commit-resume latency, failed commits on the healthy
  groups (must be ZERO — the managers' failover clients retry inside the
  quorum deadline instead of failing the step), and state continuity on
  the new leader (/metrics still shows every replica's step AND the
  straggler-sentinel step-time gauges that only exist if the health state
  was replicated, at an epoch exactly one higher).

Quick mode (``run_quick()``, wired into tier-1 as
``tests/test_bench_contract.py::test_ha_quick_smoke``): 2 lighthouses,
2 groups, one SIGKILL, ~15 s window.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# Worker: one replica group's Manager control loop (re-entered subprocess)
# ---------------------------------------------------------------------------


def _worker_main(cfg: Dict) -> None:
    """One replica group: real Manager + lighthouse quorum + commit votes,
    no JAX and no gradient traffic — a control-plane treadmill.  Prints a
    one-line JSON summary on exit; per-event truth rides in the shared
    metrics stream (TPUFT_METRICS_PATH)."""
    import numpy as np

    from torchft_tpu.checkpointing.http_transport import HTTPTransport
    from torchft_tpu.collectives import TCPCollective
    from torchft_tpu.manager import Manager
    from datetime import timedelta

    state = {"w": np.zeros(8, dtype=np.float32)}
    manager = Manager(
        collective=TCPCollective(timeout=20.0),
        load_state_dict=lambda sd: state.update(sd),
        state_dict=lambda: dict(state),
        min_replica_size=1,
        rank=0,
        world_size=1,
        replica_id=str(cfg["group"]),
        lighthouse_addr=cfg["lighthouse"],
        # The failover budget: a quorum call must be allowed to ride out a
        # full leader election (lease expiry + takeover) inside its own
        # deadline, or the step fails and the zero-failed-commits contract
        # breaks on a fault that lost no worker.
        quorum_timeout=timedelta(seconds=cfg.get("quorum_timeout_s", 20.0)),
        timeout=timedelta(seconds=20.0),
        connect_timeout=timedelta(seconds=10.0),
        checkpoint_transport=HTTPTransport(timeout=20.0),
        init_sync=False,
    )
    # ALL groups share one absolute end_ts (driver wall clock): a per-process
    # now+run_s deadline lets the earliest starter exit while a sibling still
    # counts steps, and a counted quorum with an absent sibling blocks on
    # the split-brain guard until timeout — a failed commit the CONTROL
    # plane never caused.
    end_ts = float(cfg["end_ts"])
    step_s = float(cfg.get("step_s", 0.05))
    groups = int(cfg["groups"])
    workdir = cfg["workdir"]
    commits = 0
    failed = 0
    try:
        while time.time() < end_ts:
            manager.start_quorum()
            time.sleep(step_s)  # the "train step"
            if manager.should_commit():
                commits += 1
            else:
                failed += 1
        # Linger: keep feeding the quorum machine (uncounted) until every
        # sibling has finished its counted window, so a sibling's LAST
        # counted quorum — started a tick before ours ended — still forms
        # instead of stalling against our missing join.
        with open(os.path.join(workdir, f"done_{cfg['group']}"), "w"):
            pass
        linger_deadline = time.time() + 20.0
        while time.time() < linger_deadline:
            if all(
                os.path.exists(os.path.join(workdir, f"done_{g}"))
                for g in range(groups)
            ):
                break
            manager.start_quorum()
            time.sleep(step_s)
            manager.should_commit()
    finally:
        summary = {"group": cfg["group"], "commits": commits, "failed": failed}
        print("HA_WORKER " + json.dumps(summary), flush=True)
        manager.shutdown()


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


def _spawn_lighthouse(
    bind_port: int,
    http_port: int,
    lease_path: str,
    peer_ports: List[int],
    lease_ms: int,
    log_path: str,
    metrics_path: str,
    min_replicas: int,
) -> subprocess.Popen:
    env = dict(os.environ)
    env["TPUFT_METRICS_PATH"] = metrics_path
    # The child inherits the fd via Popen; close the parent's handle right
    # away so repeated trials (and the tier-1 smoke inside pytest) do not
    # leak one fd per spawned process.
    with open(log_path, "ab") as log:
        return subprocess.Popen(
            [
                sys.executable, "-m", "torchft_tpu.lighthouse_cli",
                "--bind", f"127.0.0.1:{bind_port}",
                "--http_bind", f"127.0.0.1:{http_port}",
                # min_replicas = the full group count: the FIRST quorum
                # contains every group, so nobody sprints ahead solo and
                # forces the late joiner through a heal it cannot win a
                # split-brain vote for.
                "--min_replicas", str(min_replicas),
                "--join_timeout_ms", "2000",
                "--lease-file", lease_path,
                "--lease-ms", str(lease_ms),
                "--peers", ",".join(f"127.0.0.1:{p}" for p in peer_ports),
            ],
            env=env,
            stdout=log,
            stderr=subprocess.STDOUT,
            cwd=REPO,
        )


def _scrape(http_port: int, path: str, timeout: float = 2.0) -> Optional[str]:
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{http_port}{path}", timeout=timeout
        ) as resp:
            return resp.read().decode()
    except Exception:  # noqa: BLE001 — poller; absence is an answer
        return None


def _metric_value(text: str, name: str) -> Optional[float]:
    for line in text.splitlines():
        if line.startswith(name) and " " in line and "{" not in line:
            try:
                return float(line.rsplit(" ", 1)[1])
            except ValueError:
                return None
    return None


def _metric_replicas(text: str, name: str) -> List[str]:
    out = []
    for line in text.splitlines():
        if line.startswith(name + "{"):
            try:
                out.append(line.split('replica="', 1)[1].split('"', 1)[0])
            except IndexError:
                pass
    return out


def run_failover(
    workdir: str,
    lighthouses: int = 3,
    groups: int = 2,
    lease_ms: int = 1500,
    window_s: float = 30.0,
    quick: bool = False,
) -> Dict:
    """One failover trial.  Returns the HA_BENCH payload (see module
    docstring for the criteria each field backs)."""
    from torchft_tpu.ha.lease import FileLease
    from torchft_tpu.metrics import MetricsLogger
    from torchft_tpu.obs import report as obs_report

    os.makedirs(workdir, exist_ok=True)
    metrics_path = os.path.join(workdir, "metrics.jsonl")
    lease_path = os.path.join(workdir, "lease")
    lease_view = FileLease(lease_path, lease_ms, owner_id="bench-driver")
    fault_log = MetricsLogger(metrics_path, replica_id="bench-driver")

    ports = [_free_port() for _ in range(lighthouses)]
    http_ports = [_free_port() for _ in range(lighthouses)]
    procs: List[subprocess.Popen] = []
    workers: List[subprocess.Popen] = []
    lease_s = lease_ms / 1000.0
    result: Dict = {
        "metric": "lighthouse_failover",
        "quick": quick,
        "lighthouses": lighthouses,
        "groups": groups,
        "lease_ms": lease_ms,
        "window_s": window_s,
        "ok": False,
    }
    try:
        for i in range(lighthouses):
            peer_ports = [p for j, p in enumerate(ports) if j != i]
            procs.append(
                _spawn_lighthouse(
                    ports[i], http_ports[i], lease_path, peer_ports, lease_ms,
                    os.path.join(workdir, f"lighthouse_{i}.log"), metrics_path,
                    min_replicas=groups,
                )
            )
        # Wait for the initial election.
        t0 = time.time()
        rec = None
        while time.time() - t0 < 30.0:
            rec = lease_view.read()
            if rec is not None and not rec.expired(int(time.time() * 1000)):
                break
            time.sleep(0.05)
        assert rec is not None, "no lighthouse won the initial election"
        epoch_before = rec.epoch
        leader_idx = ports.index(int(rec.rpc_address.rsplit(":", 1)[1]))
        result["leader_epoch_before"] = epoch_before

        # Workers against the FULL address list (leader not first, so the
        # normal path already exercises rotation/redirect).
        addr_list = ",".join(f"127.0.0.1:{p}" for p in ports)
        worker_env = dict(os.environ)
        worker_env["TPUFT_METRICS_PATH"] = metrics_path
        end_ts = time.time() + window_s
        for g in range(groups):
            cfg = {
                "group": g,
                "groups": groups,
                "lighthouse": addr_list,
                "end_ts": end_ts,
                "workdir": workdir,
                "step_s": 0.05,
            }
            with open(os.path.join(workdir, f"g{g}.log"), "ab") as log:
                workers.append(
                    subprocess.Popen(
                        [sys.executable, os.path.abspath(__file__), "--worker",
                         json.dumps(cfg)],
                        env=worker_env,
                        stdout=log,
                        stderr=subprocess.STDOUT,
                        cwd=REPO,
                    )
                )

        # Hold the kill until every group has a commit timeline (and the
        # step-time EWMA had a chance to ride a heartbeat).
        def commits_per_group() -> Dict[str, List[float]]:
            return obs_report.commit_timelines(
                obs_report.read_events([metrics_path])
            )

        # The kill must land while the workers still have most of their
        # window left (post-kill commits are the resume evidence), so the
        # warm-up wait gives up at mid-window instead of outliving it.
        kill_by = end_ts - window_s * 0.5
        while time.time() < kill_by:
            cs = commits_per_group()
            if all(len(cs.get(str(g), [])) >= 5 for g in range(groups)):
                break
            time.sleep(0.25)

        # Pre-kill continuity baseline from the live leader.
        pre = _scrape(http_ports[leader_idx], "/metrics") or ""
        result["replicas_tracked_before"] = sorted(
            {r.split(":", 1)[0] for r in _metric_replicas(pre, "tpuft_replica_step")}
        )
        result["step_time_tracked_before"] = sorted(
            {r.split(":", 1)[0]
             for r in _metric_replicas(pre, "tpuft_replica_step_time_seconds")}
        )

        # THE FAULT: SIGKILL the active leader.
        kill_ts = time.time()
        fault_log.emit("fault", ts=kill_ts, kind="lighthouse", group="lighthouse",
                       plan="leader_sigkill")
        procs[leader_idx].kill()
        procs[leader_idx].wait()
        result["kill_ts"] = kill_ts

        # Takeover: lease epoch bump by a different owner.
        takeover_ts = None
        t0 = time.time()
        while time.time() - t0 < max(15.0, 6 * lease_s):
            rec2 = lease_view.read()
            if (
                rec2 is not None
                and rec2.epoch > epoch_before
                and not rec2.expired(int(time.time() * 1000))
            ):
                takeover_ts = time.time()
                result["leader_epoch_after"] = rec2.epoch
                new_leader_idx = ports.index(int(rec2.rpc_address.rsplit(":", 1)[1]))
                break
            time.sleep(0.05)
        result["takeover_s"] = (
            round(takeover_ts - kill_ts, 3) if takeover_ts is not None else None
        )
        assert takeover_ts is not None, "no standby took over the lease"

        # The lease record is written a settle-delay BEFORE the winner
        # confirms the race and flips its native role (and emits the
        # failover event) — wait for the role gauge so the continuity
        # scrape below cannot race the takeover it is trying to verify.
        poll_deadline = time.time() + 10.0
        while time.time() < poll_deadline:
            m = _scrape(http_ports[new_leader_idx], "/metrics")
            if m is not None and _metric_value(m, "tpuft_lighthouse_role") == 1.0:
                break
            time.sleep(0.05)

        # Let the workers run out their window, then collect summaries.
        for w in workers:
            w.wait(timeout=window_s + 60.0)
        summaries = []
        for g in range(groups):
            with open(os.path.join(workdir, f"g{g}.log"), "rb") as f:
                for line in f:
                    if line.startswith(b"HA_WORKER "):
                        summaries.append(json.loads(line[len(b"HA_WORKER "):]))
        result["worker_summaries"] = summaries

        # Post-failover continuity, evaluated against whoever leads NOW.
        # Re-resolve from the lease file at scrape time: on a heavily
        # loaded host a renewal stall can lapse the new leader's lease and
        # move leadership again (epoch 3+) — correct behavior (the
        # serve-time guard is doing its job and replication carries the
        # state onward), so the continuity contract follows the current
        # leader, and the split-brain check is "every OTHER instance reads
        # role 0 while the current leader reads 1", settled with a bounded
        # retry instead of one instantaneous snapshot (a single scrape
        # landing inside a renewal stall reads a conservative 0).
        post = ""
        cur_idx = new_leader_idx
        standby_roles: List[float] = []
        settle_deadline = time.time() + 15.0
        while time.time() < settle_deadline:
            cur = lease_view.read()
            if cur is not None and not cur.expired(int(time.time() * 1000)):
                try:
                    cur_idx = ports.index(int(cur.rpc_address.rsplit(":", 1)[1]))
                except ValueError:
                    pass
                result["leader_epoch_final"] = cur.epoch
            m = _scrape(http_ports[cur_idx], "/metrics")
            if m is None or _metric_value(m, "tpuft_lighthouse_role") != 1.0:
                time.sleep(0.2)
                continue
            roles = []
            for i in range(lighthouses):
                if i in (leader_idx, cur_idx):
                    continue
                s = _scrape(http_ports[i], "/metrics")
                if s is not None:
                    with open(
                        os.path.join(workdir, f"scrape_standby_{i}.metrics"), "w"
                    ) as f:
                        f.write(s)
                    roles.append(_metric_value(s, "tpuft_lighthouse_role"))
            if any(r == 1.0 for r in roles):
                # Leadership is mid-move (the "standby" just took the
                # lease); re-resolve and re-check rather than reading a
                # handoff as a split brain.
                time.sleep(0.2)
                continue
            post = m
            standby_roles = roles
            break
        with open(os.path.join(workdir, "scrape_new_leader.metrics"), "w") as f:
            f.write(post)
        result["role_new_leader"] = _metric_value(post, "tpuft_lighthouse_role")
        result["epoch_gauge_new_leader"] = _metric_value(
            post, "tpuft_lighthouse_leader_epoch"
        )
        result["replicas_tracked_after"] = sorted(
            {r.split(":", 1)[0] for r in _metric_replicas(post, "tpuft_replica_step")}
        )
        result["step_time_tracked_after"] = sorted(
            {r.split(":", 1)[0]
             for r in _metric_replicas(post, "tpuft_replica_step_time_seconds")}
        )
        result["standby_roles_after"] = standby_roles

        # Commit accounting from the stream.
        events = obs_report.read_events([metrics_path])
        commits = obs_report.commit_timelines(events)
        failed_after: Dict[str, int] = {}
        for ev in events:
            if ev.get("event") == "commit" and not ev.get("committed"):
                # Scope to the COUNTED window [kill, end_ts]: after end_ts
                # the workers are in the uncounted linger phase, where the
                # last group standing legitimately fails a quorum once its
                # siblings exit (min_replicas = all groups) — harness
                # teardown, not a control-plane failure.
                if kill_ts <= float(ev.get("ts", 0.0)) <= end_ts:
                    g = str(ev.get("replica_id", "")).split(":", 1)[0]
                    failed_after[g] = failed_after.get(g, 0) + 1
        result["failed_commits_after_kill"] = failed_after
        result["failed_commits_healthy_groups"] = sum(failed_after.values())

        resume_gaps: Dict[str, float] = {}
        medians: Dict[str, float] = {}
        for g in range(groups):
            ts_list = sorted(commits.get(str(g), []))
            pre_kill = [t for t in ts_list if t <= kill_ts]
            post_kill = [t for t in ts_list if t > kill_ts]
            iv = [b - a for a, b in zip(pre_kill, pre_kill[1:])]
            med = sorted(iv)[len(iv) // 2] if iv else 0.0
            medians[str(g)] = round(med, 4)
            if post_kill:
                resume_gaps[str(g)] = round(min(post_kill) - kill_ts, 3)
        result["per_group_commits"] = {
            g: len(ts) for g, ts in sorted(commits.items())
        }
        result["median_step_s"] = medians
        result["resume_gap_s"] = resume_gaps
        # The headline criterion: quorum formation (evidenced by the next
        # committed step, which REQUIRES a formed quorum) resumed within
        # one lease period of the kill — plus one median step (the step
        # itself is not failover cost) and a small scheduling slack for
        # this shared 2-core host.
        max_gap = max(resume_gaps.values()) if resume_gaps else None
        slack = 0.5 + 2 * max(medians.values() or [0.0])
        result["max_resume_gap_s"] = max_gap
        result["resume_budget_s"] = round(lease_s + slack, 3)
        result["resumed_within_lease"] = (
            max_gap is not None and max_gap <= lease_s + slack
        )

        # The failover must be visible in the obs stream (the standby's
        # takeover event), and the report must charge it as quorum-ish
        # time, not a worker fault.
        failover_events = [
            ev for ev in events if ev.get("event") == "lighthouse_failover"
        ]
        result["failover_event_seen"] = bool(failover_events)
        result["failover_event_epoch"] = (
            failover_events[0].get("leader_epoch") if failover_events else None
        )
        attribution = obs_report.attribute(events)
        result["election_s_attributed"] = attribution["totals"].get("election_s")
        result["lighthouse_elections_in_report"] = attribution["goodput"].get(
            "lighthouse_elections"
        )
        result["victims_recovered_in_report"] = attribution["goodput"].get(
            "victims_recovered"
        )

        # The epoch gauge must match the CURRENT lease epoch (>= the
        # takeover epoch: under load leadership may have moved again, and
        # continuity must hold across every hop, not just the first).
        final_epoch = result.get("leader_epoch_final", result["leader_epoch_after"])
        result["metrics_continuity_ok"] = (
            result["role_new_leader"] == 1.0
            and result["epoch_gauge_new_leader"] == float(final_epoch)
            and final_epoch >= result["leader_epoch_after"]
            and result["replicas_tracked_after"] == result["replicas_tracked_before"]
            and result["step_time_tracked_after"] == result["step_time_tracked_before"]
            and len(result["replicas_tracked_after"]) == groups
        )
        result["ok"] = bool(
            result["resumed_within_lease"]
            and result["failed_commits_healthy_groups"] == 0
            and result["metrics_continuity_ok"]
            and result["failover_event_seen"]
            and all(r == 0.0 for r in standby_roles)
            and all(s["commits"] > 0 and s["failed"] == 0 for s in summaries)
        )
        return result
    finally:
        fault_log.close()
        for w in workers:
            if w.poll() is None:
                w.kill()
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def run_quick() -> Dict:
    """Tier-1 smoke shape: 2 lighthouses, 2 groups, one leader SIGKILL,
    short window.  Workdir is kept under a tempdir for post-mortem."""
    workdir = tempfile.mkdtemp(prefix="tpuft_ha_quick_")
    return run_failover(
        workdir, lighthouses=2, groups=2, lease_ms=1200, window_s=18.0, quick=True
    )


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--worker", default=None, help=argparse.SUPPRESS)
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--lighthouses", type=int, default=3)
    parser.add_argument("--groups", type=int, default=2)
    parser.add_argument("--lease-ms", type=int, default=1500)
    parser.add_argument("--window-s", type=float, default=30.0)
    parser.add_argument("--out", default=os.path.join(REPO, "HA_BENCH.json"))
    args = parser.parse_args()
    if args.worker is not None:
        _worker_main(json.loads(args.worker))
        return
    if args.quick:
        payload = run_quick()
    else:
        workdir = os.environ.get("TPUFT_BENCH_WORKDIR") or tempfile.mkdtemp(
            prefix="tpuft_bench_ha_"
        )
        payload = run_failover(
            workdir,
            lighthouses=args.lighthouses,
            groups=args.groups,
            lease_ms=args.lease_ms,
            window_s=args.window_s,
        )
        payload["workdir"] = workdir
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
