"""Fault-tolerant data-parallel training example.

Reference parity: train_ddp.py at the reference root — one process is one
replica group; gradients are averaged across groups through the Manager's
fault-tolerant allreduce; a killed process is restarted by the launcher's
supervisor (torchft_tpu/launch.py), heals live weights from a peer, and
rejoins without stopping the others.

Run (two supervised replica groups + embedded Lighthouse, one command)::

    python -m torchft_tpu.launch --groups 2 -- \
        python examples/train_ddp.py --steps 20

or by hand against an external Lighthouse::

    python -m torchft_tpu.lighthouse_cli --bind [::]:29510 --min_replicas 1 &
    TPUFT_LIGHTHOUSE=localhost:29510 REPLICA_GROUP_ID=0 NUM_REPLICA_GROUPS=2 \
        python examples/train_ddp.py --steps 20 &
    TPUFT_LIGHTHOUSE=localhost:29510 REPLICA_GROUP_ID=1 NUM_REPLICA_GROUPS=2 \
        python examples/train_ddp.py --steps 20

The model is a small conv net on synthetic CIFAR-shaped data (the reference
uses CIFAR-10; synthetic keeps the example hermetic).  At exit each process
prints a params checksum — after any number of mid-run kills, all groups
print the same checksum.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _common import (
    TrainGate,
    make_manager,
    maybe_straggle,
    params_digest,
    pin_platform_and_cache,
    replica_env,
)


def main() -> None:
    # INFO so the manager's lifecycle lines ("healing from replica ...",
    # reconfigures) land in the log — the FT demo's evidence trail.
    logging.basicConfig(level=logging.INFO)
    # SIGUSR1 dumps all thread stacks: `kill -USR1 <pid>` is the first move
    # when a replica looks wedged.
    import faulthandler
    import signal as _signal

    faulthandler.register(_signal.SIGUSR1)
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--batch", type=int, default=32)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--min_replicas", type=int, default=1)
    parser.add_argument(
        "--ckpt_dir",
        default=os.environ.get("TPUFT_CKPT_DIR", ""),
        help="durable checkpoint directory; empty disables disk checkpoints",
    )
    parser.add_argument("--ckpt_every", type=int, default=10)
    parser.add_argument(
        "--require-merged-final", type=int, default=0,
        help="keep stepping past --steps until a committed step ran with "
        "at least this many participating groups (deterministic merged "
        "finish for the kill/heal tests)",
    )
    parser.add_argument(
        "--steps-cap", type=int, default=0,
        help="hard step bound when --require-merged-final can never be met",
    )
    args = parser.parse_args()

    pin_platform_and_cache()

    import jax
    import numpy as np
    import optax

    from torchft_tpu import GradientAverager, Optimizer
    from torchft_tpu.data import DistributedSampler

    # -- model: tiny convnet on 32x32x3 inputs (CIFAR shaped) ----------------
    # Everything here is GROUP-INDEPENDENT, so it runs before the group id
    # resolves: a hot spare (launch --spares) pays params init + the JIT
    # compile while idling, and adoption costs only Manager setup + rejoin.
    from torchft_tpu.models import convnet_loss, init_convnet_params

    init_params = init_convnet_params
    grad_fn = jax.jit(jax.value_and_grad(convnet_loss))
    params0 = init_params(jax.random.PRNGKey(42))

    # Synthetic dataset, identical in every process (seeded).
    rng = np.random.default_rng(0)
    dataset_x = rng.standard_normal((2048, 32, 32, 3)).astype(np.float32)
    dataset_y = rng.integers(0, 10, size=(2048,)).astype(np.int32)
    # Warm the compiled step (from the shared cache when available).
    jax.block_until_ready(
        grad_fn(params0, dataset_x[: args.batch], dataset_y[: args.batch])[0]
    )

    replica_group, num_groups = replica_env()

    # -- manager wiring ------------------------------------------------------
    state = {}

    def save():
        return {"params": state["opt"].params, "opt_state": state["opt"].opt_state}

    def load(sd):
        state["opt"].params = sd["params"]
        state["opt"].opt_state = sd["opt_state"]

    manager = make_manager(
        save, load, replica_group, min_replicas=args.min_replicas
    )

    state["opt"] = Optimizer(manager, optax.sgd(args.lr), params0)
    averager = GradientAverager(manager)

    # Durable disk checkpoints: peer transports heal a restarted group from
    # a live one, but a cold start (every group gone) would otherwise begin
    # at step 0.
    ckpt = None
    if args.ckpt_dir:
        from torchft_tpu.checkpointing import ManagedDiskCheckpoint

        ckpt = ManagedDiskCheckpoint(
            manager, save, load,
            os.path.join(args.ckpt_dir, f"group_{replica_group}"),
            every=args.ckpt_every,
        )
        ckpt_step = ckpt.restore()
        if ckpt_step is not None:
            print(
                f"[group {replica_group}] resumed from disk checkpoint "
                f"step={ckpt_step}",
                flush=True,
            )

    gate = TrainGate(
        manager, args.steps,
        require_merged=args.require_merged_final, steps_cap=args.steps_cap,
    )
    try:
        while gate.should_continue():
            state["opt"].step_begin()
            step = manager.current_step()

            # Shard by the *static* replica group id (reference train_ddp.py
            # does the same): dynamic quorum state would shift every group's
            # shard on each membership change, and a healing group
            # (participating_rank None) would alias group 0's shard.
            sampler = DistributedSampler(
                len(dataset_x),
                replica_group=replica_group,
                num_replica_groups=num_groups,
                shuffle=True,
                seed=step,
            )
            idx = [i for _, i in zip(range(args.batch), iter(sampler))]
            x, y = dataset_x[idx], dataset_y[idx]

            loss, grads = grad_fn(state["opt"].params, x, y)
            # Straggler-bench injection point (no-op outside the scenario):
            # extra per-step sleep here models slow compute on this host.
            maybe_straggle(replica_group)
            grads = averager.allreduce(grads)
            committed = state["opt"].step(grads)
            gate.note_commit(committed)
            if ckpt is not None:
                ckpt.maybe_save(committed)
            print(
                f"[group {replica_group}] step={step} loss={float(loss):.4f} "
                f"participants={manager.num_participants()} committed={committed}",
                flush=True,
            )

        if not gate.finish(replica_group):
            print(f"[group {replica_group}] FINAL step={manager.current_step()} "
                  f"params_sha256={params_digest(state['opt'].params)}", flush=True)
    finally:
        if ckpt is not None:
            ckpt.shutdown()
        manager.shutdown()


if __name__ == "__main__":
    main()
