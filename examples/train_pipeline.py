"""Fault-tolerant pipeline-parallel training: GPipe inside the group,
replicate across groups, heal pipeline-sharded state live.

The composition the reference describes for FSDP/TP ("fault tolerance
across the replicated dimension with any mix of ... across the other
dimensions", reference README) — demonstrated here for PIPELINE
parallelism, which the reference does not have at all (SURVEY.md §2.3).
Each process is one replica group whose transformer layer stack is sharded
across a pipeline mesh axis (stage-to-stage ppermute hops inside the jit
step, parallel/pipeline.py); groups average gradients through the
Manager's fault-tolerant allreduce; a killed group restarts and heals its
PIPELINE-SHARDED state in place (NamedShardings restored onto its own
mesh) from a healthy peer.

Run (two supervised groups; each simulates a pipeline x data slice on
CPU — pin TPUFT_JAX_PLATFORM=cpu when a TPU is attached, it cannot be
shared by two processes)::

    TPUFT_JAX_PLATFORM=cpu python -m torchft_tpu.launch --groups 2 \
        --max-restarts 3 -- python examples/train_pipeline.py --steps 200
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _common import (
    TrainGate,
    make_manager,
    params_digest,
    pin_platform_and_cache,
    replica_env,
)


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--microbatches", type=int, default=2)
    parser.add_argument("--lr", type=float, default=3e-3)
    parser.add_argument(
        "--schedule", choices=["gpipe", "1f1b"], default="gpipe",
        help="gpipe: forward pipeline + autodiff reverse; 1f1b: loss and "
        "backward inside the pipeline, activation memory bounded by the "
        "pipe depth",
    )
    parser.add_argument(
        "--pipe", type=int, default=2, help="pipeline stages per group"
    )
    parser.add_argument(
        "--devices", type=int, default=4,
        help="virtual devices forming this group's (pipeline x data) mesh",
    )
    parser.add_argument(
        "--require-merged-final", type=int, default=0,
        help="keep stepping past --steps until a committed step ran with "
        "at least this many participating groups (deterministic merged "
        "finish for the kill/heal tests)",
    )
    parser.add_argument(
        "--steps-cap", type=int, default=0,
        help="hard step bound when --require-merged-final can never be met",
    )
    args = parser.parse_args()

    n_layers = 4
    if args.devices % args.pipe:
        parser.error(f"--devices {args.devices} not divisible by --pipe {args.pipe}")
    data = args.devices // args.pipe
    if n_layers % args.pipe:
        parser.error(f"{n_layers} layers not divisible over --pipe {args.pipe}")
    if args.batch % data or (args.batch // data) % args.microbatches:
        parser.error(
            f"--batch {args.batch} must divide over data axis {data} and "
            f"then into --microbatches {args.microbatches}"
        )

    pin_platform_and_cache(virtual_devices=args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchft_tpu import GradientAverager, Optimizer
    from torchft_tpu.checkpointing.serialization import sharding_restorer
    from torchft_tpu.data import DistributedSampler
    from torchft_tpu.models import TransformerConfig, init_params
    from torchft_tpu.models.transformer import param_axes
    from torchft_tpu.parallel import TrainStep, ft_init_mesh
    from torchft_tpu.parallel.pipeline import (
        pipeline_1f1b_value_and_grad,
        pipeline_loss_fn,
    )

    replica_group, num_groups = replica_env()

    cfg = TransformerConfig(
        vocab_size=512,
        d_model=128,
        n_layers=n_layers,
        n_heads=4,
        n_kv_heads=4,
        d_ff=256,
        max_seq=64,
        dtype=jnp.float32,  # exact cross-group convergence for the demo
        remat=False,
    )
    seq = 64

    ftmesh = ft_init_mesh({"pipeline": args.pipe, "data": data})
    schedule_kwargs = (
        {
            "value_and_grad_fn": lambda p, b: pipeline_1f1b_value_and_grad(
                p, b, cfg, ftmesh.mesh, num_microbatches=args.microbatches
            )
        }
        if args.schedule == "1f1b"
        else {
            "loss_fn": lambda p, b: pipeline_loss_fn(
                p, b, cfg, ftmesh.mesh, num_microbatches=args.microbatches
            )
        }
    )
    step_fn = TrainStep(ftmesh, optax.sgd(args.lr), **schedule_kwargs)

    # Synthetic token stream, identical in every process (seeded).
    rng = np.random.default_rng(0)
    dataset = rng.integers(0, cfg.vocab_size, size=(4096, seq)).astype(np.int32)

    state = {}

    def save():
        return {"params": state["opt"].params, "opt_state": state["opt"].opt_state}

    def load(sd):
        # The transport restored NamedShardings onto THIS group's mesh —
        # the layer stack lands back sharded over the pipeline axis.
        state["opt"].params = sd["params"]
        state["opt"].opt_state = sd["opt_state"]

    manager = make_manager(
        save, load, replica_group, restore_sharding=sharding_restorer(save)
    )
    ftmesh.manager = manager

    params = ftmesh.shard_params(init_params(jax.random.PRNGKey(7), cfg), param_axes(cfg))
    state["opt"] = Optimizer(manager, optax.sgd(args.lr), params)
    averager = GradientAverager(manager)

    sampler = DistributedSampler(
        len(dataset),
        replica_group=replica_group,
        num_replica_groups=num_groups,
        shuffle=True,
    )

    gate = TrainGate(
        manager, args.steps,
        require_merged=args.require_merged_final, steps_cap=args.steps_cap,
    )
    try:
        while gate.should_continue():
            state["opt"].step_begin()
            step = manager.current_step()
            sampler.set_epoch(step)
            idx = [i for _, i in zip(range(args.batch), iter(sampler))]
            tokens = jnp.asarray(dataset[idx])
            batch = {
                "tokens": jax.device_put(tokens, ftmesh.sharding("batch", "seq")),
                "targets": jax.device_put(
                    jnp.roll(tokens, -1, axis=1), ftmesh.sharding("batch", "seq")
                ),
            }
            loss, grads = step_fn.grads(state["opt"].params, batch)
            grads = averager.allreduce(grads)
            committed = state["opt"].step(grads)
            gate.note_commit(committed)
            print(
                f"[group {replica_group}] step={step} loss={float(loss):.4f} "
                f"participants={manager.num_participants()} committed={committed}",
                flush=True,
            )

        if not gate.finish(replica_group):
            layer_spec = str(
                jax.tree_util.tree_leaves(
                    state["opt"].params["layers"]
                )[0].sharding.spec
            )
            print(
                f"[group {replica_group}] FINAL step={manager.current_step()} "
                f"params_sha256={params_digest(state['opt'].params)} "
                f"layer_sharding={layer_spec}",
                flush=True,
            )
    finally:
        manager.shutdown()


if __name__ == "__main__":
    main()
