"""Fault-tolerant LONG-CONTEXT training: ring attention inside the group,
replicate across groups, heal sequence-sharded state live.

Each process is one replica group whose activations are sharded along the
sequence axis of its own (data x sequence) mesh; attention runs as a
K/V-rotation ring over that axis (ops/ring_attention.py — ppermute hops,
online log-sum-exp merges), optionally in the work-balanced zigzag layout.
Groups average gradients through the Manager's fault-tolerant allreduce; a
killed group restarts and heals in place from a healthy peer.  The
reference has neither sequence parallelism nor this composition
(SURVEY.md §2.3); the FT mechanics mirror its DDP recovery story
(torchft/manager_integ_test.py:281).

Run (two supervised groups; pin TPUFT_JAX_PLATFORM=cpu when a TPU is
attached — one chip cannot be shared by two processes)::

    TPUFT_JAX_PLATFORM=cpu python -m torchft_tpu.launch --groups 2 \
        --max-restarts 3 -- python examples/train_ring.py --steps 200 \
        --layout zigzag
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _common import (
    TrainGate,
    make_manager,
    params_digest,
    pin_platform_and_cache,
    replica_env,
)


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--lr", type=float, default=3e-3)
    parser.add_argument(
        "--layout", choices=["contiguous", "zigzag"], default="contiguous",
        help="sequence layout for the causal ring (zigzag balances work)",
    )
    parser.add_argument(
        "--sequence", type=int, default=4,
        help="ring size: sequence-axis shards per group",
    )
    parser.add_argument(
        "--devices", type=int, default=4,
        help="virtual devices forming this group's (data x sequence) mesh",
    )
    parser.add_argument(
        "--require-merged-final", type=int, default=0,
        help="keep stepping past --steps until a committed step ran with "
        "at least this many participating groups (deterministic merged "
        "finish for the kill/heal tests)",
    )
    parser.add_argument(
        "--steps-cap", type=int, default=0,
        help="hard step bound when --require-merged-final can never be met",
    )
    args = parser.parse_args()

    if args.devices % args.sequence:
        parser.error(
            f"--devices {args.devices} not divisible by --sequence {args.sequence}"
        )

    pin_platform_and_cache(virtual_devices=args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchft_tpu import GradientAverager, Optimizer
    from torchft_tpu.checkpointing.serialization import sharding_restorer
    from torchft_tpu.data import DistributedSampler
    from torchft_tpu.models import TransformerConfig, init_params, loss_fn
    from torchft_tpu.models.transformer import param_axes
    from torchft_tpu.ops.ring_attention import to_zigzag
    from torchft_tpu.parallel import TrainStep, ft_init_mesh

    replica_group, num_groups = replica_env()

    seq = 64
    cfg = TransformerConfig(
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=4,
        d_ff=256,
        max_seq=seq,
        dtype=jnp.float32,  # exact cross-group convergence for the demo
        attention="ring",
        ring_layout=args.layout,
    )

    data = args.devices // args.sequence
    ftmesh = ft_init_mesh({"data": data, "sequence": args.sequence})
    step_fn = TrainStep(
        ftmesh, optax.sgd(args.lr),
        lambda p, b: loss_fn(p, b, cfg, ftmesh.mesh, ftmesh.rules),
    )

    rng = np.random.default_rng(0)
    dataset = rng.integers(0, cfg.vocab_size, size=(4096, seq)).astype(np.int32)

    state = {}

    def save():
        return {"params": state["opt"].params, "opt_state": state["opt"].opt_state}

    def load(sd):
        state["opt"].params = sd["params"]
        state["opt"].opt_state = sd["opt_state"]

    manager = make_manager(
        save, load, replica_group, restore_sharding=sharding_restorer(save)
    )
    ftmesh.manager = manager

    params = ftmesh.shard_params(
        init_params(jax.random.PRNGKey(7), cfg), param_axes(cfg)
    )
    state["opt"] = Optimizer(manager, optax.sgd(args.lr), params)
    averager = GradientAverager(manager)

    sampler = DistributedSampler(
        len(dataset),
        replica_group=replica_group,
        num_replica_groups=num_groups,
        shuffle=True,
    )

    gate = TrainGate(
        manager, args.steps,
        require_merged=args.require_merged_final, steps_cap=args.steps_cap,
    )
    try:
        while gate.should_continue():
            state["opt"].step_begin()
            step = manager.current_step()
            sampler.set_epoch(step)
            idx = [i for _, i in zip(range(args.batch), iter(sampler))]
            tokens = jnp.asarray(dataset[idx])
            targets = jnp.roll(tokens, -1, axis=1)
            if args.layout == "zigzag":
                # One host-side permutation pair; rope positions follow
                # inside the model (TransformerConfig.ring_layout).
                tokens = to_zigzag(tokens, args.sequence, axis=1)
                targets = to_zigzag(targets, args.sequence, axis=1)
            batch = {
                "tokens": jax.device_put(tokens, ftmesh.sharding("batch", "seq")),
                "targets": jax.device_put(targets, ftmesh.sharding("batch", "seq")),
            }
            loss, grads = step_fn.grads(state["opt"].params, batch)
            grads = averager.allreduce(grads)
            committed = state["opt"].step(grads)
            gate.note_commit(committed)
            print(
                f"[group {replica_group}] step={step} loss={float(loss):.4f} "
                f"participants={manager.num_participants()} committed={committed}",
                flush=True,
            )

        if not gate.finish(replica_group):
            sample = jax.tree_util.tree_leaves_with_path(
                state["opt"].params["layers"]
            )[0]
            print(
                f"[group {replica_group}] FINAL step={manager.current_step()} "
                f"params_sha256={params_digest(state['opt'].params)} "
                f"ring_layout={args.layout} "
                f"sample_sharding={sample[1].sharding.spec}",
                flush=True,
            )
    finally:
        manager.shutdown()


if __name__ == "__main__":
    main()
