"""Fault-tolerant HSDP training example: shard inside the group, replicate
across groups, heal sharded state live.

Reference parity: the reference's HSDP story is torch FSDP2 over a
ManagedDeviceMesh (torchft/device_mesh.py:290-323, torchft/fsdp_test.py) —
fault tolerance across the replicated dimension with FSDP/TP inside each
replica group.  Here each process is one replica group whose transformer
params are sharded over the group's own (fsdp x tensor) device mesh; groups
average gradients through the Manager's fault-tolerant allreduce; a killed
group restarts, heals its SHARDED state in place (NamedShardings restored on
its own mesh) from a healthy peer, and rejoins.

Run (two supervised groups; each simulates a 4-device slice on CPU)::

    python -m torchft_tpu.launch --groups 2 --max-restarts 3 -- \
        python examples/train_hsdp.py --steps 200

On real hardware drop the virtual-device flag: the group mesh is the TPU
slice's ICI devices and the cross-group dimension rides DCN unchanged.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _common import (
    TrainGate,
    make_manager,
    params_digest,
    pin_platform_and_cache,
    replica_env,
)


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--lr", type=float, default=3e-3)
    parser.add_argument(
        "--devices", type=int, default=4,
        help="virtual devices forming this group's (fsdp x tensor) mesh",
    )
    parser.add_argument(
        "--ckpt_dir",
        default=os.environ.get("TPUFT_CKPT_DIR", ""),
        help="durable checkpoint directory; empty disables disk checkpoints",
    )
    parser.add_argument("--ckpt_every", type=int, default=20)
    parser.add_argument(
        "--require-merged-final", type=int, default=0,
        help="keep stepping past --steps until a committed step ran with "
        "at least this many participating groups (deterministic merged "
        "finish for the kill/heal tests)",
    )
    parser.add_argument(
        "--steps-cap", type=int, default=0,
        help="hard step bound when --require-merged-final can never be met",
    )
    args = parser.parse_args()

    pin_platform_and_cache(virtual_devices=args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchft_tpu import GradientAverager, Optimizer
    from torchft_tpu.checkpointing.serialization import sharding_restorer
    from torchft_tpu.data import DistributedSampler
    from torchft_tpu.models import TransformerConfig, init_params, loss_fn
    from torchft_tpu.models.transformer import param_axes
    from torchft_tpu.parallel import TrainStep, ft_init_mesh

    replica_group, num_groups = replica_env()

    cfg = TransformerConfig(
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=4,
        d_ff=256,
        max_seq=64,
        dtype=jnp.float32,  # exact cross-group convergence for the demo
    )
    seq = 64

    fsdp = max(1, args.devices // 2)
    tensor = max(1, args.devices // fsdp)
    ftmesh = ft_init_mesh({"fsdp": fsdp, "tensor": tensor})
    step_fn = TrainStep(
        ftmesh, optax.sgd(args.lr),
        lambda p, b: loss_fn(p, b, cfg, ftmesh.mesh, ftmesh.rules),
    )

    # Synthetic token stream, identical in every process (seeded).
    rng = np.random.default_rng(0)
    dataset = rng.integers(0, cfg.vocab_size, size=(4096, seq)).astype(np.int32)

    state = {}

    def save():
        return {"params": state["opt"].params, "opt_state": state["opt"].opt_state}

    def load(sd):
        # The transport restored NamedShardings onto THIS group's mesh
        # (in-place sharded receive); adopt the healed trees as-is.
        state["opt"].params = sd["params"]
        state["opt"].opt_state = sd["opt_state"]

    manager = make_manager(
        save, load, replica_group, restore_sharding=sharding_restorer(save)
    )
    ftmesh.manager = manager

    params = ftmesh.shard_params(init_params(jax.random.PRNGKey(7), cfg), param_axes(cfg))
    state["opt"] = Optimizer(manager, optax.sgd(args.lr), params)
    averager = GradientAverager(manager)

    # Durable SHARDED checkpoints: the disk format records NamedShardings,
    # and restore places every leaf back onto this group's own
    # (fsdp x tensor) mesh via the live tree's shardings — cold-start
    # resume for a whole HSDP job, where peer healing has no live peer.
    ckpt = None
    if args.ckpt_dir:
        from torchft_tpu.checkpointing import ManagedDiskCheckpoint

        ckpt = ManagedDiskCheckpoint(
            manager, save, load,
            os.path.join(args.ckpt_dir, f"group_{replica_group}"),
            every=args.ckpt_every,
        )
        ckpt_step = ckpt.restore()
        if ckpt_step is not None:
            print(
                f"[group {replica_group}] resumed from disk checkpoint "
                f"step={ckpt_step}",
                flush=True,
            )

    sampler = DistributedSampler(
        len(dataset),
        replica_group=replica_group,
        num_replica_groups=num_groups,
        shuffle=True,
    )

    gate = TrainGate(
        manager, args.steps,
        require_merged=args.require_merged_final, steps_cap=args.steps_cap,
    )
    try:
        while gate.should_continue():
            state["opt"].step_begin()
            step = manager.current_step()
            # One sampler, re-seeded per step: a restarted group resumes the
            # same shard permutation at the healed step.
            sampler.set_epoch(step)
            idx = [i for _, i in zip(range(args.batch), iter(sampler))]
            tokens = jnp.asarray(dataset[idx])
            batch = {
                "tokens": jax.device_put(tokens, ftmesh.sharding("batch", "seq")),
                "targets": jax.device_put(
                    jnp.roll(tokens, -1, axis=1), ftmesh.sharding("batch", "seq")
                ),
            }
            loss, grads = step_fn.grads(state["opt"].params, batch)
            grads = averager.allreduce(grads)
            committed = state["opt"].step(grads)
            gate.note_commit(committed)
            if ckpt is not None:
                ckpt.maybe_save(committed)
            print(
                f"[group {replica_group}] step={step} loss={float(loss):.4f} "
                f"participants={manager.num_participants()} committed={committed}",
                flush=True,
            )

        if not gate.finish(replica_group):
            shardings = {
                path[-1].key if hasattr(path[-1], "key") else str(path[-1]):
                    str(leaf.sharding.spec)
                for path, leaf in jax.tree_util.tree_leaves_with_path(
                    state["opt"].params["layers"]
                )[:2]
            }
            print(
                f"[group {replica_group}] FINAL step={manager.current_step()} "
                f"params_sha256={params_digest(state['opt'].params)} "
                f"sample_shardings={shardings}",
                flush=True,
            )
    finally:
        if ckpt is not None:
            ckpt.shutdown()
        manager.shutdown()


if __name__ == "__main__":
    main()
