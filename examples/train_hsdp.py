"""Fault-tolerant HSDP training example: shard inside the group, replicate
across groups, heal sharded state live.

Reference parity: the reference's HSDP story is torch FSDP2 over a
ManagedDeviceMesh (torchft/device_mesh.py:290-323, torchft/fsdp_test.py) —
fault tolerance across the replicated dimension with FSDP/TP inside each
replica group.  Here each process is one replica group whose transformer
params are sharded over the group's own (fsdp x tensor) device mesh; groups
average gradients through the Manager's fault-tolerant allreduce; a killed
group restarts, heals its SHARDED state in place (NamedShardings restored on
its own mesh) from a healthy peer, and rejoins.

Run (two supervised groups; each simulates a 4-device slice on CPU)::

    python -m torchft_tpu.launch --groups 2 --max-restarts 3 -- \
        python examples/train_hsdp.py --steps 200

On real hardware drop the virtual-device flag: the group mesh is the TPU
slice's ICI devices and the cross-group dimension rides DCN unchanged.
"""

from __future__ import annotations

import argparse
import hashlib
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--lr", type=float, default=3e-3)
    parser.add_argument(
        "--devices", type=int, default=4,
        help="virtual devices forming this group's (fsdp x tensor) mesh",
    )
    parser.add_argument(
        "--ckpt_dir",
        default=os.environ.get("TPUFT_CKPT_DIR", ""),
        help="durable checkpoint directory; empty disables disk checkpoints",
    )
    parser.add_argument("--ckpt_every", type=int, default=20)
    args = parser.parse_args()

    # Each process simulates one multi-device slice (demo only): the flag
    # must land before backend init.
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()

    import jax

    forced = os.environ.get("TPUFT_JAX_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)
    cache_dir = os.environ.get("TPUFT_COMPILE_CACHE")
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    import jax.numpy as jnp
    import numpy as np
    import optax
    from datetime import timedelta

    from torchft_tpu import GradientAverager, Manager, Optimizer, TCPCollective
    from torchft_tpu.checkpointing.http_transport import HTTPTransport
    from torchft_tpu.checkpointing.serialization import sharding_restorer
    from torchft_tpu.data import DistributedSampler
    from torchft_tpu.models import TransformerConfig, init_params, loss_fn
    from torchft_tpu.models.transformer import param_axes
    from torchft_tpu.parallel import TrainStep, ft_init_mesh

    replica_group = int(os.environ.get("REPLICA_GROUP_ID", 0))
    num_groups = int(os.environ.get("NUM_REPLICA_GROUPS", 2))

    cfg = TransformerConfig(
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=4,
        d_ff=256,
        max_seq=64,
        dtype=jnp.float32,  # exact cross-group convergence for the demo
    )
    seq = 64

    fsdp = max(1, args.devices // 2)
    tensor = max(1, args.devices // fsdp)
    ftmesh = ft_init_mesh({"fsdp": fsdp, "tensor": tensor})
    step_fn = TrainStep(
        ftmesh, optax.sgd(args.lr),
        lambda p, b: loss_fn(p, b, cfg, ftmesh.mesh, ftmesh.rules),
    )

    # Synthetic token stream, identical in every process (seeded).
    rng = np.random.default_rng(0)
    dataset = rng.integers(0, cfg.vocab_size, size=(4096, seq)).astype(np.int32)

    state = {}

    def save():
        return {"params": state["opt"].params, "opt_state": state["opt"].opt_state}

    def load(sd):
        # The transport restored NamedShardings onto THIS group's mesh
        # (in-place sharded receive); adopt the healed trees as-is.
        state["opt"].params = sd["params"]
        state["opt"].opt_state = sd["opt_state"]

    manager = Manager(
        collective=TCPCollective(timeout=30.0),
        load_state_dict=load,
        state_dict=save,
        min_replica_size=1,
        timeout=timedelta(seconds=30),
        rank=0,
        world_size=1,
        replica_id=str(replica_group),
        checkpoint_transport=HTTPTransport(
            timeout=30.0, restore_sharding=sharding_restorer(save)
        ),
    )
    ftmesh.manager = manager

    params = ftmesh.shard_params(init_params(jax.random.PRNGKey(7), cfg), param_axes(cfg))
    state["opt"] = Optimizer(manager, optax.sgd(args.lr), params)
    averager = GradientAverager(manager)

    # Durable SHARDED checkpoints: the disk format records NamedShardings,
    # and restore places every leaf back onto this group's own
    # (fsdp x tensor) mesh via the live tree's shardings — cold-start
    # resume for a whole HSDP job, where peer healing has no live peer.
    ckpt = None
    if args.ckpt_dir:
        from torchft_tpu.checkpointing import ManagedDiskCheckpoint

        ckpt = ManagedDiskCheckpoint(
            manager, save, load,
            os.path.join(args.ckpt_dir, f"group_{replica_group}"),
            every=args.ckpt_every,
        )
        ckpt_step = ckpt.restore()
        if ckpt_step is not None:
            print(
                f"[group {replica_group}] resumed from disk checkpoint "
                f"step={ckpt_step}",
                flush=True,
            )

    sampler = DistributedSampler(
        len(dataset),
        replica_group=replica_group,
        num_replica_groups=num_groups,
        shuffle=True,
    )

    try:
        while manager.current_step() < args.steps:
            state["opt"].step_begin()
            step = manager.current_step()
            # One sampler, re-seeded per step: a restarted group resumes the
            # same shard permutation at the healed step.
            sampler.set_epoch(step)
            idx = [i for _, i in zip(range(args.batch), iter(sampler))]
            tokens = jnp.asarray(dataset[idx])
            batch = {
                "tokens": jax.device_put(tokens, ftmesh.sharding("batch", "seq")),
                "targets": jax.device_put(
                    jnp.roll(tokens, -1, axis=1), ftmesh.sharding("batch", "seq")
                ),
            }
            loss, grads = step_fn.grads(state["opt"].params, batch)
            grads = averager.allreduce(grads)
            committed = state["opt"].step(grads)
            if ckpt is not None:
                ckpt.maybe_save(committed)
            print(
                f"[group {replica_group}] step={step} loss={float(loss):.4f} "
                f"participants={manager.num_participants()} committed={committed}",
                flush=True,
            )

        digest = hashlib.sha256()
        leaves = sorted(
            jax.tree_util.tree_leaves_with_path(state["opt"].params),
            key=lambda kv: jax.tree_util.keystr(kv[0]),
        )
        for _, leaf in leaves:
            digest.update(np.asarray(leaf).tobytes())
        shardings = {
            path[-1].key if hasattr(path[-1], "key") else str(path[-1]): str(leaf.sharding.spec)
            for path, leaf in jax.tree_util.tree_leaves_with_path(
                state["opt"].params["layers"]
            )[:2]
        }
        print(
            f"[group {replica_group}] FINAL step={manager.current_step()} "
            f"params_sha256={digest.hexdigest()} sample_shardings={shardings}",
            flush=True,
        )
    finally:
        if ckpt is not None:
            ckpt.shutdown()
        manager.shutdown()


if __name__ == "__main__":
    main()
