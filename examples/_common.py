"""Shared mechanics for the example trainers.

Only the non-instructive plumbing lives here (platform pinning, compile
cache, Manager wiring, the FINAL digest); each example keeps its own train
loop inline so it still reads as a tutorial for its parallelism style.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Any, Callable, Optional


def pin_platform_and_cache(virtual_devices: Optional[int] = None) -> None:
    """Applies the environment contract every example shares, BEFORE the
    first touch of the JAX backend:

    - ``virtual_devices``: simulate one multi-device slice per process
      (demo only; real hardware drops this).
    - ``TPUFT_JAX_PLATFORM``: explicit platform pin — env JAX_PLATFORMS
      alone can be overridden by site hooks after launch, and multi-process
      drives must not share a single TPU chip.
    - ``TPUFT_COMPILE_CACHE``: persistent compile cache so a restarted
      replica re-JITs from disk, shrinking the recovery window.
    """
    if virtual_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={virtual_devices}"
            ).strip()

    import jax

    forced = os.environ.get("TPUFT_JAX_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)
    cache_dir = os.environ.get("TPUFT_COMPILE_CACHE")
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def replica_env() -> tuple:
    """(replica_group, num_replica_groups) from the launcher's env.

    Hot-spare mode: when the supervisor started this process as a SPARE
    (``TPUFT_SPARE_FILE`` set, no ``REPLICA_GROUP_ID``), finish the
    expensive initialization NOW — force the JAX backend up — and block
    until the supervisor assigns a replica group by writing the go-file.
    Adoption then skips the process-spawn + runtime-init floor that
    dominates a cold restart's dead window (measured ~7 s of the ~7.5 s
    downtime on the kill bench)."""
    gid = os.environ.get("REPLICA_GROUP_ID")
    spare = os.environ.get("TPUFT_SPARE_FILE")
    if gid is None and spare:
        import jax

        jax.devices()  # backend init happens while idling, not after a death
        print(f"[spare] ready (backend up), waiting at {spare}", flush=True)
        while not os.path.exists(spare):
            time.sleep(0.05)
        with open(spare) as f:
            gid = f.read().strip()
        os.environ["REPLICA_GROUP_ID"] = gid
        print(f"[spare] adopted replica group {gid}", flush=True)
    return (
        int(gid or 0),
        int(os.environ.get("NUM_REPLICA_GROUPS", 2)),
    )


def maybe_straggle(replica_group: int) -> float:
    """Fault injection for the straggler bench scenario: when the driver
    wrote ``<TPUFT_STRAGGLE_DIR>/straggle_<group>.json`` this step sleeps
    ``sleep_s`` extra, simulating a degraded-but-alive host (the failure
    mode no heartbeat timeout ever catches).  The notice is PID-pinned: a
    replacement incarnation adopting the same group id models a healthy
    spare host and must not inherit the slowness.  Returns the seconds
    slept (0 = no injection)."""
    d = os.environ.get("TPUFT_STRAGGLE_DIR")
    if not d:
        return 0.0
    import json

    path = os.path.join(d, f"straggle_{replica_group}.json")
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return 0.0
    pid = data.get("pid")
    # A notice MUST name a pid: a pid-less file matching every incarnation
    # would pin the slowness to each replacement forever, turning one slow
    # host into an unrecoverable slow group.
    if pid is None or int(pid) != os.getpid():
        return 0.0
    sleep_s = float(data.get("sleep_s", 0.0))
    if sleep_s > 0.0:
        time.sleep(sleep_s)
    return sleep_s


def make_manager(
    save: Callable[[], Any],
    load: Callable[[Any], None],
    replica_group: int,
    *,
    min_replicas: int = 1,
    timeout_s: float = 30.0,
    restore_sharding: Any = None,
) -> Any:
    """One-replica-group Manager with the examples' standard wiring:
    TCPCollective data plane + HTTP checkpoint transport (optionally with a
    sharding restorer for sharded-state healing), plus the cooperative-drain
    watcher (SIGTERM / supervisor notice file / opt-in GCE metadata poll) so
    a planned departure hands off instead of dying."""
    from datetime import timedelta

    from torchft_tpu import Manager, TCPCollective
    from torchft_tpu.checkpointing.http_transport import HTTPTransport

    manager = Manager(
        collective=TCPCollective(timeout=timeout_s),
        load_state_dict=load,
        state_dict=save,
        min_replica_size=min_replicas,
        timeout=timedelta(seconds=timeout_s),
        rank=0,
        world_size=1,
        replica_id=str(replica_group),
        checkpoint_transport=HTTPTransport(
            timeout=timeout_s, restore_sharding=restore_sharding
        ),
    )
    manager.attach_drain_watcher()
    return manager


class TrainGate:
    """Decides when an example train loop is done.

    Three exits, in priority order:

    - **drain** — a cooperative-departure notice arrived (the Manager's
      DrainWatcher fired): finish the in-flight step and leave NOW; the
      supervisor already pre-warmed a replacement.
    - **merged final** (``require_merged`` > 0) — don't stop at the step
      budget until a committed step at-or-after it ran with at least that
      many participating groups.  This replaces the fixed-step-budget race
      in the kill tests with a deterministic criterion: a survivor keeps
      stepping (solo) until the healed replacement merges back, so both
      groups provably finish the same merged step with identical state.
    - **step budget** — plain ``current_step() >= steps`` otherwise, with
      ``steps_cap`` as a runaway bound when the merged criterion can never
      be met (e.g. the peer is gone for good).
    """

    def __init__(
        self, manager: Any, steps: int, *, require_merged: int = 0, steps_cap: int = 0
    ) -> None:
        self._manager = manager
        self._steps = steps
        self._require_merged = require_merged
        self._steps_cap = steps_cap
        self._last_merged = 0

    def should_continue(self) -> bool:
        if self._manager.drain_requested():
            return False
        step = self._manager.current_step()
        if self._steps_cap and step >= self._steps_cap:
            return False
        if step < self._steps:
            return True
        return self._require_merged > 0 and self._last_merged < self._require_merged

    def note_commit(self, committed: bool) -> None:
        """Record the last commit's participation (call once per step)."""
        self._last_merged = self._manager.num_participants() if committed else 0

    def drained(self) -> bool:
        return self._manager.drain_requested()

    def finish(self, replica_group: int) -> bool:
        """Drain epilogue: completes a requested drain and prints the exit
        marker.  Returns True when this was a drain exit (the caller skips
        its FINAL print — the departing params are donor state, not the
        run's converged result)."""
        if not self.drained():
            return False
        self._manager.complete_drain()
        print(
            f"[group {replica_group}] DRAIN exit step="
            f"{self._manager.current_step()}",
            flush=True,
        )
        return True


def params_digest(params: Any) -> str:
    """Order-stable sha256 over every parameter leaf — the cross-group
    convergence evidence each example prints at FINAL."""
    import jax
    import numpy as np

    digest = hashlib.sha256()
    leaves = sorted(
        jax.tree_util.tree_leaves_with_path(params),
        key=lambda kv: jax.tree_util.keystr(kv[0]),
    )
    for _, leaf in leaves:
        digest.update(np.asarray(leaf).tobytes())
    return digest.hexdigest()
